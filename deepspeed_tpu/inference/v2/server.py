"""Serving front end over ``InferenceEngineV2`` — the MII surface.

The reference ships FastGen behind DeepSpeed-MII (``mii.serve`` spawns a
persistent server whose scheduler drives ``engine_v2.put()`` continuously;
reference ``inference/v2/engine_v2.py:107`` is the documented integration
point for exactly this loop). This module is that missing deployment layer,
TPU-native and stdlib-only:

- :class:`ServingScheduler` — a background thread running TRUE continuous
  batching with Dynamic SplitFuse scheduling (the FastGen algorithm):
  requests arrive and retire asynchronously; every tick is one ragged
  forward of at most ``token_budget`` tokens where decoding sequences are
  guaranteed their token first and prefills chunk into the remainder
  (a drafted tick adds a separate windowed put — speculative decoding
  rides the same loop, and in steady state eligible speculative rows run
  their draft/verify/accept entirely on device inside the fused K-window
  scan). Per-request sampling controls, logprobs, token streaming. Admission reserves full decode headroom (prompt +
  max_new_tokens blocks) exactly like ``InferenceEngineV2.generate`` so
  a tick cannot run the allocator dry; if it still does (best-effort
  admission), the newest sequence is evicted and replayed.
- :class:`RequestHandle` — caller's side of one request: ``stream()``
  yields token ids as they land, ``result()`` /
  ``result_with_logprobs()`` block for the full output, ``cancel()``
  retires the sequence at the next scheduler tick.
- :func:`create_http_server` / ``bin/ds_serve`` — a ThreadingHTTPServer
  exposing ``POST /generate`` (optionally chunk-streamed), the OpenAI
  ``/v1/completions`` and ``/v1/chat/completions`` shapes, and
  ``GET /health`` (queue depths + TTFT/decode-rate aggregates).
  Token-id native; pass a HF tokenizer name to accept ``{"text": ...}``
  bodies, string stops, and chat messages.

Single-threaded device access: ONLY the scheduler thread touches the
engine. ``submit``/``cancel`` just enqueue under a lock and set an event,
so arbitrarily many HTTP threads are safe.

Resilience (``serving_resilience`` config block, see config_v2.py):
per-request deadlines/TTL expire with a typed :class:`DeadlineExceeded`
(HTTP 504) and release their KV; bounded queues shed at ``submit()``
with :class:`SchedulerOverloaded` (HTTP 429 + Retry-After); a per-tick
fault boundary retries transient engine errors and bisects a
reproducible fault down to the one poisoning request (error-finishing
only it — the loop survives); a watchdog flips ``/health`` to
``degraded`` when ticks stall. All deterministic-testable through the
``serve.*`` sites of ``utils/fault_injection.py``.

Durability (``durable_serving`` config block + ``inference/v2/journal.py``):
with the write-ahead request journal enabled, every admitted request is
persisted (prompt, sampling params, seed, deadline) and its emitted-token
high-water mark + PRNG key-burn count follow per tick. A daemon crash (or
SIGTERM ``handoff()``) therefore loses nothing: the next ``start()`` scans
the journal, re-admits unfinished requests with their original uids and
remaining deadlines, force-feeds the already-emitted tokens as prefix, and
fast-forwards each key chain by its burn count — resumed greedy AND sampled
streams continue byte-identically to an uninterrupted run. Clients
re-attach by request id: ``GET /requests/<uid>`` blocks for the result,
``GET /requests/<uid>/stream?from_token=N`` resumes a token stream at the
client's own high-water mark (offset-addressed, so nothing double-emits).
"""

import itertools
import json
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from ...observability import ProfilerBusy, ServingInstruments
from ...utils.fault_injection import InjectedFault, get_fault_injector
from ...utils.logging import logger
from ...utils.retry import RetriesExhausted, retry_with_backoff
from .config_v2 import (ContinuousFusionConfig, DurableServingConfig,
                        ObservabilityConfig, ServingResilienceConfig,
                        TenantConfig)
from .adapters import AdapterSlotsExhausted
from .disagg import DisaggServing
from .journal import RequestJournal, ServingCrash
from .engine_v2 import InferenceEngineV2, SampleSpec
from .ragged.sequence_descriptor import PlaceholderSequenceDescriptor
from .scheduling_utils import (DeadlineExceeded, SchedulerOverloaded,
                               SchedulingError, SchedulingResult,
                               UnsupportedFeature, error_reason)

_END = object()  # stream sentinel


@dataclass
class _Request:
    uid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: int = 0
    stop: list = field(default_factory=list)  # normalized token-id seqs
    min_new_tokens: int = 0
    repetition_penalty: float = 1.0
    logits_processor: Optional[object] = None
    speculative: Optional[str] = None
    num_draft_tokens: int = 4
    draft_ngram: int = 2
    return_logprobs: bool = False
    # multi-tenant scheduling: which tenant contract (config ``tenants``
    # block) this request admits/sheds/budgets under
    tenant: str = "default"
    # multi-LoRA: the client-facing adapter name (None = base weights) and
    # the RESOLVED versioned id (``name@version``) the stream decodes with —
    # the journal records the resolved id so replay/migration re-pin the
    # exact factors, never "whatever version is latest over there"
    adapter: Optional[str] = None
    adapter_id: Optional[str] = None
    logprobs: list = field(default_factory=list)
    # speculative accept-rate accounting (drafted tokens offered / accepted)
    drafted: int = 0
    accepted: int = 0
    # host prompt-lookup fallback: cached last-match position so the
    # bounded backward scan usually starts where it last succeeded
    match_cache: dict = field(default_factory=dict)
    # scheduler state
    outputs: List[int] = field(default_factory=list)
    fed: int = 0                   # tokens of prompt+outputs already in KV
    stream_q: "queue.Queue" = field(default_factory=queue.Queue)
    done: "threading.Event" = field(default_factory=threading.Event)
    cancelled: bool = False
    error: Optional[BaseException] = None
    rng: Optional[np.random.Generator] = None
    # durability state: counted device-PRNG key burns (one per sampled
    # per-token dispatch / fused scan step / verified speculative window),
    # the journal high-water marks, and the replay/skip flags
    key_burns: int = 0
    journaled_n: int = 0       # outputs already on journal record
    journaled_burns: int = 0   # key_burns already on journal record
    journal_skip: bool = False  # host logits_processor: not serializable
    replayed: bool = False
    stream: bool = False       # submitted as a stream() consumer
    # resilience state
    t_deadline: Optional[float] = None        # monotonic; queue + decode
    t_queue_deadline: Optional[float] = None  # monotonic; unadmitted only
    wake: Optional[threading.Event] = None    # cancel() nudges the loop
    queued: bool = False  # counted in the shed-policy accounting
    # metrics timeline (time.monotonic)
    t_submit: float = 0.0
    t_first: float = 0.0
    t_last: float = 0.0   # last emitted token (inter-token gap anchor)
    t_done: float = 0.0

    @property
    def feed(self) -> List[int]:
        """Everything that must be in the KV cache: prompt, plus generated
        tokens (relevant after an eviction replay resets ``fed``)."""
        return self.prompt + self.outputs

    @property
    def pending(self) -> int:
        """Tokens of ``feed`` not yet in the KV cache. 1 ⇔ a pure decode
        step (the last sampled token); >1 ⇔ (re)prefilling."""
        return len(self.prompt) + len(self.outputs) - self.fed

    def feed_slice(self, take: int) -> List[int]:
        """Next ``take`` unfed tokens, without concatenating the history."""
        start, lp = self.fed, len(self.prompt)
        if start >= lp:
            return self.outputs[start - lp:start - lp + take]
        head = self.prompt[start:start + take]
        if len(head) < take:
            head = head + self.outputs[:take - len(head)]
        return head


class RequestHandle:
    """Caller's view of one in-flight generation."""

    def __init__(self, req: _Request):
        self._req = req

    @property
    def uid(self) -> int:
        return self._req.uid

    def stream(self, timeout: Optional[float] = None):
        """Yield token ids as the scheduler produces them."""
        while True:
            tok = self._req.stream_q.get(timeout=timeout)
            if tok is _END:
                if self._req.error is not None:
                    raise self._req.error
                return
            yield tok

    def stream_from(self, from_token: int = 0,
                    timeout: Optional[float] = None, poll: float = 0.02):
        """Offset-addressed stream for (re)connecting consumers: yields
        ``outputs[from_token:]`` — already-generated tokens immediately,
        then live ones as they land. Unlike ``stream()`` it never touches
        the delivery queue, so any number of consumers can attach at their
        own high-water marks (e.g. after an HTTP reconnect or a daemon
        warm restart) without double-emitting or stealing tokens."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        i = max(0, int(from_token))
        while True:
            n = len(self._req.outputs)  # append-only: snapshot is safe
            while i < n:
                yield int(self._req.outputs[i])
                i += 1
            if self._req.done.is_set():
                if len(self._req.outputs) > i:
                    continue  # tokens landed between the scan and done
                if self._req.error is not None:
                    raise self._req.error
                return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"request {self._req.uid} still running")
            self._req.done.wait(poll)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until generation finishes; returns all generated tokens."""
        if not self._req.done.wait(timeout):
            raise TimeoutError(f"request {self._req.uid} still running")
        if self._req.error is not None:
            raise self._req.error
        return list(self._req.outputs)

    def result_with_logprobs(self, timeout: Optional[float] = None):
        """(tokens, per-token logprobs) — requires submit(...,
        return_logprobs=True)."""
        toks = self.result(timeout)
        return toks, list(self._req.logprobs[:len(toks)])

    @property
    def stats(self) -> dict:
        """Per-request accounting. For speculative requests this carries
        the accept-rate counters (``drafted`` tokens offered for
        verification, ``accepted`` of them kept), available live and after
        ``result()``."""
        r = self._req
        out = {"tokens": len(r.outputs)}
        if r.speculative is not None:
            out["drafted"] = r.drafted
            out["accepted"] = r.accepted
            out["accept_rate"] = (round(r.accepted / r.drafted, 4)
                                  if r.drafted else None)
        return out

    def cancel(self) -> None:
        self._req.cancelled = True
        if self._req.wake is not None:
            # wake an idle loop NOW: the sweep frees this request's KV
            # before the next admission pass instead of after idle_wait
            self._req.wake.set()

    @property
    def finished(self) -> bool:
        return self._req.done.is_set()


class ServingScheduler:
    """Continuous-batching serving loop over one ``InferenceEngineV2``.

    Scheduling is Dynamic SplitFuse (the reference's FastGen algorithm,
    ``blogs/deepspeed-fastgen``): every tick runs ONE ragged forward of at
    most ``token_budget`` tokens — each decoding sequence is guaranteed its
    1 token first (the decode-latency SLA), then prefilling sequences fill
    the remainder in chunks. Long prompts therefore spread across ticks
    instead of stalling live decodes behind one huge forward, and short
    prompts pack into the same forward as the decodes.
    """

    def __init__(self, engine: InferenceEngineV2, idle_wait: float = 0.05,
                 token_budget: Optional[int] = None,
                 fused_decode_window: Optional[int] = None,
                 journal: Optional[RequestJournal] = None,
                 instruments: "Union[ServingInstruments, bool, None]" = None,
                 disagg: Optional[DisaggServing] = None,
                 uid_base: Optional[int] = None):
        self._engine = engine
        self._idle_wait = idle_wait
        # disaggregated prefill/decode (disagg.py): ``engine`` is the
        # DECODE group's; pending>1 requests route to the prefill group
        # and their KV pages migrate back through the handoff queue.
        # None (the default / single-group fallback) leaves every code
        # path byte-identical to the time-overlap scheduler.
        self._disagg = disagg
        self._on_prefill: set = set()  # uids resident on the prefill group
        self._disagg_fed_tick = False  # one prefill-group put per tick
        if fused_decode_window is None:
            from ...ops.registry import on_tpu
            fused_decode_window = 16 if on_tpu() else 1
        # steady-state fast path: when nothing waits to prefill, the
        # plain-greedy subset of live decodes runs K fused steps per
        # dispatch (engine.fused_decode_steps — the CUDA-graph-replay
        # analog) while sampled/controlled requests keep their per-token
        # SplitFuse tick in the same scheduler pass
        self._fused_window = int(fused_decode_window)
        scfg = getattr(engine._config, "sampling", None)
        # on-device sampling: eligible requests (no host logits_processor)
        # sample in one batched device dispatch per tick, and — with
        # fused_sampled_decode — ride the fused K-step program next to the
        # greedy ones, so the fused partition is by FEASIBILITY
        # (prefilled, pending==1, >= 2 tokens of room), not by greediness
        self._device_sampling = bool(scfg and scfg.device_sampling)
        self._fused_sampled = bool(self._device_sampling
                                   and scfg.fused_sampled_decode)
        # fused speculative: eligible speculative rows (no host callbacks,
        # device-matchable ngram) run draft+verify+accept inside the K-step
        # scan — one dispatch + one fetch per window instead of one host
        # round-trip per token. Gate-off keeps the per-token host path (the
        # parity oracle) for everything.
        self._fused_spec = bool(scfg and scfg.fused_speculative_decode)
        self._spec_max_ngram = int(scfg.spec_max_ngram) if scfg else 8
        # continuous fusion: dispatch the fused wave (async), feed prefill
        # chunks + admit arrivals WHILE it runs on device, harvest after —
        # the K-step amortization survives sustained traffic instead of
        # being an idle-system-only mode. Gate-off restores the exclusive
        # modes exactly.
        ccfg = getattr(engine._config, "continuous_fusion", None)
        self._cf: ContinuousFusionConfig = (
            ccfg if ccfg is not None else ContinuousFusionConfig())
        # uids of wave members whose fused program is in flight: the
        # eviction and retirement paths must not flush them (the device is
        # still writing their KV); empty outside the overlap window
        self._in_flight: frozenset = frozenset()
        # EWMA of measured seconds per fused decode step — the adaptive-K
        # deadline bound's cost model (0 until the first wave completes)
        self._step_ewma = 0.0
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._inbox: List[_Request] = []
        self._waiting: List[_Request] = []
        self._live: List[_Request] = []
        # fleet uid namespacing: the router exports DS_SERVE_UID_BASE so
        # every replica generation mints uids from a disjoint stride —
        # migrated requests keep their original uids on any peer without
        # ever colliding with the peer's own mints
        self._uid_base = uid_base if uid_base is not None else int(
            os.environ.get("DS_SERVE_UID_BASE", "0") or 0)
        self._uid_iter = itertools.count(self._uid_base + 1)
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._draining = False
        # submit()..._finish() span, maintained under _lock: queue-membership
        # checks can race the loop's unlocked transfers, this count cannot
        self._active = 0
        rcfg = getattr(engine._config, "serving_resilience", None)
        self._res: ServingResilienceConfig = (
            rcfg if rcfg is not None else ServingResilienceConfig())
        # shed-policy accounting: unadmitted requests / their prompt tokens,
        # maintained under _lock so submit() can refuse without touching the
        # scheduler thread's queues
        self._queued_n = 0
        self._queued_tokens = 0
        # multi-tenant weighted-fair scheduling: per-tenant contracts from
        # the config ``tenants`` block (unknown tenants fall back to the
        # "default" entry, else weight-1/no-caps), plus the per-tenant
        # accounting admission and shedding run on. _tenant_queued mutates
        # under _lock with the global queue counters; _tenant_delivered is
        # scheduler-thread-only (stats snapshots it under _lock).
        self._tenants = dict(getattr(engine._config, "tenants", None) or {})
        self._tenant_fallback = self._tenants.get("default") or TenantConfig()
        self._tenant_queued: dict = {}
        self._tenant_delivered: dict = {}
        self._degraded = False
        # live-migration state: export_journal() flips _migrating so
        # /health answers "migrating" (distinct from a plain drain — the
        # router and ds_top can tell a handoff-in-progress from a
        # shutdown) and records how many entries left in the export
        self._migrating = False
        self._journal_export_depth = 0
        self._imported = 0
        self._last_progress = time.monotonic()
        self._watchdog: Optional[threading.Thread] = None
        # resilience event counters (mutations: scheduler thread, except
        # "shed" which submit() bumps under _lock; stats/trace snapshot
        # under the same lock)
        self._trace = {"shed": 0, "expired_queue": 0, "expired_live": 0,
                       "tick_errors": 0, "quarantined": [],
                       "watchdog_trips": 0, "slow_consumer_cancels": 0,
                       "spec_drafted": 0, "spec_accepted": 0,
                       # continuous-fusion observability: decode tokens
                       # from fused dispatches vs all decode tokens (the
                       # occupancy ratio), dispatch/window-size tallies,
                       # and prefill tokens fed inside overlap windows
                       "fused_tokens": 0, "decode_tokens": 0,
                       "fused_dispatches": 0, "fused_k_sum": 0,
                       "prefill_overlap_tokens": 0}
        # durability: the write-ahead request journal (explicit instance
        # wins; else built from the durable_serving config block), plus the
        # uid registry the reconnect surface resolves against
        dcfg = getattr(engine._config, "durable_serving", None)
        self._durable: DurableServingConfig = (
            dcfg if dcfg is not None else DurableServingConfig())
        if journal is not None:
            self._journal: Optional[RequestJournal] = journal
        elif self._durable.enabled:
            self._journal = RequestJournal(
                self._durable.journal_dir,
                fsync_policy=self._durable.fsync_policy,
                compact_every=self._durable.compact_every)
        else:
            self._journal = None
        # crash/handoff sets this so the drain's error-finishes do NOT
        # retire journal entries — the next boot must replay them
        self._preserve_journal = False
        self._requests = {}  # uid -> _Request, live + recently finished
        from collections import deque
        self._done_order: "deque" = deque()
        self._replayed = 0
        self._restart_count = int(
            os.environ.get("DS_SERVE_RESTART_COUNT", "0") or 0)
        # supervisor-exported budget headroom (how many more crashes the
        # relaunch loop will absorb) — surfaced through stats//health so
        # the router can prefer peers with budget left
        _budget = os.environ.get("DS_SERVE_RESTART_BUDGET_REMAINING", "")
        self._restart_budget_remaining = int(_budget) if _budget else None
        self._boot_wall = time.time()
        # last-256 completed requests for the metrics aggregates:
        # (t_submit, t_first, t_done, n_tokens, replayed)
        self._completed: "deque" = deque(maxlen=256)
        # observability: pre-resolved metric handles + per-request span
        # tracer + profiler guard, or None with the block disabled (every
        # recording site is one `if self._obs is not None` away from the
        # pre-observability scheduler). An explicit ``instruments``
        # (private registry) wins — test isolation; ``instruments=False``
        # force-disables regardless of config (the bench's A/B arm).
        obscfg = getattr(engine._config, "observability", None)
        self._ocfg: ObservabilityConfig = (
            obscfg if obscfg is not None else ObservabilityConfig())
        if instruments is False:
            self._obs: Optional[ServingInstruments] = None
        elif instruments is not None:
            self._obs = instruments
        elif self._ocfg.enabled:
            self._obs = ServingInstruments(
                trace_requests=self._ocfg.trace_requests,
                trace_spans_per_request=self._ocfg.trace_spans_per_request,
                trace_waves=self._ocfg.trace_waves,
                profile_dir=self._ocfg.profile_dir,
                profile_max_seconds=self._ocfg.profile_max_seconds)
        else:
            self._obs = None
        sm = engine._config.state_manager
        self._max_batch_tokens = sm.max_ragged_batch_size
        self._token_budget = min(token_budget or self._max_batch_tokens,
                                 self._max_batch_tokens)
        self._max_seqs = min(sm.max_ragged_sequence_count,
                             self._token_budget)
        self._max_context = sm.max_context

    # ---- client surface (any thread) ----

    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_token_id: Optional[int] = None,
               seed: int = 0,
               stop=None,
               min_new_tokens: int = 0,
               repetition_penalty: float = 1.0,
               logits_processor=None,
               speculative: Optional[str] = None,
               num_draft_tokens: int = 4,
               draft_ngram: int = 2,
               return_logprobs: bool = False,
               deadline_s: Optional[float] = None,
               queue_ttl_s: Optional[float] = None,
               stream: bool = False,
               tenant: Optional[str] = None,
               adapter: Optional[str] = None) -> RequestHandle:
        """``deadline_s``: end-to-end budget (queue + decode) after which
        the request finishes with :class:`DeadlineExceeded`; ``queue_ttl_s``
        bounds only the unadmitted wait. Both default from the
        ``serving_resilience`` config. ``stream=True`` marks the caller as
        a ``stream()`` consumer: its token queue is bounded by
        ``max_stream_backlog`` and stops the request if never drained.
        ``tenant`` selects the scheduling contract from the config
        ``tenants`` block (weighted-fair admission + budgets, per-tenant
        shed); unnamed requests run as "default". ``adapter`` names a LoRA
        adapter (or exact ``name@version``) from the engine's adapter
        registry; defaults to the tenant's ``default_adapter``; unknown
        ids are a structured error, never a silent base fallback."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self._max_context:
            raise SchedulingError(SchedulingResult.SequenceTokenLimitExceeded)
        if speculative is not None:
            if speculative != "prompt_lookup":
                raise UnsupportedFeature(
                    f"unknown speculative mode {speculative!r}",
                    reason="unknown_speculative_mode")
            if (min_new_tokens or repetition_penalty != 1.0
                    or logits_processor is not None or return_logprobs):
                # UnsupportedFeature (a ValueError) → the HTTP handler's
                # structured 400 (not a dead request). temperature/top_k/
                # top_p are FINE now: the window verify rejection-samples
                # against the draft point masses on the per-sequence key
                # chains. The leftovers here mutate the distribution per
                # emitted token (penalty/min_new) or need host callbacks/
                # per-token logprobs a multi-token accept cannot honor.
                raise UnsupportedFeature(
                    "speculative decoding does not compose with "
                    "min_new_tokens/repetition_penalty/logits_processor/"
                    "logprobs", reason="speculative_compose_unsupported")
            if temperature != 0.0 and not self._device_sampling:
                raise UnsupportedFeature(
                    "speculative sampling requires "
                    "sampling.device_sampling",
                    reason="speculative_requires_device_sampling")
        tenant_name = str(tenant) if tenant else "default"
        if adapter is None:
            # per-tenant default: the tenants config block can route a
            # tenant's unadorned requests onto its own adapter
            adapter = self._tenant_cfg(tenant_name).default_adapter
        adapter_id = None
        if adapter is not None:
            reg = getattr(self._engine, "adapters", None)
            if reg is None:
                raise UnsupportedFeature(
                    f"adapter {adapter!r} requested but the engine has no "
                    "adapter registry (adapters.enabled is off)",
                    reason="adapters_disabled")
            try:
                adapter_id = reg.resolve(str(adapter))
            except KeyError:
                raise UnsupportedFeature(
                    f"unknown adapter {adapter!r}",
                    reason="unknown_adapter") from None
        req = _Request(uid=next(self._uid_iter), prompt=prompt,
                       max_new_tokens=int(max_new_tokens),
                       temperature=float(temperature), top_k=int(top_k),
                       top_p=float(top_p), eos_token_id=eos_token_id,
                       seed=int(seed),
                       stop=InferenceEngineV2.normalize_stop(stop),
                       min_new_tokens=int(min_new_tokens),
                       repetition_penalty=float(repetition_penalty),
                       logits_processor=logits_processor,
                       speculative=speculative,
                       num_draft_tokens=int(num_draft_tokens),
                       draft_ngram=int(draft_ngram),
                       return_logprobs=bool(return_logprobs),
                       tenant=tenant_name,
                       adapter=str(adapter) if adapter else None,
                       adapter_id=adapter_id)
        req.rng = np.random.default_rng(req.seed)
        req.t_submit = time.monotonic()
        req.wake = self._wake
        req.stream = bool(stream)
        # a host logits_processor is an arbitrary callable — it cannot be
        # journaled, so such requests are (documented) non-durable
        req.journal_skip = logits_processor is not None
        res = self._res
        if res.enabled:
            if deadline_s is None:
                deadline_s = res.default_deadline_s
            if queue_ttl_s is None:
                queue_ttl_s = res.default_queue_ttl_s
            if stream and res.max_stream_backlog > 0:
                req.stream_q = queue.Queue(maxsize=int(res.max_stream_backlog))
        if deadline_s is not None:
            req.t_deadline = req.t_submit + float(deadline_s)
        if queue_ttl_s is not None:
            req.t_queue_deadline = req.t_submit + float(queue_ttl_s)
        with self._lock:
            # the lock orders this against stop()'s drain: a submit that
            # loses the race lands AFTER _stopping is visible and is
            # rejected here rather than queued for a loop that never runs
            if self._stopping or self._draining:
                raise RuntimeError("scheduler is stopped")
            if res.enabled and (
                    (res.max_queued
                     and self._queued_n >= res.max_queued)
                    or (res.max_queued_tokens and self._queued_n
                        and (self._queued_tokens + len(prompt)
                             > res.max_queued_tokens))):
                self._trace["shed"] += 1
                if self._obs is not None:
                    self._obs.shed.inc()
                raise SchedulerOverloaded(
                    f"queue full ({self._queued_n} requests, "
                    f"{self._queued_tokens} prompt tokens queued)",
                    retry_after_s=res.retry_after_s)
            tcfg = self._tenant_cfg(req.tenant)
            if (tcfg.max_queued and self._tenant_queued.get(
                    req.tenant, 0) >= tcfg.max_queued):
                # per-tenant shed: one tenant's backlog must not consume
                # the global queue budget the other tenants share
                self._trace["shed"] += 1
                if self._obs is not None:
                    self._obs.shed.inc()
                raise SchedulerOverloaded(
                    f"tenant {req.tenant!r} queue full "
                    f"({self._tenant_queued.get(req.tenant, 0)} queued)",
                    retry_after_s=res.retry_after_s if res.enabled else 1.0)
            if req.adapter_id is not None:
                # pin INSIDE the lock, after every shed check: a request
                # that is rejected above never takes a slot, and one that
                # is admitted holds its adapter until _finish unpins
                try:
                    self._engine.set_request_adapter(req.uid, req.adapter_id)
                except KeyError:
                    raise UnsupportedFeature(
                        f"adapter {req.adapter_id!r} was unloaded",
                        reason="unknown_adapter") from None
                except AdapterSlotsExhausted as e:
                    self._trace["shed"] += 1
                    if self._obs is not None:
                        self._obs.shed.inc()
                    raise SchedulerOverloaded(
                        str(e), retry_after_s=(res.retry_after_s
                                               if res.enabled else 1.0)
                    ) from None
            # journal BEFORE the request becomes visible to the loop: the
            # loop could otherwise finish it and write a finish record the
            # recovery scan would see before (and thus ignore) the admit
            self._journal_admit(req)
            self._requests[req.uid] = req
            self._inbox.append(req)
            self._active += 1
            req.queued = True
            self._tq_inc(req)
            self._queued_n += 1
            self._queued_tokens += len(prompt)
        if self._obs is not None:
            self._obs.request_submitted(req.uid, req.t_submit)
        self._wake.set()
        return RequestHandle(req)

    def _journal_admit(self, req: _Request) -> None:
        if self._journal is None or req.journal_skip:
            return
        now_w, now_m = time.time(), time.monotonic()
        params = {
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature, "top_k": req.top_k,
            "top_p": req.top_p, "eos_token_id": req.eos_token_id,
            "seed": req.seed, "stop": req.stop,
            "min_new_tokens": req.min_new_tokens,
            "repetition_penalty": req.repetition_penalty,
            "speculative": req.speculative,
            "num_draft_tokens": req.num_draft_tokens,
            "draft_ngram": req.draft_ngram,
            "return_logprobs": req.return_logprobs,
            "stream": req.stream, "tenant": req.tenant,
            "adapter": req.adapter_id}
        try:
            self._journal.record_admit(
                req.uid, req.prompt, params,
                deadline_wall=(now_w + (req.t_deadline - now_m)
                               if req.t_deadline is not None else None),
                queue_deadline_wall=(
                    now_w + (req.t_queue_deadline - now_m)
                    if req.t_queue_deadline is not None else None))
        except OSError as e:  # journaling is best-effort; serving goes on
            logger.warning(f"[journal] admit record failed for request "
                           f"{req.uid}: {e}")

    # ---- multi-tenant bookkeeping -------------------------------------

    def _tenant_cfg(self, name: str) -> TenantConfig:
        """Scheduling contract for a tenant: its ``tenants`` config entry,
        else the "default" entry, else a neutral weight-1 contract — unknown
        tenants are never rejected, they just share the default lane."""
        return self._tenants.get(name) or self._tenant_fallback

    def _tq_inc(self, req: _Request) -> None:
        """Caller holds ``_lock``. Mirrors every ``req.queued = True``."""
        self._tenant_queued[req.tenant] = \
            self._tenant_queued.get(req.tenant, 0) + 1
        if self._obs is not None:
            self._obs.tenant_queue_depth(
                req.tenant, self._tenant_queued[req.tenant])

    def _tq_dec(self, req: _Request) -> None:
        """Caller holds ``_lock``. Mirrors every ``req.queued = False``."""
        n = self._tenant_queued.get(req.tenant, 0) - 1
        self._tenant_queued[req.tenant] = max(0, n)
        if self._obs is not None:
            self._obs.tenant_queue_depth(
                req.tenant, self._tenant_queued[req.tenant])

    def lookup(self, uid: int) -> Optional[RequestHandle]:
        """Re-attach to an in-flight or recently finished request by id —
        the reconnect surface. Works across a warm restart because journal
        replay keeps original uids."""
        with self._lock:
            req = self._requests.get(int(uid))
        return RequestHandle(req) if req is not None else None

    @property
    def stats(self) -> dict:
        with self._lock:
            inbox = len(self._inbox)
            done = list(self._completed)  # (t_sub, t_first, t_done, n, rp)
            queued_tokens = self._queued_tokens
            tr = self._trace
            shed, quarantined = tr["shed"], len(tr["quarantined"])
            expired = tr["expired_queue"] + tr["expired_live"]
            watchdog_trips = tr["watchdog_trips"]
            spec_drafted = tr["spec_drafted"]
            spec_accepted = tr["spec_accepted"]
            fused_tokens = tr["fused_tokens"]
            decode_tokens = tr["decode_tokens"]
            fused_dispatches = tr["fused_dispatches"]
            fused_k_sum = tr["fused_k_sum"]
            prefill_overlap = tr["prefill_overlap_tokens"]
            tq = dict(self._tenant_queued)
            td = dict(self._tenant_delivered)
        out = {"waiting": len(self._waiting) + inbox,
               "live": len(self._live),
               "free_blocks": self._engine.free_blocks,
               "stopped": self._stopping,
               "draining": self._draining,
               "degraded": self._degraded,
               "last_progress_age_s": round(
                   time.monotonic() - self._last_progress, 3),
               "queued_tokens": queued_tokens,
               "shed": shed,
               "expired": expired,
               "quarantined": quarantined,
               "watchdog_trips": watchdog_trips,
               "spec_drafted": spec_drafted,
               "spec_accepted": spec_accepted,
               "spec_accept_rate": (round(spec_accepted / spec_drafted, 4)
                                    if spec_drafted else None),
               # continuous fusion: how much of the decode stream the
               # K-step wave owns (≈0 means every token pays a per-token
               # host round-trip), the realized mean window, and prefill
               # tokens fed while a wave was in flight
               "fused_occupancy": (round(fused_tokens / decode_tokens, 4)
                                   if decode_tokens else None),
               "mean_fused_K": (round(fused_k_sum / fused_dispatches, 2)
                                if fused_dispatches else None),
               "prefill_overlap_tokens": prefill_overlap,
               # disaggregated prefill/decode: group topology, handoff
               # queue depth, degrade/stall tallies (None = single group)
               "disagg": (self._disagg.stats()
                          if self._disagg is not None else None),
               "journal_depth": (self._journal.depth
                                 if self._journal is not None else 0),
               "replayed_requests": self._replayed,
               # live-migration readiness: a handoff in progress (journal
               # export running / exported) is NOT a plain drain
               "migrating": self._migrating,
               "journal_export_depth": self._journal_export_depth,
               "imported_requests": self._imported,
               "restart_count": self._restart_count,
               "restart_budget_remaining": self._restart_budget_remaining,
               "last_restart_age_s": (round(time.time() - self._boot_wall, 3)
                                      if self._restart_count else None),
               "completed": len(done)}
        # per-tenant scheduling view: queue depth, live load, delivered
        # tokens — the router's tenant-aware balancer and ds_top read this
        live_by = {}
        live_tok = {}
        for r in list(self._live):
            live_by[r.tenant] = live_by.get(r.tenant, 0) + 1
            live_tok[r.tenant] = (live_tok.get(r.tenant, 0)
                                  + len(r.prompt) + r.max_new_tokens)
        tenants = {}
        for name in set(tq) | set(td) | set(live_by) | set(self._tenants):
            cfg = self._tenant_cfg(name)
            tenants[name] = {
                "queued": tq.get(name, 0),
                "live": live_by.get(name, 0),
                "live_tokens": live_tok.get(name, 0),
                "delivered_tokens": td.get(name, 0),
                "weight": cfg.weight, "priority": cfg.priority}
        out["tenants"] = tenants
        out["prefix_cache"] = self._engine.prefix_cache_report()
        # multi-LoRA view: registered/live/pinned adapters — the router's
        # adapter-affinity scoring and ds_top read this
        reg = getattr(self._engine, "adapters", None)
        out["adapters"] = reg.stats() if reg is not None else None
        done = [d for d in done if d[3] > 0]
        # replayed requests' TTFT spans the crash + restart (measured from
        # the ORIGINAL admit) — real for that client, but a restart would
        # skew the scheduler-latency aggregate, so the mean excludes them
        fresh = [d for d in done if not d[4]]
        if fresh:
            # MII-style serving metrics over the recent completions:
            # time-to-first-token and per-request decode rate
            out["ttft_mean_s"] = round(
                sum(t1 - t0 for t0, t1, _, _, _ in fresh) / len(fresh), 4)
        if done:
            rates = [(n - 1) / max(t2 - t1, 1e-9)
                     for _, t1, t2, n, _ in done if n > 1]
            if rates:
                out["decode_tok_s_mean"] = round(sum(rates) / len(rates), 2)
        if self._obs is not None:
            # histogram-derived percentiles (whole-process, not last-256)
            ps = self._obs.ttft.percentiles((0.5, 0.95, 0.99))
            for q, v in zip(("p50", "p95", "p99"), ps):
                if v is not None:
                    out[f"ttft_{q}_s"] = round(v, 4)
            it99 = self._obs.inter_token.quantile(0.99)
            if it99 is not None:
                out["inter_token_p99_s"] = round(it99, 4)
        return out

    @property
    def trace(self) -> dict:
        """Resilience event counters (tests assert on these): ``shed``,
        ``expired_queue``/``expired_live``, ``tick_errors``, the ordered
        ``quarantined`` uid list, ``watchdog_trips``,
        ``slow_consumer_cancels``."""
        with self._lock:
            return {k: (list(v) if isinstance(v, list) else v)
                    for k, v in self._trace.items()}

    @property
    def observability(self) -> Optional[ServingInstruments]:
        """The instruments bundle (registry/tracer/profiler) the HTTP
        observability endpoints render, or None with the block disabled."""
        return self._obs

    @property
    def engine(self) -> InferenceEngineV2:
        """The served engine (the adapter admin endpoints reach its
        registry through this)."""
        return self._engine

    def trace_timeline(self, uid: int) -> Optional[dict]:
        """Per-request span timeline (``GET /requests/<uid>/trace``)."""
        if self._obs is None:
            return None
        return self._obs.tracer.timeline(str(int(uid)))

    def wait_timeout(self, handle: RequestHandle) -> Optional[float]:
        """Bound for a blocking wait on one request (the HTTP threads'
        ``result()`` / per-token stream gap): the remaining deadline when
        the request has one (plus slack for the expiry sweep to run), else
        the ``http_timeout_s`` cap. None only with resilience disabled —
        the legacy unbounded wait."""
        res = self._res
        cap = res.http_timeout_s if res.enabled else None
        t_deadline = handle._req.t_deadline
        if t_deadline is not None:
            remaining = max(0.05, t_deadline - time.monotonic()
                            + 4 * self._idle_wait + 1.0)
            return min(remaining, cap) if cap is not None else remaining
        return cap

    # ---- lifecycle ----

    def start(self) -> "ServingScheduler":
        assert self._thread is None, "scheduler already started"
        self._stopping = False
        self._draining = False
        self._degraded = False
        self._preserve_journal = False
        if self._journal is not None and self._durable.replay_on_start:
            self._replay_journal()
        self._last_progress = time.monotonic()
        self._thread = threading.Thread(target=self._run, name="ds-serve",
                                        daemon=True)
        self._thread.start()
        if self._res.enabled and self._res.watchdog_s > 0:
            self._watchdog = threading.Thread(
                target=self._watch, name="ds-serve-watchdog", daemon=True)
            self._watchdog.start()
        return self

    def stop(self, timeout: float = 30.0, drain: bool = False) -> None:
        """Stop the loop. ``drain=True`` first refuses new submissions and
        lets in-flight requests run to completion; the WHOLE shutdown
        (drain poll + thread join) is bounded by ``timeout``. Without
        drain, pending requests are error-finished immediately."""
        deadline = time.monotonic() + timeout
        if drain and self._thread is not None:
            with self._lock:
                self._draining = True  # submit() rejects, loop keeps going
            while time.monotonic() < deadline:
                with self._lock:
                    idle = self._active == 0  # submit().._finish() span —
                    # immune to the loop's unlocked queue transfers
                if idle:
                    break
                time.sleep(self._idle_wait)
        self._stopping = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(max(0.0, deadline - time.monotonic()) or 0.01)
            self._thread = None
        if self._watchdog is not None:
            # joined so a later start() can't race a stale watchdog seeing
            # the reset _stopping flag and living on as a duplicate
            self._watchdog.join(1.5)
            self._watchdog = None

    def handoff(self, timeout: float = 30.0) -> None:
        """SIGTERM path: stop the loop WITHOUT retiring journal entries,
        then fsync the journal — the next daemon generation (pointed at
        the same journal dir) replays every in-flight request and its
        resumed stream continues bit-identically. Pending local handles
        error-finish exactly like ``stop()``; remote clients re-attach by
        uid against the new boot."""
        self._preserve_journal = True
        with self._lock:
            self._draining = True  # submit() refuses from here on
        self._stopping = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if self._watchdog is not None:
            self._watchdog.join(1.5)
            self._watchdog = None
        if self._journal is not None:
            # every emitted token was journaled at its tick's end, so the
            # checkpoint only needs to make the tail durable
            try:
                self._journal.checkpoint()
            except OSError as e:
                logger.warning(f"[journal] handoff checkpoint failed: {e}")

    def _replay_journal(self) -> None:
        """Warm restart: re-admit every unfinished journaled request with
        its original uid and remaining wall-clock deadline. Emitted tokens
        become prefix feed (the eviction-replay machinery re-prefills them
        chunkwise and samples the next token when the feed completes), the
        host RNG re-burns its consumed entropy, and ``_restore_sampler``
        fast-forwards the device key chain at admission — so resumed
        greedy AND sampled streams are byte-identical to an uninterrupted
        run. Requests whose journaled output already satisfies a finish
        condition (crash after the last token, before the finish record)
        complete immediately instead of re-entering the queue."""
        try:
            entries = self._journal.recover()
        except OSError as e:
            logger.warning(f"[journal] recovery failed: {e}")
            return
        if not entries:
            return
        admitted, finished, _ = self._admit_replayed_entries(entries,
                                                             live=False)
        logger.warning(f"[journal] replayed {len(admitted) + len(finished)} "
                       f"unfinished request(s) ({len(finished)} already "
                       f"complete)")

    def _repin_adapter(self, req: _Request) -> bool:
        """Re-pin a replayed request's journaled adapter version; on any
        failure set a typed error and report False (the caller
        error-finishes the request instead of continuing it wrong)."""
        if req.adapter_id is None:
            return True
        try:
            self._engine.set_request_adapter(req.uid, req.adapter_id)
            return True
        except (KeyError, RuntimeError) as e:
            req.error = UnsupportedFeature(
                f"replay: adapter {req.adapter_id!r} unavailable: {e}",
                reason="adapter_unavailable")
            return False

    def _req_from_entry(self, e, now_w: float, now_m: float) -> _Request:
        """Rebuild a scheduler request from a journal entry: original uid,
        emitted tokens as prefix feed, key burns for the sampler
        fast-forward, wall deadlines converted back to monotonic."""
        p = e.params
        req = _Request(
            uid=e.uid, prompt=[int(t) for t in e.prompt],
            max_new_tokens=int(p.get("max_new_tokens", 32)),
            temperature=float(p.get("temperature", 0.0)),
            top_k=int(p.get("top_k", 0)),
            top_p=float(p.get("top_p", 1.0)),
            eos_token_id=p.get("eos_token_id"),
            seed=int(p.get("seed", 0)),
            stop=[[int(t) for t in s] for s in p.get("stop") or []],
            min_new_tokens=int(p.get("min_new_tokens", 0)),
            repetition_penalty=float(p.get("repetition_penalty", 1.0)),
            speculative=p.get("speculative"),
            num_draft_tokens=int(p.get("num_draft_tokens", 4)),
            draft_ngram=int(p.get("draft_ngram", 2)),
            return_logprobs=bool(p.get("return_logprobs")),
            tenant=str(p.get("tenant") or "default"),
            adapter=p.get("adapter"), adapter_id=p.get("adapter"))
        req.outputs = [int(t) for t in e.tokens]
        req.logprobs = list(e.logprobs)
        req.key_burns = int(e.key_burns)
        req.journaled_n = len(req.outputs)
        req.journaled_burns = req.key_burns
        req.replayed = True
        req.stream = bool(p.get("stream"))
        req.wake = self._wake
        req.t_submit = now_m
        if req.outputs:
            req.t_first = now_m
        req.rng = np.random.default_rng(req.seed)
        self._burn_host_rng(req)
        if (req.stream and self._res.enabled
                and self._res.max_stream_backlog > 0):
            req.stream_q = queue.Queue(
                maxsize=int(self._res.max_stream_backlog))
        if e.deadline_wall is not None:
            req.t_deadline = now_m + (e.deadline_wall - now_w)
        if e.queue_deadline_wall is not None:
            req.t_queue_deadline = now_m + (e.queue_deadline_wall - now_w)
        return req

    def _admit_replayed_entries(self, entries, live: bool):
        """Re-admit journal entries into the scheduler. ``live=False`` is
        the boot-time replay (the loop has not started; entries land in
        ``_waiting`` and the uid iterator bumps past them). ``live=True``
        is a cross-replica import on a RUNNING scheduler: entries land in
        the inbox (the loop's own transfer point), are re-journaled into
        THIS replica's WAL so a later crash here still preserves them, and
        uids already owned by this scheduler are refused (split brain —
        two replicas must never serve one stream). Returns
        ``(admitted_uids, finished_uids, refused_uids)``."""
        now_w, now_m = time.time(), time.monotonic()
        max_uid = 0
        finish_now: List[_Request] = []
        admitted: List[int] = []
        refused: List[int] = []
        split_brain = (get_fault_injector().fire("router.split_brain_uid")
                       if live else None)
        with self._lock:
            for e in entries:
                if live:
                    if self._stopping or self._draining:
                        refused.append(e.uid)
                        continue
                    collide = (split_brain is not None
                               and int(split_brain.get("uid", e.uid))
                               == e.uid)
                    if e.uid in self._requests or collide:
                        logger.warning(
                            f"[journal] import refused uid {e.uid}: already "
                            f"owned by this replica (split brain)")
                        refused.append(e.uid)
                        continue
                max_uid = max(max_uid, e.uid)
                req = self._req_from_entry(e, now_w, now_m)
                self._requests[req.uid] = req
                self._active += 1
                if self._finished_already(req):
                    finish_now.append(req)
                elif not self._repin_adapter(req):
                    # the journaled VERSIONED id must re-resolve exactly —
                    # a replayed stream continuing on different factors (or
                    # silently on base weights) would diverge byte-wise, so
                    # unavailability is a loud error finish
                    finish_now.append(req)
                else:
                    req.queued = True
                    self._tq_inc(req)
                    self._queued_n += 1
                    self._queued_tokens += len(req.prompt)
                    if live:
                        self._inbox.append(req)
                    else:
                        self._waiting.append(req)
                    admitted.append(req.uid)
                self._replayed += 1
                if live:
                    self._imported += 1
                if self._obs is not None:
                    self._obs.request_replayed(req.uid, req.t_submit,
                                               len(req.outputs))
            if live and self._journal is not None:
                # the importer's own WAL must cover adopted requests from
                # this instant: admit + folded progress, inside the lock so
                # no finish can precede its admit (same ordering as submit)
                for e in entries:
                    if e.uid in refused:
                        continue
                    try:
                        self._journal.record_admit(
                            e.uid, e.prompt, e.params,
                            deadline_wall=e.deadline_wall,
                            queue_deadline_wall=e.queue_deadline_wall)
                        if e.tokens or e.key_burns:
                            self._journal.record_progress(
                                e.uid, e.tokens, len(e.tokens), e.key_burns,
                                logprobs=e.logprobs or None)
                    except OSError as err:
                        logger.warning(f"[journal] import record failed "
                                       f"for request {e.uid}: {err}")
        if not live:
            # original uids survive the restart; fresh mints go above them.
            # Imports do NOT bump: a migrated uid lives in its source
            # replica's stride (DS_SERVE_UID_BASE namespacing) and must
            # not drag this replica's iterator into a foreign namespace.
            nxt = next(self._uid_iter)
            self._uid_iter = itertools.count(max(nxt, max_uid + 1))
        for req in finish_now:  # _finish takes the lock itself
            self._finish(req, flush=False)
        if live and (admitted or finish_now):
            self._wake.set()
        return admitted, [r.uid for r in finish_now], refused

    # ---- cross-replica live migration (router surface) ----

    def export_journal(self, drain: bool = True) -> bytes:
        """Drain this replica's unfinished journal entries as a portable
        CRC-frame stream (``GET /journal/export``): flips readiness to
        ``migrating``, stops the scheduler WITHOUT retiring journal
        entries (the ``handoff()`` path), and snapshots the unfinished
        state. A peer POSTs the bytes to ``/journal/import`` and replays
        every stream mid-flight, byte-identically."""
        if self._journal is None:
            raise RuntimeError("journal export needs durable serving "
                               "(durable_serving.enabled)")
        self._migrating = True
        with self._lock:
            self._journal_export_depth = self._journal.depth
        if drain and self._thread is not None:
            self.handoff()
        frames, depth = self._journal.export_frames()
        with self._lock:
            self._journal_export_depth = depth
        return frames

    def import_journal_frames(self, buf: bytes) -> dict:
        """Adopt a peer's exported journal frames mid-run
        (``POST /journal/import``): scan with the recovery scanner
        (damaged records quarantine individually), re-admit the unfinished
        requests with their ORIGINAL uids, and continue each stream
        byte-identically — emitted tokens replay as prefix feed and the
        PRNG chains fast-forward by their recorded burn counts."""
        from .journal import entries_from_frames
        entries, bad = entries_from_frames(buf)
        admitted, finished, refused = self._admit_replayed_entries(
            entries, live=True)
        if admitted or finished:
            logger.warning(
                f"[journal] imported {len(admitted) + len(finished)} "
                f"migrated request(s) ({len(finished)} already complete, "
                f"{len(refused)} refused, {bad} quarantined)")
        return {"imported": len(admitted), "finished": len(finished),
                "refused_uids": refused, "quarantined_records": bad}

    def _finished_already(self, req: _Request) -> bool:
        if not req.outputs:
            return False
        if len(req.outputs) >= req.max_new_tokens:
            return True
        # emission never continues past eos, so membership == cut
        if req.eos_token_id is not None and req.eos_token_id in req.outputs:
            return True
        return bool(req.stop
                    and self._engine.hit_stop(req.outputs, req.stop))

    def _burn_host_rng(self, req: _Request) -> None:
        """Re-consume the host numpy sampler's entropy for a replayed
        request: exactly one vocab-sized gumbel per emitted token iff the
        request sampled on host (positive temperature and top_p, not
        device-owned) — the replayed generator then continues the same
        draw sequence an uninterrupted run would have used."""
        if req.temperature <= 0 or req.top_p <= 0 or not req.outputs:
            return
        if req.speculative is not None or self._device_eligible(req):
            return  # chain lives on device; _restore_sampler handles it
        vocab = int(self._engine._model.config.vocab_size)
        for _ in req.outputs:
            req.rng.gumbel(size=vocab)

    def _restore_sampler(self, req: _Request) -> None:
        """Re-seed the device key chain at its recorded position for a
        request entering the live set WITH history (journal replay or
        eviction replay): ``flush()`` dropped the key, and reseeding from
        scratch would fork the sampled stream mid-request."""
        if req.key_burns > 0 and req.outputs:
            self._engine.fast_forward_sampler(req.uid, req.seed,
                                              req.key_burns)

    def _run(self) -> None:
        crash: Optional[BaseException] = None
        try:
            while not self._stopping:
                t_tick = time.monotonic()
                progressed = self._safe_step()
                self._last_progress = time.monotonic()
                if self._obs is not None and progressed:
                    # idle polls stay out: the histogram measures work
                    # ticks, not the idle_wait cadence
                    self._obs.tick.record(self._last_progress - t_tick)
                if self._obs is not None:
                    tr = self._trace
                    self._obs.refresh(
                        self._queued_n, len(self._live),
                        self._engine.free_blocks,
                        tr["fused_tokens"], tr["decode_tokens"])
                if not progressed:
                    self._wake.wait(self._idle_wait)
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 — loop death must not
            crash = e               # silently hang every blocked caller
            # a crash is exactly what the journal exists for: keep every
            # entry so the next boot replays them (clean stop() retires)
            self._preserve_journal = True
        finally:
            self._stopping = True
            # drain UNDER the lock: submit() rejects once _stopping is
            # visible, so nothing can land in the inbox after this snapshot
            with self._lock:
                pending = self._live + self._waiting + self._inbox
                self._live, self._waiting, self._inbox = [], [], []
            for req in pending:
                if not req.done.is_set():
                    try:
                        self._engine.flush(req.uid)
                    except Exception:  # noqa: BLE001 — uid may be unknown
                        pass
                    req.error = crash or RuntimeError("server stopped")
                    self._finish(req, flush=False)
        if crash is not None:
            raise crash

    # ---- scheduler iteration (scheduler thread only) ----

    def step(self) -> bool:
        """One continuous-batching iteration: admit + prefill newly feasible
        prompts, advance every live sequence one decode token. Returns
        whether any work happened (False = fully idle)."""
        inj = get_fault_injector()
        if inj.enabled:
            args = inj.fire("serve.tick_hang")
            if args is not None:
                time.sleep(float(args.get("seconds", 0.5)))
            if inj.fire("serve.tick_error") is not None:
                raise InjectedFault("injected serving tick error")
            args = inj.fire("serve.crash")
            if args is not None:
                if str(args.get("mode", "drop")) == "exit":
                    # a real daemon death: the supervisor's relaunch path
                    os._exit(int(args.get("exit_code", 23)))
                # kill just the scheduler loop (BaseException sails past
                # the tick retry AND the quarantine bisect) — in-process
                # tests then replay the journal over the same engine
                raise ServingCrash("injected daemon crash")
        with self._lock:
            if self._inbox:
                self._waiting.extend(self._inbox)
                self._inbox = []

        # cancelled LIVE rows free their engine state HERE, before this
        # tick's admission — a cancel storm's blocks are available to
        # _admit in the same step instead of one tick later
        self._sweep_cancelled()
        self._expire_deadlines()

        admitted = self._admit()
        advanced = self._advance_tick()
        if self._journal is not None:
            self._journal_progress()
        return bool(admitted or advanced)

    def _journal_progress(self) -> None:
        """Append each live request's new tokens + key-burn count since the
        last record — the high-water marks a warm restart resumes from."""
        for req in self._live:
            if req.journal_skip:
                continue
            n = len(req.outputs)
            if n == req.journaled_n and req.key_burns == req.journaled_burns:
                continue
            lps = (req.logprobs[req.journaled_n:n]
                   if req.return_logprobs else None)
            t0 = time.monotonic()
            try:
                self._journal.record_progress(
                    req.uid, req.outputs[req.journaled_n:n], n,
                    req.key_burns, logprobs=lps)
            except OSError as e:
                logger.warning(f"[journal] progress record failed for "
                               f"request {req.uid}: {e}")
                continue
            if self._obs is not None:
                self._obs.tracer.span(
                    str(req.uid), "journal_append", t0, time.monotonic(),
                    {"tokens": n - req.journaled_n})
            req.journaled_n = n
            req.journaled_burns = req.key_burns

    def _sweep_cancelled(self) -> None:
        for req in [r for r in self._live if r.cancelled]:
            self._live.remove(req)
            self._finish(req)
        for req in [r for r in self._waiting if r.cancelled]:
            self._waiting.remove(req)
            self._finish(req, flush=False)

    def _expire_deadlines(self) -> None:
        """Finish requests past their deadline/TTL with a typed
        ``DeadlineExceeded``. Queued requests expire on either bound
        without ever touching the engine; live ones expire on the
        end-to-end deadline and flush, releasing their KV reservation."""
        if not self._res.enabled:
            return
        now = time.monotonic()

        def _past(t: Optional[float]) -> bool:
            return t is not None and now > t

        for req in [r for r in self._waiting
                    if _past(r.t_queue_deadline) or _past(r.t_deadline)]:
            self._waiting.remove(req)
            req.error = DeadlineExceeded(
                f"request {req.uid} expired unadmitted after "
                f"{now - req.t_submit:.3f}s")
            self._trace["expired_queue"] += 1
            self._finish(req, flush=False)
        for req in [r for r in self._live if _past(r.t_deadline)]:
            self._live.remove(req)
            req.error = DeadlineExceeded(
                f"request {req.uid} exceeded its deadline after "
                f"{now - req.t_submit:.3f}s ({len(req.outputs)} tokens)")
            self._trace["expired_live"] += 1
            self._finish(req)  # flush=True: KV reservation released

    def _safe_step(self) -> bool:
        """One tick behind the fault boundary. Transient engine errors are
        retried with backoff; a fault that survives the retry budget is
        reproducible and gets bisected to the one poisoning request, which
        alone is error-finished — the loop survives. Only a fault that
        reproduces with NO live requests (engine-global breakage with
        nothing to quarantine) still propagates to _run, whose drain
        error-finishes every blocked caller."""
        res = self._res
        if not res.enabled:
            return self.step()

        def _tick():
            try:
                return self.step()
            except Exception:
                self._trace["tick_errors"] += 1
                raise

        try:
            return retry_with_backoff(
                _tick, retries=1 + max(0, res.tick_retries),
                base_delay=res.tick_retry_backoff_s,
                exceptions=(Exception, ), desc="serving tick")
        except RetriesExhausted as e:
            self._quarantine(e.__cause__ if e.__cause__ is not None else e)
            return True

    def _quarantine(self, exc: BaseException) -> None:
        """Isolate the request that poisons the tick. The fault outlived
        its retry budget, so it is reproducible: bisect the live wave —
        tick one half with the other parked, keep whichever half still
        reproduces the fault — until one request remains, and error-finish
        only it. A probe IS a regular tick over a subset, so healthy
        requests advance their (deterministic) decode during the search;
        at most O(log n) extra probe ticks run."""
        suspects = list(self._live)
        if not suspects:
            raise exc
        while len(suspects) > 1:
            test = suspects[:len(suspects) // 2]
            rest = suspects[len(suspects) // 2:]
            test_ids = {id(r) for r in test}
            parked = [r for r in self._live if id(r) not in test_ids]
            self._live = [r for r in self._live if id(r) in test_ids]
            try:
                self._advance_tick()
                nxt = rest  # test half ticked clean: culprit is elsewhere
            except Exception:  # noqa: BLE001 — any repro narrows the hunt
                nxt = test
            finally:
                self._live.extend(parked)
            # a probe tick may have retired suspects (eos/eviction): keep
            # only the ones still live — an empty set means the fault
            # dissolved and the next regular tick proceeds normally
            live_ids = {id(r) for r in self._live}
            suspects = [r for r in nxt if id(r) in live_ids]
            if not suspects:
                return
        culprit = suspects[0]
        if culprit in self._live:
            self._live.remove(culprit)
        culprit.error = exc
        self._trace["quarantined"].append(culprit.uid)
        if self._obs is not None:
            self._obs.quarantined.inc()
            self._obs.tracer.event(str(culprit.uid), "quarantine",
                                   args={"error": repr(exc)})
        logger.warning(f"[serving] quarantined request {culprit.uid} after "
                       f"reproducible tick fault: {exc!r}")
        self._finish(culprit)  # flush=True: its KV reservation is released

    def _watch(self) -> None:
        """Watchdog thread: with work in flight and no tick progress for
        ``watchdog_s``, flip /health to degraded (carrying the
        last-progress age); clear it when the loop moves again."""
        period = max(0.02, min(self._res.watchdog_s / 4, 0.5))
        while not self._stopping:
            time.sleep(period)
            with self._lock:
                busy = self._active > 0
            age = time.monotonic() - self._last_progress
            if busy and age > self._res.watchdog_s:
                if not self._degraded:
                    self._degraded = True
                    with self._lock:
                        self._trace["watchdog_trips"] += 1
                    if self._obs is not None:
                        self._obs.watchdog_trips.inc()
                    logger.warning(f"[serving-watchdog] no scheduler "
                                   f"progress for {age:.2f}s with work in "
                                   "flight; /health degraded")
            elif self._degraded:
                self._degraded = False
                logger.warning("[serving-watchdog] scheduler progressing "
                               "again; /health restored")

    # Admission reservation MIRRORS InferenceEngineV2.generate: blocks for
    # the full feed + decode budget of every admitted AND live sequence,
    # so a tick's put cannot exhaust the allocator mid-flight (the shared
    # arithmetic is the model's own get_kv_requirements). Differences from
    # generate(), both deliberate: max_context is enforced at submit()
    # (sequences retire at seen+1 > max_context, so replay feeds stay
    # bounded), and prefill happens chunkwise inside _advance_tick's
    # SplitFuse budget instead of one whole-feed put per admission.
    def _future_blocks(self, seq_desc, extra: int) -> int:
        _, req = self._engine._model.get_kv_requirements(seq_desc, extra,
                                                         1 << 30)
        return req

    def _live_reserve(self) -> int:
        total = 0
        for r in self._live:
            seq = self._engine._state_manager.get_sequence(r.uid)
            if seq is None:  # admitted this tick, nothing fed yet
                seq = PlaceholderSequenceDescriptor()
            total += self._future_blocks(
                seq, r.pending + max(0, r.max_new_tokens - len(r.outputs)))
        return total

    def _admit(self) -> List[_Request]:
        """Move waiting requests into the live set (no forward happens
        here — _advance_tick feeds them chunkwise). A request admits when
        blocks for its ENTIRE feed + decode budget fit after the projected
        growth of everything already live.

        Admission order is weighted-fair across tenants: each pick takes
        the FIFO head of the tenant with the smallest weighted live-token
        deficit (higher ``priority`` strictly first; tenants at their
        ``max_live_tokens`` cap are skipped, so their share redistributes
        — work-conserving). The loop still breaks the moment the chosen
        head cannot fit, never queue-jumping within or across tenants, so
        a single-tenant system degenerates exactly to plain FIFO."""
        free = self._engine.free_blocks - self._live_reserve()
        admitted: List[_Request] = []
        live_tok: Dict[str, int] = {}
        for r in self._live:
            live_tok[r.tenant] = (live_tok.get(r.tenant, 0)
                                  + len(r.prompt) + r.max_new_tokens)
        queues: Dict[str, List[_Request]] = {}
        for r in self._waiting:
            queues.setdefault(r.tenant, []).append(r)
        while True:
            if len(self._live) >= self._max_seqs:
                break
            best = None
            for name, q in queues.items():
                if not q:
                    continue
                cfg = self._tenant_cfg(name)
                if (cfg.max_live_tokens
                        and live_tok.get(name, 0) >= cfg.max_live_tokens):
                    continue
                key = (-cfg.priority,
                       live_tok.get(name, 0) / cfg.weight, name)
                if best is None or key < best[0]:
                    best = (key, name, q)
            if best is None:
                break
            _, name, q = best
            req = q[0]
            need = self._future_blocks(
                PlaceholderSequenceDescriptor(),
                len(req.feed) + max(0, req.max_new_tokens - len(req.outputs)))
            if need > free:
                # the chosen head is the most-deficient admissible tenant's
                # oldest request — admitting anything else over it would be
                # queue-jumping, so stop the whole pass here
                break
            q.pop(0)
            free -= need
            self._waiting.remove(req)
            req.fed = 0
            self._restore_sampler(req)
            self._live.append(req)
            self._queue_drop(req)
            admitted.append(req)
            live_tok[name] = (live_tok.get(name, 0)
                              + len(req.prompt) + req.max_new_tokens)
        if not admitted and not self._live and self._waiting:
            # nothing can reserve full headroom: admit ONE on feed
            # feasibility alone rather than deadlocking (eviction truncates
            # it if the cache truly runs out)
            req = self._waiting[0]
            feed_need = self._future_blocks(PlaceholderSequenceDescriptor(),
                                            len(req.feed))
            if feed_need <= self._engine._state_manager.free_blocks:
                self._waiting.pop(0)
                req.fed = 0
                self._restore_sampler(req)
                self._live.append(req)
                self._queue_drop(req)
                admitted.append(req)
            else:
                # nothing is live, so nothing will ever free up: this
                # request can never run (generate() raises here too)
                req.error = SchedulingError(
                    SchedulingResult.KVCacheLimitExceeded)
                self._waiting.remove(req)
                self._finish(req, flush=False)
        if self._obs is not None and admitted:
            now = time.monotonic()
            for r in admitted:
                self._obs.request_admitted(r.uid, r.t_submit, now)
        return admitted

    @staticmethod
    def _water_fill(demands: Dict[str, Tuple[float, int]],
                    budget: int) -> Dict[str, int]:
        """Weighted max-min (water-filling) split of ``budget`` tokens over
        ``{tenant: (weight, demand)}``: each round hands every unsatisfied
        tenant its weighted share of the remaining budget (at least 1, so
        the loop always terminates), tenants that fill their demand drop
        out and their leftover redistributes — work-conserving."""
        grant = {name: 0 for name in demands}
        pending = {name: d for name, (_, d) in demands.items() if d > 0}
        while budget > 0 and pending:
            wsum = sum(demands[n][0] for n in pending)
            round_budget = budget
            for name in list(pending):
                w = demands[name][0]
                share = max(1, int(round_budget * w / wsum))
                take = min(share, pending[name], budget)
                grant[name] += take
                pending[name] -= take
                budget -= take
                if pending[name] <= 0:
                    del pending[name]
                if budget <= 0:
                    break
        return grant

    def _fair_takes(self, reqs, budget: int):
        """Split a prefill token budget across ``reqs`` (each wanting
        ``req.pending``) by tenant weight, FIFO within a tenant. With one
        tenant this is exactly the old greedy head-of-line loop. Returns
        ``[(req, take), ...]`` preserving the input (arrival) order."""
        tenants = {r.tenant for r in reqs}
        takes = []
        if len(tenants) <= 1:
            spent = 0
            for req in reqs:
                if spent >= budget:
                    break
                take = min(req.pending, budget - spent)
                takes.append((req, take))
                spent += take
            return takes
        demands = {}
        for r in reqs:
            w, d = demands.get(r.tenant, (self._tenant_cfg(r.tenant).weight,
                                          0))
            demands[r.tenant] = (w, d + r.pending)
        grant = self._water_fill(demands, budget)
        for req in reqs:
            left = grant.get(req.tenant, 0)
            if left <= 0:
                continue
            take = min(req.pending, left)
            grant[req.tenant] = left - take
            takes.append((req, take))
        return takes

    def _fair_decode_order(self, decodes):
        """WFQ order for an oversubscribed decode set: virtual finish time
        ``(i+1)/weight`` over each tenant's FIFO index ``i``, priority
        classes strictly first, uid as the deterministic tiebreak. Called
        only when decodes exceed the tick budget — the common case skips
        the sort entirely."""
        idx: Dict[str, int] = {}

        def key(r):
            cfg = self._tenant_cfg(r.tenant)
            i = idx.get(r.tenant, 0)
            idx[r.tenant] = i + 1
            return (-cfg.priority, (i + 1) / cfg.weight, r.uid)

        return sorted(decodes, key=key)

    def _queue_drop(self, req: _Request) -> None:
        """Request left the unadmitted set (admitted; finishes drop inside
        _finish's own lock section)."""
        with self._lock:
            if req.queued:
                req.queued = False
                self._tq_dec(req)
                self._queued_n -= 1
                self._queued_tokens -= len(req.prompt)

    def _queue_readd(self, req: _Request) -> None:
        """Eviction sent a live request back to the waiting queue."""
        with self._lock:
            if not req.queued:
                req.queued = True
                self._tq_inc(req)
                self._queued_n += 1
                self._queued_tokens += len(req.prompt)

    def _advance_tick(self) -> bool:
        """ONE scheduling pass of ≤ token_budget fed tokens (Dynamic
        SplitFuse): decoding sequences (pending == 1) are guaranteed their
        token first, prefilling sequences chunk into the remaining budget.
        A sequence samples only on the tick its feed completes.

        With continuous fusion (the default), the fusable decodes run
        their K-step wave EVERY tick — dispatched async, with prefill
        chunks and admission overlapped while the program runs on device
        (_continuous_tick). With the gate off, the wave only runs in the
        legacy exclusive mode: a quiet system with no prefill, no inbox,
        and no ADMISSIBLE waiting request (a request that cannot admit
        until KV frees gets no say — it cannot run either way, so it must
        not pin every decode to per-token dispatches)."""
        if self._disagg is not None:
            self._disagg_fed_tick = False
            self._disagg_pump()
        if not self._live:
            return False
        budget = self._token_budget
        decodes, prefills = [], []
        for r in self._live:
            if r.uid in self._on_prefill:
                # resident on the prefill group: pending>1 feeds there
                # (_disagg_fill); pending==1 means the final prompt chunk
                # sampled but its KV is still mid-handoff — the decode
                # wave cannot own it yet
                if r.pending <= 1:
                    self._disagg.note_decode_stall(r.uid)
                continue
            (decodes if r.pending == 1 else prefills).append(r)
        if self._fused_window > 1 and decodes:
            if self._cf.enabled:
                done = self._continuous_tick(decodes, prefills, budget)
                if done is not None:
                    return done
                # no wave could form (nothing fusable / adaptive K < 2 /
                # KV refused): the per-token tick below owns this pass
            elif (not prefills and not self._inbox
                    and not self._has_admissible_waiting()):
                # legacy exclusive mode: fuse EVERY feasible decode (K
                # steps, one dispatch) — plain-greedy requests and (when
                # on-device sampling is enabled) sampled/controlled ones
                # together; the partition is by feasibility, not
                # greediness. Requests the device cannot own — speculative
                # drafting and host logits_processor callbacks — keep
                # their per-token tick below (each request's sampling
                # depends only on its own context, so outputs are
                # unchanged by who shares the dispatch). A just-admitted
                # 1-token-prompt request has pending==1 but NO engine
                # sequence yet — it must take the per-token path, which
                # owns prefill (fused_decode_steps requires prefilled
                # history).
                eligible = [r for r in decodes if self._fusable(r)]
                fused = self._fused_tick(eligible) if eligible else []
                # speculative rows run their OWN fused wave (the
                # draft/verify scan feeds 1+d tokens per window — a
                # different program from the 1-token fused decode),
                # grouped so one dispatch still serves everything with
                # the same feed geometry
                live_ids = {id(r) for r in self._live}
                spec_rows = [r for r in decodes
                             if id(r) in live_ids and self._prefilled(r)
                             and self._spec_fusable(r)]
                fused += self._fused_spec_tick(spec_rows) if spec_rows \
                    else []
                if fused:
                    # exclude exactly the requests the fused dispatch
                    # advanced; near-budget greedy stragglers the
                    # partition left out stay in ``decodes`` and take
                    # this same tick's per-token path — one constrained
                    # request no longer demotes the whole wave
                    fused_ids = {id(r) for r in fused}
                    live_ids = {id(r) for r in self._live}
                    decodes = [r for r in decodes
                               if id(r) not in fused_ids
                               and id(r) in live_ids]
                    if not decodes:
                        return True
                    # fall through: per-token tick for the remainder
        advanced = self._per_token_tick(decodes, prefills, budget)
        # in-flight handoffs ARE progress: keep ticking (pumping) at full
        # cadence instead of sleeping idle_wait on top of the transfer
        return advanced or bool(self._on_prefill)

    def _prefilled(self, r: _Request) -> bool:
        seq = self._engine._state_manager.get_sequence(r.uid)
        return seq is not None and seq.seen_tokens > 0

    def _fusable(self, r: _Request) -> bool:
        if r.speculative is not None or not self._prefilled(r):
            return False
        if self._plain_greedy(r):
            return True
        return self._fused_sampled and self._device_eligible(r)

    def _has_admissible_waiting(self) -> bool:
        """True only if some waiting request could actually join the live
        set right now (seq-count + full-reservation feasible). _admit ran
        earlier this tick, so leftovers are normally infeasible — this
        re-check exists because admission stops at the first infeasible
        head-of-line request, which may shadow a smaller feasible one.
        An infeasible-until-KV-frees request returns False: it cannot run
        whether or not the wave fuses, so it must not demote the fused
        path to per-token mode (the `_waiting`-pins-the-wave bug)."""
        if not self._waiting:
            return False
        if len(self._live) >= self._max_seqs:
            return False
        free = self._engine.free_blocks - self._live_reserve()
        for req in self._waiting:
            need = self._future_blocks(
                PlaceholderSequenceDescriptor(),
                len(req.feed) + max(0, req.max_new_tokens - len(req.outputs)))
            if need <= free:
                return True
        return False

    def _adaptive_window(self) -> int:
        """Continuous-fusion window: the configured K, shrunk toward 1 as
        queue depth grows (halved per ``queue_depth_per_halving`` queued
        requests) and capped so the wave's estimated duration fits inside
        ``deadline_slack_frac`` of the slack to the nearest deadline —
        overlap never costs more than a bounded TTFT/deadline delay."""
        cap = self._fused_window
        cf = self._cf
        if cf.queue_depth_per_halving > 0:
            with self._lock:
                depth = len(self._inbox)
            depth += len(self._waiting)
            cap >>= min(depth // cf.queue_depth_per_halving, cap.bit_length())
        if self._step_ewma > 0.0 and self._res.enabled:
            now = time.monotonic()
            slack = None
            for r in self._live + self._waiting:
                if r.t_deadline is not None:
                    s = r.t_deadline - now
                    slack = s if slack is None else min(slack, s)
            if slack is not None:
                if slack <= 0:
                    return 1  # past due: expiry owns it next tick
                cap = min(cap, int(slack * cf.deadline_slack_frac
                                   / self._step_ewma))
        return max(cap, 1)

    def _continuous_tick(self, decodes, prefills, budget) -> Optional[bool]:
        """The overlapped tick: dispatch the fused K-step wave(s) async,
        spend the overlap window on host-side work (inbox drain, admission
        of newly feasible requests, prefill chunks up to the prefill
        budget) while the program runs on device, THEN harvest the fused
        fetch, and finish with a per-token pass for whatever the wave
        could not own. Returns None when no wave formed — the caller's
        per-token tick owns the pass (including eviction)."""
        eligible = [r for r in decodes if self._fusable(r)]
        spec_rows = [r for r in decodes if self._prefilled(r)
                     and self._spec_fusable(r)]
        if not eligible and not spec_rows:
            return None
        cap = self._adaptive_window()
        if self._obs is not None:
            self._obs.adaptive_k.set(cap)
        if cap < 2:
            return None
        t0 = time.monotonic()
        wave = self._fused_begin(eligible, cap) if eligible else None
        swaves = self._fused_spec_begin(spec_rows, cap) if spec_rows else []
        if wave is None and not swaves:
            return None
        protected = set()
        if wave is not None:
            protected.update(r.uid for r in wave[0])
        for sw in swaves:
            protected.update(r.uid for r in sw[0])
        self._in_flight = frozenset(protected)
        n_steps = 0
        try:
            fed = self._overlap_fill(budget)
            if fed:
                self._trace["prefill_overlap_tokens"] += fed
                if self._obs is not None:
                    self._obs.prefill_overlap.inc(fed)
        finally:
            # harvest EVEN IF the overlap work raised (a put fault rides
            # the tick retry boundary): an unharvested wave would leave
            # seq bookkeeping advanced with its tokens lost
            advanced = []
            if wave is not None:
                advanced += self._fused_harvest(wave)
                n_steps = max(n_steps, wave[2])
            for sw in swaves:
                advanced += self._fused_spec_harvest(sw)
                n_steps = max(n_steps, sw[1])
            self._in_flight = frozenset()
        if n_steps:
            per_step = (time.monotonic() - t0) / n_steps
            self._step_ewma = (per_step if self._step_ewma == 0.0
                               else 0.7 * self._step_ewma + 0.3 * per_step)
        self._retire_finished()
        # remainder pass: per-token tick for live decodes the wave didn't
        # advance (spec-ineligible rows, unprefilled admits, near-budget
        # stragglers) and any prefill still pending after the overlap —
        # rebuilt from the live set so overlap-window admissions ride this
        # same tick
        adv_ids = {id(r) for r in advanced}
        rem_decodes = [r for r in self._live
                       if r.pending == 1 and id(r) not in adv_ids
                       and r.uid not in self._on_prefill]
        rem_prefills = [r for r in self._live if r.pending > 1
                        and r.uid not in self._on_prefill]
        if rem_decodes or rem_prefills:
            self._per_token_tick(rem_decodes, rem_prefills, budget)
        return True

    def _overlap_fill(self, budget) -> int:
        """Host-side work done WHILE the fused wave runs on device: drain
        the inbox, admit newly feasible arrivals, and feed prefill chunks
        up to ``prefill_budget_frac`` of the token budget. The wave's KV
        is untouchable by construction — all its blocks were allocated at
        dispatch — and _tick_put's eviction fence keeps wave members out
        of the victim choice. Returns the prefill tokens fed."""
        with self._lock:
            if self._inbox:
                self._waiting.extend(self._inbox)
                self._inbox = []
        if self._waiting:
            self._admit()
        overlap_fed = 0
        if self._disagg is not None:
            # the prefill GROUP's put runs here so the host-side wait on
            # its logits overlaps the decode group's in-flight wave — the
            # space analog of the time overlap below
            overlap_fed += self._disagg_fill(budget)
        p_budget = int(budget * self._cf.prefill_budget_frac)
        if p_budget <= 0:
            return overlap_fed
        cands = [req for req in self._live
                 if not (req.uid in self._in_flight or req.pending <= 1
                         or req.uid in self._on_prefill)]
        p_reqs, p_chunks, spent = [], [], 0
        for req, take in self._fair_takes(cands, p_budget):
            p_reqs.append(req)
            p_chunks.append(req.feed_slice(take))
            spent += take
        if not p_reqs:
            return overlap_fed
        t0 = time.monotonic()
        if self._tick_put(p_reqs, p_chunks, {}) is None:
            # eviction fence refused / eviction ended the fill
            return overlap_fed
        if self._obs is not None:
            self._obs.prefill_span([r.uid for r in p_reqs], t0,
                                   time.monotonic(), spent, overlap=True)
        return overlap_fed + spent

    # ---- disaggregated prefill/decode (disagg.py) ----

    def _disagg_fill(self, budget) -> int:
        """Route-and-feed pass for the PREFILL group: newly admitted
        pending>1 requests with no decode-side history route here (unless
        the router is degraded or the prefill pool cannot hold them), then
        every resident gets a prompt chunk within the token budget — one
        ragged put on the prefill engine per tick. Returns tokens fed."""
        if self._disagg_fed_tick:
            return 0
        self._disagg_fed_tick = True
        ds = self._disagg
        for r in self._live:
            if (r.uid not in self._on_prefill and r.pending > 1
                    and self._engine._state_manager.get_sequence(r.uid)
                    is None
                    and ds.route_to_prefill(r.pending)):
                self._on_prefill.add(r.uid)
                if r.key_burns > 0 and r.outputs:
                    # replayed history: the final chunk SAMPLES on the
                    # prefill engine, so its key chain must stand at the
                    # recorded position too (the decode-side twin of
                    # _restore_sampler)
                    ds.prefill_engine.fast_forward_sampler(
                        r.uid, r.seed, r.key_burns)
        if not self._on_prefill:
            return 0
        reqs, chunks, spent = [], [], 0
        for r in self._live:
            if r.uid not in self._on_prefill or r.pending <= 1:
                continue
            if spent >= budget:
                break
            take = min(r.pending, budget - spent)
            reqs.append(r)
            chunks.append(r.feed_slice(take))
            spent += take
        if not reqs:
            return 0
        t0 = time.monotonic()
        if not self._disagg_put(reqs, chunks):
            return 0
        if self._obs is not None:
            self._obs.prefill_span([r.uid for r in reqs], t0,
                                   time.monotonic(), spent, overlap=True)
        return spent

    def _disagg_put(self, reqs, chunks) -> bool:
        """One ragged put on the prefill engine + handoff submission. The
        sampling mirror of _tick_put's draft-free branch pointed at the
        prefill group: a final prompt chunk's logits row comes from the
        same compiled program over the same weights as an in-group
        prefill's, and the device key chain stands at the same position —
        so the first token is bit-identical to the single-group path."""
        ds = self._disagg
        pe = ds.prefill_engine
        try:
            logits = np.asarray(pe.put([r.uid for r in reqs], chunks))
        except SchedulingError:
            # prefill pool exhausted mid-batch: nothing advanced (fed is
            # untouched) — this batch re-prefills in-group
            for r in list(reqs):
                self._degrade_to_decode(r)
            return False
        device_wave, finals = [], {}
        for req, chunk, row in zip(reqs, chunks, logits):
            req.fed += len(chunk)
            if req.pending == 0:  # feed complete: row is the next token
                # capture the handed-off history BEFORE emission grows it
                finals[id(req)] = np.asarray(req.feed, np.int32)
                if req.speculative is not None and req.temperature != 0.0:
                    new_toks, _ = pe.accept_drafts_sampled(
                        req.uid, [], row, self._spec_for(req),
                        req.num_draft_tokens)
                    req.key_burns += 1  # draft-free window still burns
                    self._trace["decode_tokens"] += self._emit_many(
                        req, new_toks)
                elif self._device_eligible(req):
                    device_wave.append((req, row))
                else:
                    self._emit(req, row)
        if device_wave:
            self._emit_device(device_wave, engine=pe)
        for req in reqs:
            hist = finals.get(id(req))
            if not ds.advance(req.uid, final=hist is not None,
                              tokens=hist):
                # decode pool refused the destination blocks
                self._degrade_to_decode(req)
        return True

    def _disagg_pump(self) -> None:
        """Land every handoff transfer that is ready on the wire, complete
        takeovers (the request joins the decode group: descriptor adopted
        over the landed blocks, prefix blocks registered, device key chain
        fast-forwarded), and degrade wedged handoffs to in-group prefill
        so admission never stalls behind a dead interconnect."""
        ds = self._disagg
        ready, degraded = ds.pump()
        for uid in ready:
            req = self._requests.get(uid)
            if (req is None or uid not in self._on_prefill
                    or req.done.is_set()):
                ds.abort(uid)
                self._on_prefill.discard(uid)
                continue
            if self._finished_already(req):
                # eos/stop/max on the very first token: no decode steps
                # will run — retire without adopting (the _finish hook
                # aborts the handoff and frees both pools)
                if req in self._live:
                    self._live.remove(req)
                self._finish(req, flush=False)
                continue
            ds.takeover(uid)
            self._on_prefill.discard(uid)
            self._restore_sampler(req)  # decode-side chain continues
        for uid in degraded:
            req = self._requests.get(uid)
            if req is not None and uid in self._on_prefill:
                self._degrade_to_decode(req, aborted=True)
            else:
                self._on_prefill.discard(uid)
        ds.refresh_occupancy(
            len(self._on_prefill),
            sum(1 for r in self._live if r.uid not in self._on_prefill))

    def _degrade_to_decode(self, req: _Request, aborted: bool = False
                           ) -> None:
        """Move a prefill-group resident back in-group, eviction-style:
        drop its prefill seq + handoff state and re-feed its WHOLE history
        on the decode group (the replay machinery — already-emitted tokens
        never re-emit, and _restore_sampler lands the key chain at its
        recorded position, so the stream continues bit-identically)."""
        self._on_prefill.discard(req.uid)
        if not aborted:
            self._disagg.abort(req.uid)
        if self._finished_already(req):
            # sampled its last token on the prefill group already; nothing
            # left to re-prefill for
            if req in self._live:
                self._live.remove(req)
            self._finish(req, flush=False)
            return
        req.fed = 0
        self._restore_sampler(req)

    def _per_token_tick(self, decodes, prefills, budget) -> bool:
        """The per-token SplitFuse pass: one ragged forward covering every
        decode's reserved token, host-path drafts, and prefill chunks in
        the spare budget."""
        if self._disagg is not None:
            # no overlap window fed the prefill group this tick (wave-less
            # pass, or the quarantine bisect re-entered): feed it here —
            # routing newly admitted pending>1 requests in the process —
            # then keep its residents out of the in-group lists
            self._disagg_fill(budget)
            if self._on_prefill:
                decodes = [r for r in decodes
                           if r.uid not in self._on_prefill]
                prefills = [r for r in prefills
                            if r.uid not in self._on_prefill]
        # decode SLA: every decoding sequence's 1 token is RESERVED before
        # drafts or prefill chunks may spend anything (generate() reserves
        # identically: draft_budget = max_batch - len(live))
        if len(decodes) > budget:
            # only an oversubscribed tick rations decode slots — and then
            # by weighted-fair queueing order, not arrival order
            decodes = self._fair_decode_order(decodes)
        reserve = min(len(decodes), budget)
        spare = budget - reserve
        d_reqs, d_chunks, drafted = [], [], {}
        for req in decodes[:reserve]:
            chunk = req.feed_slice(1)
            if req.speculative and spare > 0 and req.outputs:
                seq = self._engine._state_manager.get_sequence(req.uid)
                room = min(req.num_draft_tokens, spare,
                           self._max_context - seq.seen_tokens - 2,
                           req.max_new_tokens - len(req.outputs) - 1)
                d = InferenceEngineV2.prompt_lookup_draft(
                    req.prompt + req.outputs,
                    draft_ngram=req.draft_ngram, max_tokens=room,
                    match_window=self._engine.spec_ring_window(
                        req.num_draft_tokens),
                    match_cache=req.match_cache)
                if d:
                    drafted[req.uid] = d
                    chunk = chunk + d
                    spare -= len(d)
            d_reqs.append(req)
            d_chunks.append(chunk)
        p_reqs, p_chunks = [], []
        for req, take in self._fair_takes(prefills, max(0, spare)):
            p_reqs.append(req)
            p_chunks.append(req.feed_slice(take))
            spare -= take
        if not d_reqs and not p_reqs:
            return False
        t_put = time.monotonic()
        if drafted and p_reqs:
            # a prefill chunk inside a window-logits put would materialize
            # [S, chunk, vocab] logits — issue the windowed decode put and
            # the plain prefill put separately (generate() likewise keeps
            # its admit put apart from its windowed decode put)
            if self._tick_put(d_reqs, d_chunks, drafted) is None:
                return True  # eviction ended the tick; next tick rebuilds
            self._tick_put(p_reqs, p_chunks, {})
        elif drafted:
            self._tick_put(d_reqs, d_chunks, drafted)
        else:
            self._tick_put(d_reqs + p_reqs, d_chunks + p_chunks, {})
        if self._obs is not None and p_reqs:
            self._obs.prefill_span(
                [r.uid for r in p_reqs], t_put, time.monotonic(),
                sum(len(c) for c in p_chunks))
        self._retire_finished()
        return True

    @staticmethod
    def _plain_greedy(r: _Request) -> bool:
        """No sampling, no controls, no logprobs — the original argmax-only
        fused program (and the zero-dispatch host argmax per-token path)."""
        return (r.temperature == 0.0 and not r.return_logprobs
                and r.min_new_tokens == 0 and r.repetition_penalty == 1.0
                and r.logits_processor is None)

    def _device_eligible(self, r: _Request) -> bool:
        """Requests whose sampling/controls run on device (ops/sampling):
        anything except a host ``logits_processor`` callback (host-only by
        construction) or plain greedy (host argmax is already free)."""
        return (self._device_sampling and r.logits_processor is None
                and not self._plain_greedy(r))

    @staticmethod
    def _spec_for(r: _Request) -> "SampleSpec":
        return SampleSpec(
            temperature=r.temperature, top_k=r.top_k, top_p=r.top_p,
            repetition_penalty=r.repetition_penalty,
            eos_token_id=r.eos_token_id,
            block_eos=len(r.outputs) < r.min_new_tokens,
            history=(r.prompt + r.outputs)
            if r.repetition_penalty != 1.0 else None,
            seed=r.seed, want_logprobs=r.return_logprobs,
            n_out=len(r.outputs), min_new=r.min_new_tokens)

    def _fused_tick(self, decodes) -> list:
        """K decode steps for the fusable subset of the given (prefilled,
        device-ownable) decodes in ONE dispatch. An all-greedy wave runs
        the original argmax program; a wave with any sampled/controlled
        request runs the sampled scan program (greedy members are
        temperature-0 rows of the same dispatch — argmax over identical
        logits, so their streams don't change). Returns the list of
        requests the fused dispatch actually advanced — empty when no
        subset can reach a 2-step window or KV pressure refuses the wave
        (the caller's per-token tick owns eviction). The partition means a
        request within one token of its budget rides the per-token path
        alone instead of demoting the whole batch. Token accounting: the
        dispatch feeds each fused request's pending token plus its K-1
        first generations, so ``fed += K`` restores the pending==1 decode
        invariant; requests whose emit was cut short (eos/stop/max) retire
        this tick, exactly the conditions _emit_many cut on."""
        wave = self._fused_begin(decodes, self._fused_window)
        if wave is None:
            return []
        fused = self._fused_harvest(wave)
        self._retire_finished()
        return fused

    def _fused_begin(self, decodes, cap: int):
        """Partition + async dispatch of the plain/sampled fused wave.
        Returns ``(fused_reqs, engine_handle, K, all_greedy, t_dispatch)``,
        or None when no subset reaches a 2-step window or KV pressure
        refuses the wave (the caller's per-token tick owns eviction)."""
        fusable_uids, K, _solo = self._engine.fused_partition(
            [r.uid for r in decodes],
            [r.max_new_tokens - len(r.outputs) for r in decodes], cap)
        if K < 2:
            return None
        fusable_set = set(fusable_uids)
        fused = [r for r in decodes if r.uid in fusable_set]
        all_greedy = all(self._plain_greedy(r) for r in fused)
        try:
            if all_greedy:
                h = self._engine.fused_decode_begin(
                    [r.uid for r in fused],
                    [r.feed_slice(1)[0] for r in fused], K)
            else:
                h = self._engine.fused_decode_begin(
                    [r.uid for r in fused],
                    [r.feed_slice(1)[0] for r in fused], K,
                    specs=[self._spec_for(r) for r in fused])
        except SchedulingError:
            return None
        return (fused, h, K, all_greedy, time.monotonic())

    def _fused_harvest(self, wave) -> list:
        """Fetch + emit a dispatched fused wave (retirement is the
        caller's pass — wave members must not flush mid-overlap)."""
        fused, h, K, all_greedy, t0 = wave
        lps = None
        if all_greedy:
            toks = self._engine.fused_decode_harvest(h)
        else:
            toks, lps = self._engine.fused_decode_harvest(h)
            for r in fused:  # the sampled scan splits once per step
                r.key_burns += K
        self._trace["fused_dispatches"] += 1
        self._trace["fused_k_sum"] += K
        wave_tokens = 0
        for i, (req, row) in enumerate(zip(fused, toks)):
            req.fed += K
            emitted = self._emit_many(req, [int(t) for t in row],
                                      lps=[float(l) for l in lps[i]]
                                      if lps is not None else None)
            self._trace["fused_tokens"] += emitted
            self._trace["decode_tokens"] += emitted
            wave_tokens += emitted
            if not self._engine.decode_finished(
                    req.uid, req.outputs, req.max_new_tokens,
                    req.eos_token_id, req.stop):
                # deferred bookkeeping for requests that decode on
                # (fused_decode_steps defers like the speculative path);
                # retiring ones flush in _retire_finished
                seq = self._engine._state_manager.get_sequence(req.uid)
                self._engine._register_pending(seq)
                self._engine._model.maybe_free_kv(seq)
        if self._obs is not None:
            self._obs.fused_dispatches.inc()
            self._obs.fused_tokens.inc(wave_tokens)
            self._obs.wave_span([r.uid for r in fused], t0,
                                time.monotonic(), K, len(fused),
                                "greedy" if all_greedy else "sampled",
                                flops=self._engine._model.last_wave_flops())
        return fused

    def _spec_fusable(self, r: _Request) -> bool:
        """Speculative rows the device can own end-to-end: drafting from
        the ring buffer, verification, and (for sampled requests) the
        rejection-sampling accept all run inside the fused scan. Host
        ``logits_processor`` callbacks are rejected at submit; a gate-off
        or an over-wide ngram keeps the per-token host path — the parity
        oracle."""
        if r.speculative is None or not self._fused_spec:
            return False
        if r.draft_ngram > self._spec_max_ngram:
            return False
        return r.temperature == 0.0 or self._device_sampling

    def _fused_spec_tick(self, decodes) -> list:
        """K speculative draft/verify windows for the given rows in one
        dispatch per (draft width, ngram) group — the feed geometry
        ``1 + num_draft_tokens`` is a static of the compiled program, so
        heterogeneous widths run as separate waves (one dispatch each;
        workloads are typically homogeneous). Token accounting: the device
        emits between K and K*(1+d) tokens per row; ``fed`` advances by
        the emitted count so the pending==1 decode invariant holds, and
        the accept counters feed the per-request + /health observability."""
        advanced = []
        for sw in self._fused_spec_begin(decodes, self._fused_window):
            advanced.extend(self._fused_spec_harvest(sw))
        self._retire_finished()
        return advanced

    def _fused_spec_begin(self, decodes, cap: int) -> list:
        """Partition + async dispatch of the speculative wave(s), one per
        (draft width, ngram) group. Returns a list of
        ``(fused_reqs, K, engine_handle, all_greedy, t_dispatch)``
        handles — possibly empty under KV pressure (the per-token tick
        owns eviction)."""
        groups = {}
        for r in decodes:
            groups.setdefault((r.num_draft_tokens, r.draft_ngram),
                              []).append(r)
        waves = []
        for (d, ng), rows in groups.items():
            fusable_uids, K, _solo = self._engine.fused_spec_partition(
                [r.uid for r in rows],
                [r.max_new_tokens - len(r.outputs) for r in rows],
                d, cap)
            if K < 2:
                continue
            fusable_set = set(fusable_uids)
            fused = [r for r in rows if r.uid in fusable_set]
            all_greedy = all(r.temperature == 0.0 for r in fused)
            try:
                h = self._engine.fused_spec_decode_begin(
                    [r.uid for r in fused], [r.feed for r in fused], K,
                    num_draft_tokens=d, draft_ngram=ng,
                    specs=None if all_greedy
                    else [self._spec_for(r) for r in fused])
            except SchedulingError:
                continue  # KV pressure: the per-token tick owns eviction
            waves.append((fused, K, h, all_greedy, time.monotonic()))
        return waves

    def _fused_spec_harvest(self, swave) -> list:
        """Fetch + emit one dispatched speculative wave."""
        fused, K, h, all_greedy, t0 = swave
        toks_lists, drafted, accepted = \
            self._engine.fused_spec_decode_harvest(h)
        if not all_greedy:  # one split per verified window, K windows
            for req in fused:
                req.key_burns += K
        self._trace["fused_dispatches"] += 1
        self._trace["fused_k_sum"] += K
        wave_tokens = wave_dr = wave_ac = 0
        for req, row, dr, ac in zip(fused, toks_lists, drafted,
                                    accepted):
            req.fed += len(row)
            req.drafted += dr
            req.accepted += ac
            self._trace["spec_drafted"] += dr
            self._trace["spec_accepted"] += ac
            wave_dr += dr
            wave_ac += ac
            emitted = self._emit_many(req, row)
            self._trace["fused_tokens"] += emitted
            self._trace["decode_tokens"] += emitted
            wave_tokens += emitted
            if not self._engine.decode_finished(
                    req.uid, req.outputs, req.max_new_tokens,
                    req.eos_token_id, req.stop):
                # deferred bookkeeping exactly like _fused_tick:
                # retiring rows flush in _retire_finished instead
                seq = self._engine._state_manager.get_sequence(req.uid)
                self._engine._register_pending(seq)
                self._engine._model.maybe_free_kv(seq)
        if self._obs is not None:
            self._obs.fused_dispatches.inc()
            self._obs.fused_tokens.inc(wave_tokens)
            self._obs.spec_drafted.inc(wave_dr)
            self._obs.spec_accepted.inc(wave_ac)
            self._obs.wave_span([r.uid for r in fused], t0,
                                time.monotonic(), K, len(fused), "spec",
                                drafted=wave_dr, accepted=wave_ac,
                                flops=self._engine._model.last_wave_flops())
        return fused

    def _tick_put(self, reqs, chunks, drafted) -> Optional[bool]:
        """One ragged put + row processing. Returns None if KV exhaustion
        evicted a sequence (the tick must end: the eviction may have
        invalidated any other pending put group)."""
        use_window = bool(drafted)
        while True:
            try:
                # do_checks stays ON: chunks always fit the ragged limits
                # under the SplitFuse budget, and the feasibility check is
                # what turns KV exhaustion into a catchable SchedulingError
                logits = np.asarray(self._engine.put(
                    [r.uid for r in reqs], chunks,
                    window_logits=use_window,
                    defer_register=(frozenset(drafted)
                                    if use_window else frozenset())))
                break
            except SchedulingError:
                if use_window:
                    # drafts don't justify evicting a healthy sequence:
                    # retry the put draft-free (generate()'s rule)
                    chunks = [c[:1] if r.uid in drafted else c
                              for r, c in zip(reqs, chunks)]
                    drafted, use_window = {}, False
                    continue
                # KV exhausted mid-tick: evict the NEWEST live sequence
                # (generate()'s recovery). A lone sequence held the WHOLE
                # cache when it died, so its replay could never prefill —
                # finish it truncated (generate()'s lone-sequence
                # semantics) instead of requeueing it into a guaranteed
                # admission error discarding the tokens already streamed.
                # EVICTION FENCE: a member of an in-flight fused wave is
                # untouchable — the device program is still writing its KV
                # pages — so the victim is the newest NON-wave sequence;
                # with only wave members live the fill simply yields (the
                # post-harvest pass owns eviction with the fence down).
                # prefill-group residents are fenced like wave members:
                # their decode-pool blocks are mid-handoff (they free via
                # degrade/abort, never via this eviction path)
                vi = next((i for i in range(len(self._live) - 1, -1, -1)
                           if self._live[i].uid not in self._in_flight
                           and self._live[i].uid not in self._on_prefill),
                          None)
                if vi is None:
                    return None
                victim = self._live.pop(vi)
                self._engine.flush(victim.uid)
                victim.fed = 0
                if self._live:
                    self._waiting.insert(0, victim)
                    self._queue_readd(victim)
                elif victim.outputs:
                    self._finish(victim, flush=False)
                else:
                    victim.error = SchedulingError(
                        SchedulingResult.KVCacheLimitExceeded)
                    self._finish(victim, flush=False)
                return None
        device_wave = []  # (req, logits_row) — one batched sample dispatch
        for req, chunk, row in zip(reqs, chunks, logits):
            spec_sampled = (req.speculative is not None
                            and req.temperature != 0.0)
            d = drafted.get(req.uid, [])
            if d:
                if spec_sampled:
                    new_toks, m = self._engine.accept_drafts_sampled(
                        req.uid, d, row, self._spec_for(req),
                        req.num_draft_tokens)
                    req.key_burns += 1  # one split per verified window
                else:
                    new_toks, m = self._engine.accept_drafts(req.uid, d, row)
                req.fed += 1 + m
                req.drafted += len(d)
                req.accepted += m
                self._trace["spec_drafted"] += len(d)
                self._trace["spec_accepted"] += m
                self._trace["decode_tokens"] += self._emit_many(req,
                                                                new_toks)
            else:
                req.fed += len(chunk)
                if req.pending == 0:  # feed complete: row is the next token
                    last = row[len(chunk) - 1] if use_window else row
                    if spec_sampled:
                        # a draft-free step of a sampled speculative request
                        # still burns its per-WINDOW key (accept with an
                        # empty draft) so the key chain advances once per
                        # step on every path, fused or not
                        new_toks, _ = self._engine.accept_drafts_sampled(
                            req.uid, [], last, self._spec_for(req),
                            req.num_draft_tokens)
                        req.key_burns += 1  # draft-free window still burns
                        self._trace["decode_tokens"] += self._emit_many(
                            req, new_toks)
                    elif self._device_eligible(req):
                        device_wave.append((req, last))
                    else:
                        self._emit(req, last)
            if use_window:
                # window puts defer the trailing-window KV free for EVERY
                # sequence in the batch — resume it here
                seq = self._engine._state_manager.get_sequence(req.uid)
                if seq is not None:
                    self._engine._model.maybe_free_kv(seq)
        if device_wave:
            self._emit_device(device_wave)
        return True

    def _stream_put(self, req: _Request, tok: int) -> None:
        """Token delivery through the (possibly bounded) stream queue. A
        full queue means the consumer stopped draining — a disconnected or
        wedged client — so the request is cancelled instead of buffering
        its remaining decode without bound. The token is still appended to
        ``outputs`` by the caller; only stream delivery is dropped."""
        inj = get_fault_injector()
        if inj.enabled and inj.fire("serve.slow_consumer",
                                    uid=req.uid) is not None:
            req.cancelled = True
            self._trace["slow_consumer_cancels"] += 1
            return
        try:
            req.stream_q.put_nowait(tok)
        except queue.Full:
            req.cancelled = True
            self._trace["slow_consumer_cancels"] += 1
            logger.warning(f"[serving] request {req.uid} cancelled: stream "
                           f"consumer stopped draining "
                           f"({req.stream_q.maxsize} tokens undelivered)")

    def _mark_emit(self, req: _Request) -> None:
        """Timestamp bookkeeping for one about-to-append token: ``t_first``
        on the first (feeding the TTFT histogram unless the request is a
        journal replay, whose submit anchor predates the restart), the
        inter-token gap histogram on every later one."""
        now = time.monotonic()
        obs = self._obs
        if not req.outputs:
            req.t_first = now
            if obs is not None:
                obs.first_token(req.t_submit, now, req.replayed,
                                tenant=req.tenant)
        elif obs is not None and req.t_last > 0.0:
            obs.token_gap(now - req.t_last)
        req.t_last = now
        self._tenant_delivered[req.tenant] = \
            self._tenant_delivered.get(req.tenant, 0) + 1
        if obs is not None:
            obs.tokens.inc()
            obs.decode_tokens.inc()
            obs.tenant_token(req.tenant)
            if req.adapter_id is not None:
                obs.adapter_token(req.adapter_id)

    def _emit_device(self, wave, engine: Optional[InferenceEngineV2] = None
                     ) -> None:
        """ONE batched on-device sampling dispatch for every device-eligible
        row of a per-token tick (engine.sample_rows) — the N sampled
        decodes of a tick cost one host round-trip, not N. ``engine``
        points the dispatch at the prefill group's engine for first
        tokens sampled there (same program, same key chain → same bits)."""
        eng = engine if engine is not None else self._engine
        toks, lps = eng.sample_rows(
            [r.uid for r, _ in wave], [row for _, row in wave],
            [self._spec_for(r) for r, _ in wave])
        for (req, _), tok, lp in zip(wave, toks, lps):
            req.key_burns += 1  # sample_rows splits each row's key once
            if req.return_logprobs:
                req.logprobs.append(float(lp))
            self._mark_emit(req)
            req.outputs.append(int(tok))
            self._trace["decode_tokens"] += 1
            self._stream_put(req, int(tok))

    def _emit(self, req: _Request, logits_row) -> None:
        block_eos = len(req.outputs) < req.min_new_tokens
        if (req.repetition_penalty != 1.0 or block_eos
                or req.logits_processor is not None):
            logits_row = self._engine.process_logits(
                logits_row, req.prompt + req.outputs,
                repetition_penalty=req.repetition_penalty,
                eos_token_id=req.eos_token_id,
                block_eos=block_eos,
                logits_processor=req.logits_processor)
        tok, lp = self._engine._sample_with_logprob(
            logits_row, req.temperature, req.rng, req.top_k, req.top_p,
            want_lp=req.return_logprobs)
        if req.return_logprobs:
            req.logprobs.append(lp)
        self._mark_emit(req)
        req.outputs.append(int(tok))
        self._trace["decode_tokens"] += 1
        self._stream_put(req, int(tok))

    def _emit_many(self, req: _Request, toks, lps=None) -> int:
        """Stream a verified draft run or fused window, applying the
        eos/stop/max cuts so tokens past a cut never surface (generate()'s
        truncation rules; the overshot KV needs no rollback — the request
        retires and flushes). Returns the token count that actually
        surfaced (the occupancy counters' feed)."""
        emitted = 0
        for i, t in enumerate(toks):
            if len(req.outputs) >= req.max_new_tokens:
                break
            self._mark_emit(req)
            if req.return_logprobs:
                req.logprobs.append(float(lps[i]) if lps is not None
                                    else None)
            req.outputs.append(int(t))
            emitted += 1
            self._stream_put(req, int(t))
            if req.eos_token_id is not None and int(t) == req.eos_token_id:
                break
            if req.stop and self._engine.hit_stop(req.outputs, req.stop):
                break
        return emitted

    def _retire_finished(self) -> None:
        for req in list(self._live):
            if req.uid in self._in_flight:
                continue  # fused wave in flight: judge/flush after harvest
            if req.uid in self._on_prefill:
                # prefill-group resident: no decode-side descriptor yet —
                # an eos-on-first-token finish lands at takeover instead
                continue
            if not req.outputs or req.pending > 1:
                continue  # still (re)prefilling — nothing sampled to judge
            if self._engine._state_manager.get_sequence(req.uid) is None:
                continue  # admitted this tick, nothing fed yet
            if self._engine.decode_finished(req.uid, req.outputs,
                                            req.max_new_tokens,
                                            req.eos_token_id, req.stop):
                self._live.remove(req)
                self._finish(req)

    def _finish(self, req: _Request, flush: bool = True) -> None:
        if self._disagg is not None and req.uid in self._on_prefill:
            # prefill-group resident: its engine state is the prefill
            # seq + handoff (no decode-side descriptor to flush)
            self._on_prefill.discard(req.uid)
            self._disagg.abort(req.uid)
            flush = False
        if flush:
            self._engine.flush(req.uid)
        elif req.adapter_id is not None:
            # flush=False paths (queue expiry, replay error-finish) never
            # touched the engine, but the submit/replay pin is real
            reg = getattr(self._engine, "adapters", None)
            if reg is not None:
                reg.unpin(req.uid)
        if (self._journal is not None and not req.journal_skip
                and not self._preserve_journal):
            # crash/handoff keeps entries alive for the next boot's replay;
            # every normal finish (done/cancel/error/expiry) retires them
            try:
                self._journal.record_finish(req.uid)
            except OSError as e:
                logger.warning(f"[journal] finish record failed for "
                               f"request {req.uid}: {e}")
        req.t_done = time.monotonic()
        with self._lock:  # stats()/drain read under the same lock
            self._active -= 1
            if req.queued:  # finished straight out of the waiting queue
                req.queued = False
                self._tq_dec(req)
                self._queued_n -= 1
                self._queued_tokens -= len(req.prompt)
            if req.error is None and not req.cancelled:
                self._completed.append(
                    (req.t_submit, req.t_first, req.t_done,
                     len(req.outputs), req.replayed))
        if self._obs is not None:
            if req.error is None and not req.cancelled:
                outcome = "ok"
            elif req.cancelled:
                outcome = "cancelled"
            elif isinstance(req.error, DeadlineExceeded):
                outcome = "expired"
            else:
                outcome = "error"
            self._obs.request_finished(req.uid, req.t_submit, req.t_done,
                                       outcome, len(req.outputs),
                                       req.replayed, tenant=req.tenant,
                                       adapter=req.adapter_id)
            # keep the last 256 finished requests reconnectable by uid,
            # then let them go so the registry stays bounded
            self._done_order.append(req.uid)
            while len(self._done_order) > 256:
                old = self._done_order.popleft()
                r = self._requests.get(old)
                if r is not None and r.done.is_set():
                    self._requests.pop(old, None)
        req.done.set()
        while True:
            try:
                req.stream_q.put_nowait(_END)
                break
            except queue.Full:
                # bounded stream of a dead consumer: drop its oldest
                # undelivered token so the sentinel always lands
                try:
                    req.stream_q.get_nowait()
                except queue.Empty:
                    pass


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


def create_http_server(scheduler: ServingScheduler, host: str = "127.0.0.1",
                       port: int = 8000, tokenizer=None) -> ThreadingHTTPServer:
    """ThreadingHTTPServer over a running scheduler.

    POST /generate body (JSON):
      {"prompt": [ids]} or {"text": "..."} (requires tokenizer),
      optional max_new_tokens / temperature / top_k / top_p / eos_token_id /
      seed / stream. ``stream: true`` answers chunked, one JSON line per
      token; otherwise one JSON object with the full output.
    GET /health: scheduler stats.
    Observability (404 with the ``observability`` config block disabled):
      GET /metrics — Prometheus text exposition of the process registry;
      GET /requests/<uid>/trace — the request's span timeline as JSON;
      GET /debug/trace?last=N — recent waves + live timelines as Chrome
      ``trace_event`` JSON (Perfetto-loadable);
      POST /debug/profile — start a bounded jax.profiler capture
      (409 while one runs); POST /debug/profile/stop — end it early.
    """

    class Handler(BaseHTTPRequestHandler):
        # chunked Transfer-Encoding is an HTTP/1.1 construct; the default
        # HTTP/1.0 status line would make real clients mis-parse streams
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet by default
            pass

        def _json(self, code: int, obj, headers=()) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                # readiness vs liveness: "draining" (stop(drain=True) in
                # progress) and "degraded" (watchdog saw a stuck tick)
                # answer 503 so load balancers stop routing here, while
                # the payload still carries the full stats for operators
                stats = scheduler.stats
                if stats["migrating"]:
                    # checked before "stopped": an export stops the loop,
                    # but the router must see a handoff in progress (with
                    # journal_export_depth), not a plain shutdown
                    status = "migrating"
                elif stats["stopped"]:
                    status = "stopped"
                elif stats["draining"]:
                    status = "draining"
                elif stats["degraded"]:
                    status = "degraded"
                else:
                    status = "ok"
                self._json(200 if status == "ok" else 503,
                           {"status": status, **stats})
            elif self.path == "/metrics":
                obs = scheduler.observability
                if obs is None:
                    self._json(404, {"error": "observability disabled"})
                    return
                body = obs.registry.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/debug/trace"):
                obs = scheduler.observability
                if obs is None:
                    self._json(404, {"error": "observability disabled"})
                    return
                from urllib.parse import parse_qs, urlparse
                q = parse_qs(urlparse(self.path).query)
                try:
                    last = int(q.get("last", ["0"])[0]) or None
                except ValueError:
                    self._json(400, {"error": "bad last"})
                    return
                self._json(200, obs.tracer.chrome_trace(last))
            elif self.path == "/journal/export":
                # migration drain: hand every unfinished journal entry to
                # the caller (the fleet router) as the WAL's own portable
                # CRC-frame stream; this replica stops serving first
                try:
                    frames = scheduler.export_journal()
                except RuntimeError as e:
                    self._json(409, {"error": str(e)})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(frames)))
                self.send_header("X-DS-Journal-Depth",
                                 str(scheduler.stats["journal_export_depth"]))
                self.end_headers()
                self.wfile.write(frames)
            elif self.path.startswith("/requests/"):
                self._do_request_get()
            else:
                self._json(404, {"error": "not found"})

        def _do_request_get(self):
            """Reconnect surface: ``GET /requests/<uid>`` blocks for the
            full result (a non-streaming wait re-attach);
            ``GET /requests/<uid>/stream?from_token=N`` resumes a chunked
            token stream at the client's own high-water mark. Both work
            across a daemon warm restart (replay keeps original uids)."""
            from urllib.parse import parse_qs, urlparse
            parsed = urlparse(self.path)
            parts = [p for p in parsed.path.split("/") if p]
            try:
                uid = int(parts[1])
            except (IndexError, ValueError):
                self._json(400, {"error": "bad request id"})
                return
            if len(parts) > 2 and parts[2] == "trace":
                # post-hoc reconstruction: the span timeline survives the
                # request itself (bounded ring), so no live handle needed
                tl = scheduler.trace_timeline(uid)
                if tl is None:
                    self._json(404, {"error": f"no trace for request {uid}"})
                    return
                self._json(200, tl)
                return
            handle = scheduler.lookup(uid)
            if handle is None:
                self._json(404, {"error": f"unknown request {uid}"})
                return
            if len(parts) > 2 and parts[2] == "stream":
                try:
                    from_token = int(
                        parse_qs(parsed.query).get("from_token", ["0"])[0])
                except ValueError:
                    self._json(400, {"error": "bad from_token"})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.send_header("Transfer-Encoding", "chunked")
                self.send_header("X-DS-Request-Id", str(uid))
                self.end_headers()
                try:
                    for tok in handle.stream_from(
                            from_token,
                            timeout=scheduler.wait_timeout(handle)):
                        line = json.dumps({"token": tok}).encode() + b"\n"
                        self.wfile.write(hex(len(line))[2:].encode()
                                         + b"\r\n" + line + b"\r\n")
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # reconnectors never cancel the request
                except Exception:  # noqa: BLE001 — timeout/req error: the
                    try:           # streamed tokens stand, end chunking
                        self.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        pass
                return
            try:
                tokens = handle.result(
                    timeout=scheduler.wait_timeout(handle))
            except DeadlineExceeded as e:
                self._json(504, {"error": str(e)})
                return
            except TimeoutError:
                self._json(504, {"error": f"request {uid} did not "
                                          "complete in time"})
                return
            except Exception as e:  # noqa: BLE001 — surfaced to client
                self._json(500, {"error": str(e)})
                return
            self._json(200, {"uid": uid, "tokens": tokens})

        def _do_profile(self):
            """``POST /debug/profile`` starts a bounded ``jax.profiler``
            capture (body: optional ``{"seconds": N, "dir": ...}``); a
            second start while one runs answers 409. ``/stop`` ends a
            capture early (the auto-stop timer otherwise does)."""
            obs = scheduler.observability
            if obs is None:
                self._json(404, {"error": "observability disabled"})
                return
            if self.path.endswith("/stop"):
                info = obs.profiler.stop()
                if info is None:
                    self._json(200, {"status": "idle"})
                else:
                    self._json(200, {"status": "stopped", **info})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                seconds = body.get("seconds")
                seconds = float(seconds) if seconds is not None else None
                directory = body.get("dir")
            except (ValueError, TypeError):
                self._json(400, {"error": "bad profile request body"})
                return
            try:
                info = obs.profiler.start(seconds, directory)
            except ProfilerBusy as e:
                self._json(409, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001 — profiler backends vary
                self._json(500, {"error": f"profiler start failed: {e}"})
                return
            self._json(200, {"status": "started", **info})

        def _do_adapters(self):
            """``POST /adapters/load`` (``{"path": dir, "name": ...}``) and
            ``POST /adapters/unload`` (``{"adapter": name_or_id}``) — the
            hot-swap surface: factors land in (or leave) the running bank
            via value-only slot writes, so the daemon never restarts and
            the fused programs never recompile."""
            reg = getattr(scheduler.engine, "adapters", None)
            if reg is None:
                self._json(404, {"error": "adapters disabled "
                                          "(adapters.enabled is off)",
                                 "reason": "adapters_disabled"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
            except ValueError:
                self._json(400, {"error": "bad JSON body"})
                return
            try:
                if self.path == "/adapters/load":
                    path = body.get("path")
                    if not path:
                        raise ValueError("missing 'path' (adapter "
                                         "checkpoint dir)")
                    aid = reg.load(str(path), name=body.get("name"))
                    self._json(200, {"status": "loaded", "adapter": aid})
                else:
                    target = body.get("adapter") or body.get("name")
                    if not target:
                        raise ValueError("missing 'adapter' (name or "
                                         "name@version)")
                    aid = reg.unload(str(target))
                    self._json(200, {"status": "unloaded", "adapter": aid})
            except KeyError as e:
                self._json(400, {"error": str(e),
                                 "reason": "unknown_adapter"})
            except ValueError as e:
                err = {"error": str(e)}
                reason = error_reason(e)
                err["reason"] = reason or "bad_adapter"
                self._json(400, err)
            except OSError as e:
                self._json(400, {"error": f"adapter load failed: {e}",
                                 "reason": "adapter_io_error"})

        def do_POST(self):
            if self.path in ("/adapters/load", "/adapters/unload"):
                self._do_adapters()
                return
            if self.path in ("/debug/profile", "/debug/profile/stop"):
                self._do_profile()
                return
            if self.path == "/journal/import":
                # migration adopt: the body is a peer's exported frame
                # stream; unfinished requests re-admit here mid-run with
                # their original uids and byte-identical continuations
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    result = scheduler.import_journal_frames(
                        self.rfile.read(n))
                except RuntimeError as e:
                    self._json(409, {"error": str(e)})
                    return
                self._json(200, {"status": "imported", **result})
                return
            if self.path not in ("/generate", "/v1/completions",
                                 "/v1/chat/completions"):
                self._json(404, {"error": "not found"})
                return
            chat = self.path == "/v1/chat/completions"
            openai = chat or self.path == "/v1/completions"
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if openai:
                    # OpenAI completions field names -> native ones
                    if "max_tokens" in body:
                        body.setdefault("max_new_tokens", body["max_tokens"])
                    if isinstance(body.get("prompt"), str):
                        body.setdefault("text", body.pop("prompt"))
                if chat:
                    if body.get("stream"):
                        raise UnsupportedFeature(
                            "streaming chat completions are not supported; "
                            "use /generate with stream for token streaming",
                            reason="streaming_chat_unsupported")
                    msgs = body.get("messages")
                    if not msgs:
                        raise ValueError("chat completions need 'messages'")
                    if tokenizer is None or not hasattr(
                            tokenizer, "apply_chat_template"):
                        raise UnsupportedFeature(
                            "chat completions need a tokenizer with a chat "
                            "template", reason="chat_template_unavailable")
                    try:
                        body["prompt"] = tokenizer.apply_chat_template(
                            msgs, add_generation_prompt=True)
                    except Exception as e:  # noqa: BLE001 — template errors
                        raise ValueError(f"malformed messages: {e}") from e
                prompt = body.get("prompt")
                if prompt is None and "text" in body:
                    if tokenizer is None:
                        raise ValueError("text input needs a tokenizer; "
                                         "pass token ids as 'prompt'")
                    prompt = tokenizer.encode(body["text"])
                if not prompt:
                    raise ValueError("missing 'prompt' (token ids) or 'text'")
                stop = body.get("stop")
                if isinstance(stop, str):
                    stop = [stop]
                if stop and any(isinstance(s, str) for s in stop):
                    if tokenizer is None:
                        raise ValueError("string stop sequences need a "
                                         "tokenizer; pass token ids")
                    from .pipeline import _encode_stop
                    stop = [_encode_stop(tokenizer, s)
                            if isinstance(s, str) else s for s in stop]
                handle = scheduler.submit(
                    prompt,
                    max_new_tokens=int(body.get("max_new_tokens", 32)),
                    temperature=float(body.get("temperature", 0.0)),
                    top_k=int(body.get("top_k", 0)),
                    top_p=float(body.get("top_p", 1.0)),
                    eos_token_id=body.get("eos_token_id"),
                    seed=int(body.get("seed", 0)),
                    stop=stop,
                    min_new_tokens=int(body.get("min_new_tokens", 0)),
                    repetition_penalty=float(
                        body.get("repetition_penalty", 1.0)),
                    speculative=body.get("speculative"),
                    num_draft_tokens=int(body.get("num_draft_tokens", 4)),
                    draft_ngram=int(body.get("draft_ngram", 2)),
                    return_logprobs=bool(body.get("logprobs")),
                    deadline_s=body.get("deadline_s"),
                    queue_ttl_s=body.get("queue_ttl_s"),
                    stream=bool(body.get("stream")),
                    tenant=body.get("tenant"),
                    adapter=body.get("adapter"))
            except SchedulerOverloaded as e:
                self._json(429, {"error": str(e),
                                 "retry_after_s": e.retry_after_s},
                           headers=(("Retry-After",
                                     str(max(1, round(e.retry_after_s)))), ))
                return
            except (ValueError, SchedulingError) as e:
                err = {"error": str(e)}
                reason = error_reason(e)
                if reason:  # machine-readable slug: clients branch on it
                    err["reason"] = reason
                self._json(400, err)
                return
            except RuntimeError as e:
                # stopped / draining / migrating: this replica no longer
                # admits — tell the client (or the router) to go elsewhere
                self._json(503, {"error": str(e)},
                           headers=(("Retry-After", "1"), ))
                return
            if body.get("stream"):
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.send_header("Transfer-Encoding", "chunked")
                # the reconnect key: a dropped client re-attaches at
                # GET /requests/<uid>/stream?from_token=<tokens seen>
                self.send_header("X-DS-Request-Id", str(handle.uid))
                self.end_headers()
                try:
                    for tok in handle.stream(
                            timeout=scheduler.wait_timeout(handle)):
                        line = json.dumps({"token": tok}).encode() + b"\n"
                        self.wfile.write(hex(len(line))[2:].encode()
                                         + b"\r\n" + line + b"\r\n")
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    handle.cancel()
                except (DeadlineExceeded, queue.Empty):
                    # deadline hit mid-stream / scheduler wedged: the
                    # tokens already streamed stand — end the chunk stream
                    # cleanly so the client sees a complete HTTP response
                    handle.cancel()
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        pass
                return
            try:
                # pinned to the request deadline (or http_timeout_s): a
                # hung scheduler answers 504 instead of pinning this HTTP
                # thread forever
                tokens = handle.result(
                    timeout=scheduler.wait_timeout(handle))
            except DeadlineExceeded as e:
                self._json(504, {"error": str(e)})
                return
            except TimeoutError:
                handle.cancel()
                self._json(504, {"error": f"request {handle.uid} did not "
                                          "complete in time"})
                return
            except Exception as e:  # noqa: BLE001 — surfaced to the client
                self._json(500, {"error": str(e)})
                return
            text = tokenizer.decode(tokens) if tokenizer is not None else None
            if openai:
                # OpenAI completions / chat-completions response shapes
                finish = ("length" if len(tokens)
                          >= int(body.get("max_new_tokens", 32)) else "stop")
                choice = {"index": 0, "tokens": tokens,
                          "finish_reason": finish}
                if chat:
                    choice["message"] = {"role": "assistant",
                                         "content": text or ""}
                else:
                    choice["text"] = text if text is not None else ""
                self._json(200, {
                    "id": f"ds-{handle.uid}",
                    "object": ("chat.completion" if chat
                               else "text_completion"),
                    "choices": [choice],
                    "usage": {"completion_tokens": len(tokens)}})
                return
            out = {"uid": handle.uid, "tokens": tokens}
            if body.get("speculative"):
                out["spec"] = handle.stats  # drafted/accepted/accept_rate
            if body.get("logprobs"):
                out["logprobs"] = handle.result_with_logprobs()[1]
            if text is not None:
                out["text"] = text
            self._json(200, out)

    return ThreadingHTTPServer((host, port), Handler)


def install_sigterm_handoff(sched: ServingScheduler, httpd) -> bool:
    """SIGTERM → journal checkpoint + clean handoff: the handler stops the
    scheduler WITHOUT retiring journal entries (``handoff()``) and shuts
    the HTTP server down, so a supervisor relaunch replays every in-flight
    request. Signal handlers only install from the main thread; returns
    whether the handler is in place."""
    import signal
    if threading.current_thread() is not threading.main_thread():
        return False

    def _on_term(signum, frame):
        logger.warning("[serving] SIGTERM: journal handoff + shutdown")
        # shutdown() blocks until serve_forever exits — which runs on THIS
        # thread when blocking — so it must be called from another one
        threading.Thread(target=httpd.shutdown, daemon=True).start()
        sched.handoff()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # non-main interpreter contexts
        return False
    return True


def serve(engine: InferenceEngineV2, host: str = "127.0.0.1", port: int = 8000,
          tokenizer=None, block: bool = True,
          fused_decode_window: Optional[int] = None,
          disagg: Optional[DisaggServing] = None):
    """One-call deployment: start the scheduler + HTTP server (mii.serve)."""
    sched = ServingScheduler(
        engine, fused_decode_window=fused_decode_window,
        disagg=disagg).start()
    httpd = create_http_server(sched, host, port, tokenizer)
    install_sigterm_handoff(sched, httpd)
    if not block:
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return sched, httpd
    try:
        httpd.serve_forever()
    finally:
        sched.stop()
    return sched, httpd
