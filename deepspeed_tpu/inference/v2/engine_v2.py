"""InferenceEngineV2 — ragged continuous-batching serving engine.

Reference: ``deepspeed/inference/v2/engine_v2.py:30 InferenceEngineV2``.
Same contract: ``put(uids, tokens)`` runs one ragged forward returning one
logits row per sequence; ``query``/``can_schedule`` expose the Dynamic
SplitFuse feasibility math to the scheduler (MII-equivalent); ``flush``
drops a sequence's KV. TPU-side, a forward is one jitted program per shape
bucket (see ragged_wrapper) and the KV cache is donated functional state.
"""

import os
import pickle
import time
from dataclasses import dataclass
from typing import Iterable, List, Optional, Tuple

import numpy as np
import jax

from ...models.llama import LlamaConfig, init_llama
from ...observability import get_registry
from ...utils.fault_injection import InjectedFault, get_fault_injector
from .config_v2 import RaggedInferenceEngineConfig
from .model import RaggedLlamaModel
from .ragged.ragged_manager import DSStateManager
from .ragged.ragged_wrapper import RaggedBatchWrapper
from .ragged.sequence_descriptor import PlaceholderSequenceDescriptor
from .scheduling_utils import SchedulingError, SchedulingResult

# Host-boundary timings (process registry, resolved once at import): the
# engine never timestamps device-side work — ``dispatch`` is the async
# enqueue half of a fused wave, ``harvest`` the blocking device_get, and
# ``put`` one whole ragged forward including its fetch.
_obs = get_registry()
_put_seconds = _obs.histogram(
    "ds_engine_put_seconds", "One ragged forward (put), dispatch + fetch")
_dispatch_seconds = _obs.histogram(
    "ds_engine_dispatch_seconds",
    "Async enqueue of a fused wave (begin half, no fetch)")
_harvest_seconds = _obs.histogram(
    "ds_engine_harvest_seconds",
    "Blocking fetch of a dispatched fused wave (device_get)")
_dispatches_total = _obs.counter(
    "ds_engine_dispatches_total", "Fused wave dispatches (plain + spec)")
_harvests_total = _obs.counter(
    "ds_engine_harvests_total", "Fused wave harvests (plain + spec)")
# prefix-cache effectiveness, previously visible only as host-side
# descriptor attrs: one hit per new sequence that adopted a cached
# prefix, plus the block count it skipped recomputing
_prefix_hits = _obs.counter(
    "ds_prefix_cache_hits_total",
    "New sequences that adopted a cached full-block prefix")
_prefix_adopted_blocks = _obs.counter(
    "ds_prefix_adopted_blocks_total",
    "KV blocks adopted from the prefix cache (prefill skipped)")
_prefix_saved_tokens = _obs.counter(
    "ds_prefix_saved_prefill_tokens_total",
    "Prompt tokens kept out of prefill by prefix adoption + COW forks "
    "(mirrors PrefixKVCache.stats['saved_tokens'] exactly)")
_prefix_cow_forks = _obs.counter(
    "ds_prefix_cow_forks_total",
    "Mid-block prompt divergences resolved by a copy-on-write block fork")


@dataclass
class SampleSpec:
    """Per-sequence sampling parameters for the ON-DEVICE sampler
    (ops/sampling) — the host-side description one row of a batched
    ``sample_rows`` dispatch or one lane of a sampled fused-decode scan is
    built from. ``history`` (prompt + outputs) is only consulted when
    ``repetition_penalty != 1`` (it becomes the [vocab] presence mask);
    ``block_eos`` is the per-token path's precomputed min_new gate, while
    the fused path derives it in-trace from ``n_out``/``min_new`` per scan
    step. ``seed`` initializes the sequence's PRNG key on first use."""
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    eos_token_id: Optional[int] = None
    block_eos: bool = False
    history: Optional[List[int]] = None
    seed: int = 0
    want_logprobs: bool = False
    n_out: int = 0
    min_new: int = 0


@dataclass
class _InFlightWave:
    """A dispatched-but-unfetched fused decode program: the device is
    running (or queued to run) the K-step scan while the scheduler's
    overlap window feeds prefill chunks; ``out``/``lps``/``new_keys`` are
    lazy jax arrays until :meth:`InferenceEngineV2.fused_decode_harvest`
    blocks on them. Sequences' host bookkeeping was already advanced at
    dispatch (plain waves grow deterministically by ``n_steps``)."""
    uids: list
    seqs: list
    tokens: "np.ndarray"   # [S] input tokens (padded row order)
    out: object            # lazy [n_steps, S] device tokens
    lps: object            # lazy [n_steps, S] logprobs (sampled waves)
    new_keys: object       # lazy [S, 2] advanced PRNG keys (sampled waves)
    n_steps: int
    sampled: bool


@dataclass
class _InFlightSpecWave:
    """Speculative sibling of :class:`_InFlightWave`. Host bookkeeping is
    wholly deferred to harvest — how far each sequence advanced is itself
    a device result (the accepted counts)."""
    uids: list
    seqs: list
    tokens: "np.ndarray"
    out: object            # lazy [n_steps, S, 1+d] emitted tokens
    n_emit: object         # lazy [n_steps, S] per-window emit counts
    dlen: object           # lazy [n_steps, S] per-window draft lengths
    new_keys: object       # lazy [S, 2] advanced keys (None when greedy)
    n_steps: int


_FF_KEY = None


def _fast_forward_key(key, n: int):
    """Advance a PRNG key by ``n`` chain burns: iterated ``split(key, 2)[0]``
    — the exact per-dispatch advance of ``sample_core`` and
    ``spec_verify_window``. Jitted once (dynamic trip count) so replaying a
    long stream costs one dispatch, not ``n``."""
    global _FF_KEY
    if _FF_KEY is None:
        _FF_KEY = jax.jit(lambda k, m: jax.lax.fori_loop(
            0, m, lambda i, kk: jax.random.split(kk, 2)[0], k))
    return _FF_KEY(key, np.int32(n))


def _fire_request_poison(uids) -> None:
    """``serve.request_poison`` fault site: a configured request uid makes
    ANY device dispatch whose batch contains it raise — per-token put,
    windowed verify, and fused scan alike — the deterministic stand-in for
    "this request's shape/content wedges the engine". The raise happens
    before any engine state mutates, so co-batched sequences stay intact.
    Inert (not even visit-counted) unless a fault plan is installed."""
    inj = get_fault_injector()
    if not inj.enabled:
        return
    uids = list(uids)
    args = inj.fire("serve.request_poison", uids=uids)
    if args is not None:
        uid = args.get("uid")
        if uid is None or uid in uids:
            raise InjectedFault(f"injected poison in request {uid}")


class InferenceEngineV2:

    def __init__(self, model: RaggedLlamaModel, engine_config: RaggedInferenceEngineConfig):
        self._config = engine_config
        self._model = model

        kv_config = model.kv_cache_config()
        self._batch = RaggedBatchWrapper(engine_config.state_manager,
                                         block_size=kv_config.block_size)
        prefix_caching = engine_config.enable_prefix_caching
        self._prefix_disable_reason = None if prefix_caching else "not_enabled"
        if prefix_caching and getattr(model.config, "sliding_window", None):
            from ...utils.logging import logger
            logger.warning("prefix caching disabled: sliding-window models "
                           "release trailing KV blocks mid-sequence, which "
                           "would free shared prefix blocks")
            prefix_caching = False
            self._prefix_disable_reason = "sliding_window_model"
        self._state_manager = DSStateManager(engine_config.state_manager, kv_config,
                                             num_blocks=engine_config.num_kv_blocks,
                                             enable_prefix_caching=prefix_caching)
        self._model.set_state_manager(self._state_manager)
        # per-sequence PRNG key state for the on-device sampler — lives
        # next to the KV cache in lifecycle terms (seeded lazily at first
        # sample, advanced one split per generated token, dropped on
        # flush). Kept as host uint32[2] rows; each dispatch carries the
        # batch's keys in and the advanced keys out.
        self._sample_keys = {}
        # Multi-LoRA: the adapter registry attached to the model (None =
        # adapter-free engine). Slot assignment is per-uid and lives in the
        # registry's pin table; KV and scheduling accounting never see it.
        self._adapters = getattr(model, "_adapters", None)

    # ---- multi-LoRA (inference/v2/adapters) ----

    @property
    def adapters(self):
        """The attached :class:`AdapterRegistry`, or None."""
        return self._adapters

    def set_request_adapter(self, uid: int, name_or_id: str) -> int:
        """Pin ``uid`` to an adapter for its lifetime (resolve + device
        slot + pin; released by :meth:`flush`). Returns the slot. Raises
        KeyError (unknown adapter) or AdapterSlotsExhausted."""
        if self._adapters is None:
            raise RuntimeError("engine built without an adapter registry "
                               "(adapters.enabled is off)")
        return self._adapters.pin(uid, name_or_id)

    def _adapter_slot_rows(self, batch_uids, n_rows: int):
        """Bucketed per-row slot array for one dispatch (None when the
        engine is adapter-free — the model then omits the bank operand).
        Padding rows carry slot 0: the identity adapter's zero factors
        make them an exact no-op."""
        if self._adapters is None:
            return None
        slots = np.zeros(n_rows, np.int32)
        for i, u in enumerate(batch_uids):
            slots[i] = self._adapters.slot_for_uid(u)
        return slots

    # ---- properties (reference engine_v2.py:47-66) ----

    @property
    def free_blocks(self) -> int:
        return self._state_manager.free_blocks

    @property
    def n_kv_cache_groups(self) -> int:
        return 1

    def model(self) -> RaggedLlamaModel:
        return self._model

    def prefix_cache_report(self) -> dict:
        """State + effectiveness of the radix prefix cache for /health,
        env_report and the bench cross-check: ``state`` is enabled/disabled
        with a machine-readable ``reason`` when disabled (e.g. a
        sliding-window model makes shared blocks unsafe to retain)."""
        pc = self._state_manager.prefix_cache
        if pc is None:
            return {"state": "disabled",
                    "reason": self._prefix_disable_reason or "not_enabled"}
        rep = pc.report()
        rep["state"] = "enabled"
        return rep

    # ---- serving (reference :107 put) ----

    def put(self, batch_uids: Iterable[int], batch_tokens: Iterable, do_checks: bool = True,
            window_logits: bool = False, defer_register=frozenset(),
            adopt_prefix: bool = True):
        """One ragged forward; returns logits [n_seqs_padded, vocab] — row i is
        the next-token distribution for batch_uids[i].

        ``window_logits``: return [n_seqs_padded, N, vocab] logits at EVERY
        fed token instead (speculative verification); trailing-window KV
        frees are deferred to the caller (who frees after rollback, when
        ``seen_tokens`` is truthful again). ``defer_register``: uids whose
        feed contains draft tokens — their prefix-cache registration is
        deferred until the caller has rolled back rejections (a rejected
        chain must never enter the cache; its blocks are overwritten in
        place)."""
        batch_uids = list(batch_uids)
        _fire_request_poison(batch_uids)
        batch_tokens = [np.asarray(t, dtype=np.int32).reshape(-1) for t in batch_tokens]

        if do_checks:
            token_lens = [t.size for t in batch_tokens]
            schedule_check = self.can_schedule(batch_uids, token_lens)
            if schedule_check != SchedulingResult.Success:
                raise SchedulingError(schedule_check)

        pc = self._state_manager.prefix_cache
        self._batch.clear()
        for i, (uid, tokens) in enumerate(zip(batch_uids, batch_tokens)):
            host_seq_desc = self._state_manager.get_sequence(uid)
            if (pc is not None and adopt_prefix and host_seq_desc is None
                    and tokens.size > 1):
                # NEW sequence: adopt the longest cached full-block prefix —
                # its KV already exists, so only the suffix is fed/computed.
                # At least one token must stay fed (logits come from it).
                matched, chain_key, fork = pc.match_fork(tokens[:tokens.size - 1])
                dst = None
                if fork is not None:
                    # mid-block divergence: COW-copy the fork source so the
                    # shared page stays read-only and this sequence writes
                    # its tail into a PRIVATE block. The transient pin taken
                    # by match_fork keeps the source alive even while it is
                    # an eviction candidate; dropped once the copy is in the
                    # device stream (later reuse of the source block orders
                    # after the copy program).
                    _src_key, src_block, fork_p = fork
                    try:
                        dst = self._state_manager.allocate_blocks(1)
                    except SchedulingError:
                        pc.release([src_block])  # abort fork: pool exhausted
                        fork = None
                    else:
                        self._model.cow_copy_block(src_block, int(dst[0]))
                        pc.commit_fork(fork_p)
                        pc.release([src_block])
                if matched or fork is not None:
                    _prefix_hits.inc()
                    _prefix_adopted_blocks.inc(len(matched))
                    host_seq_desc = self._state_manager.get_or_create_sequence(uid)
                    if matched:
                        host_seq_desc.extend_kv_cache(matched)
                    host_seq_desc.adopted_blocks = set(matched)
                    host_seq_desc.chain_key = chain_key
                    host_seq_desc.chain_blocks = len(matched)
                    skip = len(matched) * self._state_manager.block_size
                    if fork is not None:
                        _prefix_cow_forks.inc()
                        host_seq_desc.extend_kv_cache(dst)  # private COW block
                        # the forked run must reach the cache when this block
                        # completes: stage it ahead of the fed suffix so
                        # _register_pending sees the block's true contents
                        host_seq_desc.pending_tokens = np.asarray(
                            tokens[skip:skip + fork_p], np.int32)
                        skip += fork_p
                    _prefix_saved_tokens.inc(skip)
                    host_seq_desc.pre_forward(skip)
                    host_seq_desc.post_forward()  # history = cached prefix
                    tokens = tokens[skip:]
            if host_seq_desc is None:
                host_seq_desc = self._state_manager.get_or_create_sequence(uid)
            if pc is not None:
                # stage fed tokens for block registration post-forward; only
                # the sub-block tail is ever retained (O(block) per step,
                # not O(history))
                self._append_pending(host_seq_desc, tokens)
            batch_tokens[i] = tokens
            self._model.maybe_allocate_kv(host_seq_desc, tokens.size)
            host_seq_desc.pre_forward(tokens.size)
            self._batch.insert_sequence(host_seq_desc, tokens, do_checks=do_checks)

        batch = self._batch.finalize(
            total_slots=self._state_manager.kv_cache.num_blocks *
            self._state_manager.kv_cache.block_size)
        t0 = time.monotonic()
        logits = self._model.forward(
            batch, window_logits=window_logits,
            adapter_slots=self._adapter_slot_rows(
                batch_uids, batch.q_tok_idx.shape[0]))
        _put_seconds.record(time.monotonic() - t0)

        for uid in batch_uids:
            seq = self._state_manager.get_sequence(uid)
            seq.post_forward()
            # sequences whose feed carried draft tokens defer registration:
            # the caller rolls back rejections (history AND pending) and
            # then calls _register_pending itself
            if pc is not None and uid not in defer_register:
                self._register_pending(seq)
            if not window_logits:
                # draft steps also defer the trailing-window KV free: seen
                # is inflated by unverified drafts here, and a block freed
                # against the inflated window could still be needed after
                # rollback (free is irreversible — the caller frees once
                # seen is truthful)
                self._model.maybe_free_kv(seq)
        return logits

    def score(self, batch_uids: Iterable[int], batch_tokens: Iterable,
              flush: bool = True):
        """Teacher-forced log-probabilities (the MII/RLHF scoring surface):
        for each NEW sequence, returns an array of length ``len(tokens)-1``
        with ``log p(tokens[j+1] | tokens[:j+1])`` — one ragged forward via
        window logits, no decode loop. ``flush=True`` releases the scoring
        KV afterwards (set False to continue decoding from the scored
        prefix with ``put``)."""
        batch_uids = list(batch_uids)
        batch_tokens = [np.asarray(t, dtype=np.int32).reshape(-1)
                        for t in batch_tokens]
        for uid in batch_uids:
            if self._state_manager.get_sequence(uid) is not None:
                raise ValueError(
                    f"score() expects NEW sequences (uid {uid} is live): "
                    "the first fed token's score would need the previous "
                    "step's logits")
        # adoption would skip prefill for cached prefixes — but scoring
        # needs logits at EVERY position, so every token must be fed
        logits = np.asarray(self.put(batch_uids, batch_tokens,
                                     window_logits=True, adopt_prefix=False))
        out = []
        for i, toks in enumerate(batch_tokens):
            rows = logits[i, :toks.size - 1].astype(np.float64)  # [T-1, V]
            logz = np.log(np.exp(rows - rows.max(-1, keepdims=True))
                          .sum(-1)) + rows.max(-1)
            out.append(rows[np.arange(toks.size - 1), toks[1:]] - logz)
        if flush:
            for uid in batch_uids:
                self.flush(uid)
        return out

    def fused_window(self, uids, output_budgets, cap: int) -> int:
        """Largest power-of-two K <= ``cap`` that EVERY sequence can absorb
        (remaining output budget and context room); < 2 means the per-step
        path should run. The power-of-two snap bounds fused-program
        compiles at O(log cap) per bucket. Whole-batch predicate — callers
        that can split a mixed-progress wave use :meth:`fused_partition`
        instead, so one near-budget request doesn't demote the rest."""
        sm = self._config.state_manager
        K = min(cap, min(output_budgets),
                min(sm.max_context
                    - self._state_manager.get_sequence(u).seen_tokens
                    for u in uids))
        while K >= 2 and K & (K - 1):
            K &= K - 1
        return K

    def fused_partition(self, uids, output_budgets, cap: int):
        """Split a decode wave into ``(fusable, K, solo)`` so one
        near-budget request can't demote the WHOLE batch off fused
        dispatch: ``fusable`` keeps every sequence with >= 2 tokens of room
        (output budget AND context), ``K`` is the largest power-of-two
        window <= ``cap`` they can ALL absorb, and ``solo`` holds the
        constrained sequences that must tick per-step — they are within a
        token of retiring, so the caller advances them alone for the one
        or two steps they have left. Shared by generate() and the serving
        daemon's fused tick."""
        sm = self._config.state_manager
        room = {u: min(b, sm.max_context
                       - self._state_manager.get_sequence(u).seen_tokens)
                for u, b in zip(uids, output_budgets)}
        fusable = [u for u in uids if room[u] >= 2]
        solo = [u for u in uids if room[u] < 2]
        if not fusable:
            return [], 0, solo
        K = min(cap, min(room[u] for u in fusable))
        while K >= 2 and K & (K - 1):
            K &= K - 1
        if K < 2:  # cap itself forbids fusing — everything ticks per-step
            return [], 0, uids
        return fusable, K, solo

    def fused_spec_partition(self, uids, output_budgets, draft_tokens: int,
                             cap: int):
        """Speculative analog of :meth:`fused_partition`: each fused window
        can write up to ``1 + draft_tokens`` KV positions (worst case all
        drafts accepted), so a row's window room is its CONTEXT headroom
        divided by the window width, while the output-budget bound stays
        per-window (each window emits at least one token; overshoot past
        the budget is trimmed at retirement like the plain fused path).
        Returns ``(fusable, K, solo)`` with K the largest power-of-two
        window count every fusable row can absorb."""
        sm = self._config.state_manager
        w = 1 + max(1, int(draft_tokens))
        room = {}
        for u, b in zip(uids, output_budgets):
            ctx = sm.max_context \
                - self._state_manager.get_sequence(u).seen_tokens
            room[u] = min(b, ctx // w)
        fusable = [u for u in uids if room[u] >= 2]
        solo = [u for u in uids if room[u] < 2]
        if not fusable:
            return [], 0, solo
        K = min(cap, min(room[u] for u in fusable))
        while K >= 2 and K & (K - 1):
            K &= K - 1
        if K < 2:
            return [], 0, uids
        return fusable, K, solo

    def decode_finished(self, uid, outputs, max_new_tokens,
                        eos_token_id, stop) -> bool:
        """The ONE retire predicate: output budget spent, eos emitted, a
        stop sequence hit, or the context ceiling reached (retiring before
        the next decode put would raise for the whole batch). Shared by
        generate()'s retirement scan, both fused paths, and the daemon."""
        seq = self._state_manager.get_sequence(uid)
        return (len(outputs) >= max_new_tokens
                or (eos_token_id is not None and outputs
                    and outputs[-1] == eos_token_id)
                or (bool(stop) and self.hit_stop(outputs, stop))
                or seq.seen_tokens + 1 > self._config.state_manager.max_context)

    @staticmethod
    def _append_pending(seq, tokens) -> None:
        """Stage fed tokens on the descriptor for prefix-cache registration
        (shared by put() and fused_decode_steps)."""
        pend = getattr(seq, "pending_tokens", None)
        if pend is None:
            pend = np.zeros(0, np.int32)
        seq.pending_tokens = np.concatenate(
            [pend, np.asarray(tokens, np.int32)])

    def _register_pending(self, seq) -> None:
        """Register the sequence's newly completed full KV blocks with the
        prefix cache as a chain continuation — each block is hashed exactly
        once over the sequence's lifetime (O(block) per step)."""
        pc = self._state_manager.prefix_cache
        if pc is None:
            return
        bs = self._state_manager.block_size
        full = len(seq.pending_tokens) // bs
        if full:
            start = getattr(seq, "chain_blocks", 0)
            seq.chain_key, _ = pc.register_from(
                getattr(seq, "chain_key", None),
                seq.pending_tokens[:full * bs],
                seq.kv_blocks[start:start + full])
            seq.chain_blocks = start + full
            seq.pending_tokens = seq.pending_tokens[full * bs:]

    # ---- scheduling feasibility (reference :158 query / :184 can_schedule) ----

    def query(self, uid: int, max_request_tokens: int, max_request_blocks: int) -> Tuple[int, int]:
        seq_desc = self._state_manager.get_sequence(uid)
        if seq_desc is None:
            if self._state_manager.n_tracked_sequences >= \
                    self._config.state_manager.max_tracked_sequences:
                return (0, 0)
            seq_desc = PlaceholderSequenceDescriptor()
        return self._model.get_kv_requirements(seq_desc, max_request_tokens, max_request_blocks)

    def can_schedule(self, uids: Iterable[int], lengths: Iterable[int]) -> SchedulingResult:
        uids, lengths = list(uids), list(lengths)
        cur_seqs = self._state_manager.n_tracked_sequences
        free_blocks = self._state_manager.free_blocks
        batch_len = 0

        if len(uids) > self._config.state_manager.max_ragged_sequence_count:
            return SchedulingResult.BatchSequenceLimitExceeded

        for uid, length in zip(uids, lengths):
            seq_desc = self._state_manager.get_sequence(uid)
            if seq_desc is None:
                cur_seqs += 1
                seq_desc = PlaceholderSequenceDescriptor()
            if seq_desc.seen_tokens + length > self._config.state_manager.max_context:
                return SchedulingResult.SequenceTokenLimitExceeded
            sched_len, sched_blocks = self._model.get_kv_requirements(seq_desc, length, free_blocks)
            if sched_len != length:
                return SchedulingResult.KVCacheLimitExceeded
            batch_len += length
            free_blocks -= sched_blocks

        if cur_seqs > self._config.state_manager.max_tracked_sequences:
            return SchedulingResult.EngineSequenceLimitExceeded
        if batch_len > self._config.state_manager.max_ragged_batch_size:
            return SchedulingResult.BatchTokenLimitExceeded
        return SchedulingResult.Success

    def get_remaining_block_capacity(self, uid: int) -> int:
        seq_desc = self._state_manager.get_sequence(uid)
        if seq_desc is None:
            return 0
        return self._model.get_remaining_block_capacity(seq_desc)

    def warmup(self, prefill_lens=(128, ), batch_sizes=(1, ),
               draft_tokens: int = 0, fused_windows=(),
               fused_sampled_windows=(), fused_spec_windows=(),
               spec_draft_tokens: int = 4, spec_draft_ngram: int = 2,
               decode_context: int = 0) -> int:
        """Precompile the bucketed forward programs serving will hit, so the
        first real request doesn't pay compile latency (the reference's
        CUDA-graph warmup analog). Runs scratch sequences through put() —
        prefill at each length, plus the decode (1-token) program at each
        concurrent batch size — then flushes them. ``draft_tokens``: also
        warm the window-logits verify program speculative decoding uses
        (1 + draft_tokens fed tokens). ``fused_windows``: K values whose
        fused multi-step decode program (fused_decode_steps) should compile
        per batch size — the serving daemon's steady-state tick.
        ``decode_context``: prefill the batched scratch sequences to this
        length first so the decode/fused programs compile at the production
        BLOCK-TABLE bucket — the compile key includes the block bucket B,
        and a 1-token scratch sequence (B=1) would warm a program the
        ctx-length traffic never hits. Returns the number of compiled
        programs cached."""
        base = 1 << 28  # scratch uid space clear of real uids
        for n in prefill_lens:
            uid = base
            # adopt_prefix=False + defer_register: warmup must neither adopt
            # cached blocks (an earlier warmup prefill would shrink this
            # bucket's fed-token count, leaving the real bucket uncompiled)
            # nor pollute the prefix cache with zero-token entries
            self.put([uid], [np.zeros(int(n), np.int32)], do_checks=False,
                     adopt_prefix=False, defer_register={uid})
            self.put([uid], [[0]], defer_register={uid})  # decode bucket
            if draft_tokens:
                self.put([uid], [[0] * (1 + draft_tokens)],
                         window_logits=True, defer_register={uid})
                seq = self._state_manager.get_sequence(uid)
                seq.rollback(draft_tokens)
            self._scrub_pending(uid)
            self.flush(uid)
        for bs in batch_sizes:
            uids = list(range(base + 1, base + 1 + bs))
            scratch = frozenset(uids)
            for u in uids:
                feed = np.zeros(max(1, int(decode_context)), np.int32)
                self.put([u], [feed], do_checks=False, adopt_prefix=False,
                         defer_register=scratch)
            self.put(uids, [[0]] * bs,  # batched decode bucket
                     defer_register=scratch)
            for K in fused_windows:
                self.fused_decode_steps(uids, [0] * bs, int(K))
            for K in fused_sampled_windows:
                # warm the SAMPLED scan program (logprobs on — the superset
                # compile the serving daemon's mixed waves hit)
                self.fused_decode_steps(
                    uids, [0] * bs, int(K),
                    specs=[SampleSpec(temperature=1.0, want_logprobs=True)
                           for _ in uids])
            for K in fused_spec_windows:
                # warm the fused speculative programs (greedy + sampled):
                # the scratch sequences' zero-token histories draft real
                # windows (every ngram matches), so the compiled shapes are
                # exactly the production ones
                hists = [[0] * (self._state_manager.get_sequence(u)
                                .seen_tokens + 1) for u in uids]
                self.fused_spec_decode_steps(
                    uids, hists, int(K),
                    num_draft_tokens=spec_draft_tokens,
                    draft_ngram=spec_draft_ngram)
                self.fused_spec_decode_steps(
                    uids, hists, int(K),
                    num_draft_tokens=spec_draft_tokens,
                    draft_ngram=spec_draft_ngram,
                    specs=[SampleSpec(temperature=1.0) for _ in uids])
            for u in uids:
                self._scrub_pending(u)
                self.flush(u)
        return len(self._model._fwd_cache)

    def _scrub_pending(self, uid) -> None:
        """Drop a scratch sequence's staged registration tail: warmup
        sequences feed zeros, and letting flush register that tail would
        seed the radix cache with entries real zero-prefixed traffic could
        adopt (warmup must stay invisible to the cache)."""
        seq = self._state_manager.get_sequence(uid)
        if seq is not None:
            seq.pending_tokens = np.zeros(0, np.int32)

    # ---- convenience decode loop (the MII surface over FastGen) ----

    @staticmethod
    def _sample_with_logprob(row: np.ndarray, temperature: float, rng,
                             top_k: int = 0, top_p: float = 1.0,
                             want_lp: bool = True) -> Tuple[int, float]:
        """Returns (token, logprob-of-token) under the temperature-scaled,
        top-k/top-p-filtered distribution (MII returns logprobs; greedy
        logprobs come from the raw softmax). ``want_lp=False`` skips the
        O(vocab) softmax pass — the default generate() path pays nothing
        for the logprob surface it isn't using."""

        def lp_at(logits, tok):
            if not want_lp:
                return 0.0
            # exp(-inf - m) is 0, so this is also correct on FILTERED
            # logits (the renormalized nucleus/top-k distribution)
            m = np.max(logits)
            return float(logits[tok] - m
                         - np.log(np.sum(np.exp(logits - m))))

        raw = row.astype(np.float64)
        if temperature <= 0:
            tok = int(np.argmax(raw))
            return tok, lp_at(raw, tok)
        logits = raw / temperature
        if top_k > 0 and top_k < logits.size:  # <=0 = disabled (vLLM style)
            kth = np.partition(logits, -top_k)[-top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        if 0.0 < top_p < 1.0:
            # nucleus: keep the smallest set of tokens whose softmax mass
            # reaches top_p (the highest-prob token always survives:
            # cumsum(p)-p < top_p is True at the first position for any
            # positive top_p)
            order = np.argsort(logits)[::-1]
            p = np.exp(logits[order] - np.max(logits))
            p = p / p.sum()
            keep = np.cumsum(p) - p < top_p
            drop = np.ones_like(logits, dtype=bool)
            drop[order[keep]] = False
            logits = np.where(drop, -np.inf, logits)
        elif top_p <= 0.0:
            tok = int(np.argmax(logits))  # degenerate nucleus = greedy
            return tok, lp_at(logits, tok)
        # Gumbel-max: argmax(logits + G) ~ softmax(logits) sample
        # (-inf + G stays -inf, so filtered tokens can never win)
        g = rng.gumbel(size=logits.shape)
        tok = int(np.argmax(logits + g))
        return tok, lp_at(logits, tok)

    @classmethod
    def _sample(cls, row: np.ndarray, temperature: float, rng,
                top_k: int = 0, top_p: float = 1.0) -> int:
        return cls._sample_with_logprob(row, temperature, rng, top_k, top_p,
                                        want_lp=False)[0]

    # ---- on-device sampling (ops/sampling; numpy above stays the oracle) ----

    def seed_sampler(self, uid: int, seed: int = 0, key=None) -> None:
        """(Re)initialize a sequence's device PRNG key. The key stream is a
        pure function of the initial key, so the per-token and fused paths
        replay identical streams from the same seed."""
        if key is None:
            key = jax.random.PRNGKey(int(seed))
        self._sample_keys[uid] = np.asarray(key, np.uint32)

    def _sampler_key(self, uid: int, seed: int) -> np.ndarray:
        k = self._sample_keys.get(uid)
        if k is None:
            self.seed_sampler(uid, seed)
            k = self._sample_keys[uid]
        return k

    def fast_forward_sampler(self, uid: int, seed: int, burns: int) -> None:
        """Recreate a sequence's device PRNG key at chain position ``burns``:
        the state after that many counted key burns (one per sampled
        per-token dispatch, one per verified speculative window, one per
        fused scan step). Every sampling path advances keys the same way —
        ``split(key, 2)[0]`` — so iterating that split from ``PRNGKey(seed)``
        lands exactly where an uninterrupted run would be, and a replayed
        request's stream continues bit-identically (journal warm restart,
        eviction re-admission)."""
        key = jax.random.PRNGKey(int(seed))
        n = int(burns)
        if n > 0:
            key = _fast_forward_key(key, n)
        self.seed_sampler(uid, key=key)

    def spec_ring_window(self, num_draft_tokens: int) -> int:
        """Effective token-history window for prompt-lookup drafting. The
        device ring must hold at least one full speculative window plus a
        matchable pattern, so tiny ``spec_history_window`` configs get
        widened — the host fallback scan uses the SAME bound so both sides
        see (and miss) exactly the same matches."""
        scfg = getattr(self._config, "sampling", None)
        d = max(1, int(num_draft_tokens))
        max_ngram = int(scfg.spec_max_ngram) if scfg is not None else 8
        base = int(scfg.spec_history_window) if scfg is not None else 128
        return max(base, 2 * (1 + d) + max_ngram)

    @staticmethod
    def _spec_statics(specs):
        """Static compile flags a batch of SampleSpecs resolves to — part
        of the jit cache key, so an all-plain wave never pays for controls
        it doesn't use."""
        use_pen = any(s.repetition_penalty != 1.0 for s in specs)
        use_eos = any(s.eos_token_id is not None
                      and (s.block_eos or s.min_new > s.n_out)
                      for s in specs)
        want_lp = any(s.want_logprobs for s in specs)
        return use_pen, use_eos, want_lp

    def _spec_arrays(self, batch_uids, specs, S, V, use_pen):
        """Bucketed per-row control arrays shared by ``sample_rows`` and
        the sampled fused path. Padding rows are inert (temperature 0,
        penalty 1, no eos)."""
        temps = np.zeros(S, np.float32)
        top_ks = np.zeros(S, np.int32)
        top_ps = np.ones(S, np.float32)
        pens = np.ones(S, np.float32)
        eos = np.full(S, -1, np.int32)
        keys = np.zeros((S, 2), np.uint32)
        mask = np.zeros((S, V), bool) if use_pen else None
        for i, (u, s) in enumerate(zip(batch_uids, specs)):
            temps[i] = s.temperature
            top_ks[i] = s.top_k
            top_ps[i] = s.top_p
            pens[i] = s.repetition_penalty
            if s.eos_token_id is not None:
                eos[i] = int(s.eos_token_id)
            keys[i] = self._sampler_key(u, s.seed)
            if use_pen and s.repetition_penalty != 1.0 and s.history:
                mask[i, np.asarray(s.history, np.int64)] = True
        return temps, top_ks, top_ps, pens, eos, keys, mask

    def sample_rows(self, batch_uids, rows, specs):
        """ONE batched on-device sampling dispatch for logits rows fetched
        by a per-token tick: logit controls → temperature/top-k/top-p
        Gumbel-max → selected-token logprob, identical op-for-op to the
        fused scan's in-trace sampler, so a request keeps a bit-identical
        token stream when the scheduler moves it between paths. Advances
        each sequence's PRNG key by one split. Returns ``(tokens, logprobs)``
        lists of length ``len(batch_uids)``."""
        from ...ops import sampling as dsamp
        from .ragged.ragged_wrapper import _bucket
        batch_uids = list(batch_uids)
        rows = [np.asarray(r, np.float32).reshape(-1) for r in rows]
        n, V = len(batch_uids), rows[0].size
        S = _bucket(n, floor=1)
        use_pen, use_eos, want_lp = self._spec_statics(specs)
        temps, top_ks, top_ps, pens, eos, keys, mask = self._spec_arrays(
            batch_uids, specs, S, V, use_pen)
        blk = np.zeros(S, bool)
        logits = np.zeros((S, V), np.float32)
        for i, (row, s) in enumerate(zip(rows, specs)):
            logits[i] = row
            blk[i] = s.block_eos
        toks, lps, new_keys = dsamp.sample_step(
            logits, keys, temps, top_ks, top_ps, mask, pens, eos, blk,
            want_logprobs=want_lp, use_penalty=use_pen,
            use_eos_mask=use_eos)
        toks, lps, new_keys = jax.device_get((toks, lps, new_keys))
        for i, u in enumerate(batch_uids):
            self._sample_keys[u] = np.asarray(new_keys[i], np.uint32)
        return ([int(t) for t in toks[:n]], [float(l) for l in lps[:n]])

    @staticmethod
    def process_logits(row, history, *, repetition_penalty: float = 1.0,
                       eos_token_id=None, block_eos: bool = False,
                       logits_processor=None):
        """Pre-sampling logit controls (HF-generate parity for serving):
        CTRL-style repetition penalty over the full history, eos masking
        until ``min_new_tokens``, then an arbitrary user processor.
        Returns ``row`` itself when every control is off."""
        if (repetition_penalty == 1.0 and not block_eos
                and logits_processor is None):
            return row
        row = np.array(row, np.float32, copy=True)
        if repetition_penalty != 1.0:
            idx = np.unique(np.asarray(history, np.int64))
            vals = row[idx]
            row[idx] = np.where(vals > 0, vals / repetition_penalty,
                                vals * repetition_penalty)
        if block_eos and eos_token_id is not None:
            row[int(eos_token_id)] = -np.inf  # filtered tokens never win
        if logits_processor is not None:
            row = np.asarray(logits_processor(history, row), np.float32)
        return row

    @staticmethod
    def prompt_lookup_draft(history, *, draft_ngram: int, max_tokens: int,
                            match_window: int = 0, match_cache=None):
        """Prompt-lookup drafting (Saxena): propose the tokens that
        followed the most recent earlier occurrence of the trailing
        n-gram. No draft model — the history IS the drafter.

        The backward scan is bounded two ways (it used to rescan the FULL
        history every generated token — O(history × draft) per step):
        ``match_window`` > 0 restricts candidates to the trailing window
        (the device ring buffer's twin — same window, same drafts), and
        ``match_cache`` (a per-request dict) remembers the last match
        position: the most recent occurrence can only move FORWARD, so a
        still-valid cached match floors the scan and the per-token cost
        drops to O(new_tokens_since_last_match × ngram)."""
        if max_tokens <= 0 or len(history) <= draft_ngram:
            return []
        pat = history[-draft_ngram:]
        # the window bound matches the device ring's retention exactly
        # (candidate start within the trailing W tokens), so host and
        # fused drafting agree token-for-token inside the window
        lo = max(0, len(history) - match_window) if match_window > 0 else 0
        if match_cache is not None:
            p = match_cache.get("pos")
            if (p is not None and lo <= p <= len(history) - draft_ngram - 1
                    and history[p:p + draft_ngram] == pat):
                lo = p  # a match exists here; nothing older can win
        for s in range(len(history) - draft_ngram - 1, lo - 1, -1):
            if history[s:s + draft_ngram] == pat:
                if match_cache is not None:
                    match_cache["pos"] = s
                return [int(t) for t in
                        history[s + draft_ngram:s + draft_ngram + max_tokens]]
        return []

    def accept_drafts(self, uid: int, draft, window_row):
        """Greedy draft verification against one sequence's window logits
        (``[N, vocab]``, rows 0..len(draft) valid): accept the longest
        agreeing prefix plus the correction/bonus token, roll the rejected
        tail back in place (KV + prefix-cache pending tokens), and resume
        the deferred chain registration. Returns (new_tokens, n_accepted).
        Shared by ``generate()`` and the serving daemon — ONE copy of the
        rollback protocol."""
        k = len(draft)
        new_toks, m = [], 0
        for j in range(k + 1):
            t = int(window_row[j].argmax())
            if j < k and draft[j] == t:
                new_toks.append(t)
                m += 1
                continue
            new_toks.append(t)
            break
        seq = self._state_manager.get_sequence(uid)
        rejected = k - m
        if rejected:
            seq.rollback(rejected)
            if self._state_manager.prefix_cache is not None:
                seq.pending_tokens = \
                    seq.pending_tokens[:len(seq.pending_tokens) - rejected]
        if k:
            # deferred registration now that seen is truthful
            self._register_pending(seq)
        return new_toks, m

    def accept_drafts_sampled(self, uid: int, draft, window_rows, spec,
                              d_static: int):
        """Rejection-sampling draft verification for SAMPLED speculative
        requests — the host twin (and parity oracle) of one window of the
        fused speculative program. Runs the exact same op chain
        (``ops/sampling.spec_verify_window``) on this one row: accept each
        point-mass draft with the target probability of its token under
        the temperature/top-k/top-p distribution, sample the correction
        from the residual (or the bonus from the full distribution), and
        advance the sequence's PRNG key by exactly one split. ``d_static``
        must be the request's ``num_draft_tokens`` — the window's
        randomness is derived via a fixed ``split(sub, d_static + 1)``
        regardless of how many drafts were actually found, so the key
        stream stays in lockstep with the fused program (which always
        runs at the static width). Rollback bookkeeping matches
        ``accept_drafts``. Returns (new_tokens, n_accepted)."""
        from ...ops import sampling as dsamp
        d = max(1, int(d_static))
        k = len(draft)
        rows = np.asarray(window_rows, np.float32)
        if rows.ndim == 1:
            rows = rows[None, :]
        wl = np.zeros((1, 1 + d, rows.shape[-1]), np.float32)
        wl[0, :k + 1] = rows[:k + 1]
        drafts = np.zeros((1, d), np.int32)
        drafts[0, :k] = draft
        key = self._sampler_key(uid, spec.seed)
        out, n_emit, new_key = dsamp.spec_verify_window(
            wl, drafts, np.asarray([k], np.int32), key[None],
            np.asarray([spec.temperature], np.float32),
            np.asarray([spec.top_k], np.int32),
            np.asarray([spec.top_p], np.float32), d=d)
        out, n_emit, new_key = jax.device_get((out, n_emit, new_key))
        self._sample_keys[uid] = np.asarray(new_key[0], np.uint32)
        m = int(n_emit[0]) - 1
        new_toks = [int(t) for t in out[0, :m + 1]]
        seq = self._state_manager.get_sequence(uid)
        rejected = k - m
        if rejected:
            seq.rollback(rejected)
            if self._state_manager.prefix_cache is not None:
                seq.pending_tokens = \
                    seq.pending_tokens[:len(seq.pending_tokens) - rejected]
        if k:
            self._register_pending(seq)
        return new_toks, m

    def fused_decode_steps(self, batch_uids, last_tokens, n_steps: int,
                           specs=None):
        """``n_steps`` decode steps for live sequences in ONE device
        dispatch (model.fused_decode: lax.scan over the single-token forward
        — the TPU analog of the reference v1 engine's CUDA-graph decode
        replay, ``inference/engine.py:527``). Amortizes the per-step host
        round-trip: on a relay-attached TPU a single decode dispatch costs
        ~100ms+ of pure latency, so K fused steps decode up to K× faster.

        Host contract: every uid is LIVE (has prefilled history), every
        sequence has room for ``n_steps`` more tokens (context ceiling is the
        caller's check), and KV blocks for all ``n_steps`` are allocated up
        front here — raises SchedulingError(KVCacheLimitExceeded) without
        side effects if they don't fit. Like the speculative window path,
        prefix-cache registration and trailing-window frees are DEFERRED:
        the caller trims to eos/stop and then runs ``_register_pending`` /
        ``maybe_free_kv`` for sequences that stay live (retiring sequences
        just flush).

        ``specs=None`` runs the original greedy program and returns int32
        [n_seqs, n_steps] generated tokens. With one :class:`SampleSpec`
        per uid, sampling (and logit controls) run ON DEVICE inside the
        scan — temperature/top-k/top-p/repetition-penalty/eos-mask
        requests advance K tokens per dispatch too — and the call returns
        ``(tokens [n_seqs, n_steps], logprobs [n_seqs, n_steps])``, with
        each sequence's PRNG key advanced by exactly ``n_steps`` splits
        (the same count the per-token path would burn)."""
        return self.fused_decode_harvest(
            self.fused_decode_begin(batch_uids, last_tokens, n_steps,
                                    specs=specs))

    def fused_decode_begin(self, batch_uids, last_tokens, n_steps: int,
                           specs=None):
        """DISPATCH half of :meth:`fused_decode_steps` — the continuous
        fusion scheduler's entry point. Feasibility-checks and allocates
        every one of the wave's ``n_steps`` KV blocks (allocation IS the
        KV partition: an overlap-window prefill put can only draw from
        what the wave left), enqueues the fused program WITHOUT blocking
        on the fetch, advances the sequences' host bookkeeping
        (``pre_forward``/``post_forward`` — so allocator projections made
        during the overlap window already see the wave's growth), and
        returns an in-flight handle for :meth:`fused_decode_harvest`.
        Host work needing device values (sampler-key stores, prefix-cache
        pending appends) is deferred to harvest."""
        t0 = time.monotonic()
        batch_uids = list(batch_uids)
        _fire_request_poison(batch_uids)
        seqs = []
        for uid in batch_uids:
            seq = self._state_manager.get_sequence(uid)
            if seq is None or seq.seen_tokens == 0:
                raise ValueError(f"fused_decode_steps: uid {uid} is not a "
                                 "live prefilled sequence")
            seqs.append(seq)
        if len(seqs) > self._config.state_manager.max_ragged_sequence_count:
            raise SchedulingError(SchedulingResult.BatchSequenceLimitExceeded)
        sm = self._config.state_manager
        # feasibility before ANY allocation: the whole wave must fit —
        # get_kv_requirements is the allocator's own arithmetic
        free = self._state_manager.free_blocks
        for seq in seqs:
            if seq.seen_tokens + n_steps > sm.max_context:
                raise SchedulingError(SchedulingResult.SequenceTokenLimitExceeded)
            n_fit, req = self._model.get_kv_requirements(seq, n_steps, free)
            if n_fit != n_steps:
                raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
            free -= req
        for seq in seqs:
            self._model.maybe_allocate_kv(seq, n_steps)

        from .ragged.ragged_wrapper import _bucket
        S = _bucket(len(seqs), floor=1)
        B = _bucket(max(s.cur_allocated_blocks for s in seqs), floor=1)
        tokens = np.zeros(S, np.int32)
        seq_lens = np.zeros(S, np.int32)
        liv = np.zeros(S, np.int32)
        block_table = np.zeros((S, B), np.int32)
        for i, (seq, t) in enumerate(zip(seqs, last_tokens)):
            tokens[i] = int(t)
            seq_lens[i] = seq.seen_tokens
            liv[i] = 1
            block_table[i] = seq.block_table(B)
        aslots = self._adapter_slot_rows(batch_uids, S)
        lps = new_keys = None
        if specs is None:
            out = self._model.fused_decode(tokens, seq_lens, liv, block_table,
                                           n_steps, fetch=False,
                                           adapter_slots=aslots)  # [K, S]
        else:
            V = int(self._model.config.vocab_size)
            use_pen, use_eos, want_lp = self._spec_statics(specs)
            temps, top_ks, top_ps, pens, eos, keys, mask = self._spec_arrays(
                batch_uids, specs, S, V, use_pen)
            n_out = np.zeros(S, np.int32)
            min_new = np.zeros(S, np.int32)
            for i, s in enumerate(specs):
                n_out[i] = s.n_out
                min_new[i] = s.min_new
            out, lps, new_keys = self._model.fused_decode(
                tokens, seq_lens, liv, block_table, n_steps,
                sampling=dict(keys=keys, temps=temps, top_ks=top_ks,
                              top_ps=top_ps, penalties=pens, eos_ids=eos,
                              n_out=n_out, min_new=min_new, seen_mask=mask,
                              want_logprobs=want_lp, use_penalty=use_pen,
                              use_eos_mask=use_eos),
                fetch=False, adapter_slots=aslots)
        for seq in seqs:
            seq.pre_forward(n_steps)
            seq.post_forward()
        _dispatch_seconds.record(time.monotonic() - t0)
        _dispatches_total.inc()
        return _InFlightWave(uids=batch_uids, seqs=seqs, tokens=tokens,
                             out=out, lps=lps, new_keys=new_keys,
                             n_steps=n_steps, sampled=specs is not None)

    def fused_decode_harvest(self, wave: "_InFlightWave"):
        """FETCH half of :meth:`fused_decode_steps`: block on the wave's
        device arrays, store advanced sampler keys, stage prefix-cache
        pending appends, and return the per-token contract — int32
        ``[n_seqs, n_steps]`` tokens (plus ``[n_seqs, n_steps]`` logprobs
        for a sampled wave)."""
        t0 = time.monotonic()
        n, n_steps = len(wave.seqs), wave.n_steps
        lps = None
        if wave.sampled:
            out, lps, new_keys = jax.device_get(
                (wave.out, wave.lps, wave.new_keys))
            for i, u in enumerate(wave.uids):
                self._sample_keys[u] = np.asarray(new_keys[i], np.uint32)
            lps = np.asarray(lps)[:, :n].T  # [n_seqs, K]
        else:
            out = jax.device_get(wave.out)
        out = np.asarray(out)[:, :n].T  # [n_seqs, K]

        pc = self._state_manager.prefix_cache
        if pc is not None:
            for i, seq in enumerate(wave.seqs):
                # fed tokens this dispatch = the input token plus every
                # generated token except the last (it is fed by the NEXT
                # dispatch) — mirrors one put() append per step
                self._append_pending(
                    seq, np.concatenate([[wave.tokens[i]], out[i, :-1]]))
        _harvest_seconds.record(time.monotonic() - t0)
        _harvests_total.inc()
        if wave.sampled:
            return out, lps
        return out

    def fused_spec_decode_steps(self, batch_uids, histories, n_steps: int, *,
                                num_draft_tokens: int, draft_ngram: int,
                                specs=None):
        """``n_steps`` speculative draft/verify windows in ONE device
        dispatch with ONE host fetch — the speculative sibling of
        :meth:`fused_decode_steps` (model.fused_spec_decode). Drafting
        (ring-buffer prompt lookup), window verification, acceptance, and
        rejection-sampling all run inside the scan; host sync drops from
        one round-trip per window to one per K windows, i.e.
        O(new_tokens / (K × mean_accepted)) for the request.

        ``histories[i]`` is uid i's full prompt+output token list, whose
        LAST element is the next token to feed (the per-token path's
        ``last_tok``); the trailing ``spec_history_window`` tokens seed the
        device ring. KV for the worst case ``n_steps * (1 + d)`` tokens is
        reserved up front (feasibility checked before any allocation, like
        the plain fused path); rejected tails cost nothing — their slots
        are overwritten in place by the next window.

        ``specs=None`` verifies greedily (byte-identical to the per-token
        ``accept_drafts`` stream). With one :class:`SampleSpec` per uid,
        verification is rejection sampling against the point-mass drafts
        (``ops/sampling.spec_verify_window``) and each sequence's PRNG key
        advances by exactly ``n_steps`` splits — one per window, the same
        count the host ``accept_drafts_sampled`` fallback burns.

        Returns ``(tokens, drafted, accepted)``: per-uid emitted token
        lists (variable length — between ``n_steps`` and
        ``n_steps * (1 + d)``), and per-uid totals of drafted / accepted
        tokens across the K windows (the accept-rate observability feed)."""
        return self.fused_spec_decode_harvest(
            self.fused_spec_decode_begin(
                batch_uids, histories, n_steps,
                num_draft_tokens=num_draft_tokens, draft_ngram=draft_ngram,
                specs=specs))

    def fused_spec_decode_begin(self, batch_uids, histories, n_steps: int, *,
                                num_draft_tokens: int, draft_ngram: int,
                                specs=None):
        """DISPATCH half of :meth:`fused_spec_decode_steps`. Worst-case
        KV for all ``n_steps * (1 + d)`` tokens is allocated before the
        dispatch (the KV partition invariant, like
        :meth:`fused_decode_begin`), but — unlike the plain wave — the
        sequences' ``pre_forward`` advance depends on the device's
        accepted counts, so ALL host bookkeeping is deferred to
        :meth:`fused_spec_decode_harvest`; during the overlap window the
        wave members' ``seen_tokens`` are stale-low, which only makes
        admission projections conservative (their worst-case blocks are
        already taken)."""
        t0 = time.monotonic()
        batch_uids = list(batch_uids)
        _fire_request_poison(batch_uids)
        d = max(1, int(num_draft_tokens))
        scfg = getattr(self._config, "sampling", None)
        max_ngram = int(scfg.spec_max_ngram) if scfg is not None else 8
        if draft_ngram > max_ngram:
            raise ValueError(f"draft_ngram {draft_ngram} exceeds "
                             f"spec_max_ngram {max_ngram}")
        W = self.spec_ring_window(d)
        seqs = []
        for uid in batch_uids:
            seq = self._state_manager.get_sequence(uid)
            if seq is None or seq.seen_tokens == 0:
                raise ValueError(f"fused_spec_decode_steps: uid {uid} is "
                                 "not a live prefilled sequence")
            seqs.append(seq)
        if len(seqs) > self._config.state_manager.max_ragged_sequence_count:
            raise SchedulingError(SchedulingResult.BatchSequenceLimitExceeded)
        sm = self._config.state_manager
        worst = n_steps * (1 + d)
        free = self._state_manager.free_blocks
        for seq in seqs:
            if seq.seen_tokens + worst > sm.max_context:
                raise SchedulingError(
                    SchedulingResult.SequenceTokenLimitExceeded)
            n_fit, req = self._model.get_kv_requirements(seq, worst, free)
            if n_fit != worst:
                raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
            free -= req
        for seq in seqs:
            self._model.maybe_allocate_kv(seq, worst)

        from .ragged.ragged_wrapper import _bucket
        S = _bucket(len(seqs), floor=1)
        B = _bucket(max(s.cur_allocated_blocks for s in seqs), floor=1)
        tokens = np.zeros(S, np.int32)
        seq_lens = np.zeros(S, np.int32)
        liv = np.zeros(S, np.int32)
        block_table = np.zeros((S, B), np.int32)
        hist = np.zeros((S, W), np.int32)
        hist_len = np.zeros(S, np.int32)
        ngrams = np.zeros(S, np.int32)
        max_d = np.zeros(S, np.int32)
        for i, (seq, h) in enumerate(zip(seqs, histories)):
            tokens[i] = int(h[-1])
            seq_lens[i] = seq.seen_tokens
            liv[i] = 1
            block_table[i] = seq.block_table(B)
            L = len(h)
            tail = np.asarray(h[max(0, L - W):], np.int32)
            p = np.arange(L - tail.size, L)
            hist[i, p % W] = tail  # logical position p lives in slot p % W
            hist_len[i] = L
            ngrams[i] = int(draft_ngram)
            max_d[i] = d
        sampling = None
        if specs is not None:
            temps = np.zeros(S, np.float32)
            top_ks = np.zeros(S, np.int32)
            top_ps = np.ones(S, np.float32)
            keys = np.zeros((S, 2), np.uint32)
            for i, (u, s) in enumerate(zip(batch_uids, specs)):
                temps[i] = s.temperature
                top_ks[i] = s.top_k
                top_ps[i] = s.top_p
                keys[i] = self._sampler_key(u, s.seed)
            sampling = dict(keys=keys, temps=temps, top_ks=top_ks,
                            top_ps=top_ps)
        out, n_emit, dlen, new_keys = self._model.fused_spec_decode(
            tokens, seq_lens, liv, block_table, hist, hist_len, ngrams,
            max_d, n_steps, d, max_ngram, sampling=sampling, fetch=False,
            adapter_slots=self._adapter_slot_rows(batch_uids, S))
        _dispatch_seconds.record(time.monotonic() - t0)
        _dispatches_total.inc()
        return _InFlightSpecWave(uids=batch_uids, seqs=seqs, tokens=tokens,
                                 out=out, n_emit=n_emit, dlen=dlen,
                                 new_keys=new_keys, n_steps=n_steps)

    def fused_spec_decode_harvest(self, wave: "_InFlightSpecWave"):
        """FETCH half of :meth:`fused_spec_decode_steps`: block on the
        wave, store advanced keys, run the deferred per-sequence
        bookkeeping against the device's accepted counts, and return
        ``(tokens, drafted, accepted)``."""
        t0 = time.monotonic()
        n_steps, tokens, seqs = wave.n_steps, wave.tokens, wave.seqs
        if wave.new_keys is not None:
            out, n_emit, dlen, new_keys = jax.device_get(
                (wave.out, wave.n_emit, wave.dlen, wave.new_keys))
            for i, u in enumerate(wave.uids):
                self._sample_keys[u] = np.asarray(new_keys[i], np.uint32)
        else:
            out, n_emit, dlen = jax.device_get(
                (wave.out, wave.n_emit, wave.dlen))

        pc = self._state_manager.prefix_cache
        toks_lists, drafted, accepted = [], [], []
        for i, seq in enumerate(seqs):
            emitted = []
            for w in range(n_steps):
                emitted.extend(int(t) for t in out[w, i, :n_emit[w, i]])
            # seen advances by exactly what the device's lens did — the
            # accepted tokens; worst-case blocks stay allocated for the
            # next window (or free at flush)
            seq.pre_forward(len(emitted))
            seq.post_forward()
            if pc is not None:
                self._append_pending(
                    seq, np.asarray([int(tokens[i])] + emitted[:-1],
                                    np.int32))
            toks_lists.append(emitted)
            drafted.append(int(dlen[:, i].sum()))
            accepted.append(len(emitted) - n_steps)
        _harvest_seconds.record(time.monotonic() - t0)
        _harvests_total.inc()
        return toks_lists, drafted, accepted

    @staticmethod
    def normalize_stop(stop):
        """``stop`` → list of token-id sequences (one flat list = one
        sequence; None/empty = no stop sequences)."""
        if not stop:
            return []
        if all(isinstance(t, (int, np.integer)) for t in stop):
            stop = [stop]
        out = [[int(t) for t in s] for s in stop]
        if any(not s for s in out):
            raise ValueError("empty stop sequence")
        return out

    @staticmethod
    def hit_stop(outputs, stop_seqs) -> bool:
        return any(len(outputs) >= len(s) and outputs[-len(s):] == s
                   for s in stop_seqs)

    def generate(self, prompts, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0,
                 return_logprobs: bool = False,
                 seed: int = 0,
                 speculative: Optional[str] = None,
                 num_draft_tokens: int = 4,
                 draft_ngram: int = 2,
                 num_return_sequences: int = 1,
                 stop=None,
                 min_new_tokens: int = 0,
                 repetition_penalty: float = 1.0,
                 logits_processor=None,
                 fused_decode_window: Optional[int] = None):
        """Continuous-batching decode: admit prompts in scheduler-feasible
        waves (Dynamic SplitFuse ``can_schedule`` gating), decode every live
        sequence in ONE ragged batch per step (the N=1 fast path), free KV on
        completion. Returns the generated token list per prompt (no prompt
        echo).

        Admission reserves DECODE headroom, not just prompt KV: a sequence
        only enters when blocks for ``len(feed) + max_new_tokens`` fit after
        the projected growth of every live sequence, so the decode ``put``
        cannot run the allocator dry mid-generation. If it still does (e.g.
        admission fell back to best-effort), the newest live sequence is
        evicted and later replayed (prompt + tokens so far) instead of the
        whole batch crashing.

        Sampling controls (HF-generate parity): ``stop`` — token-id
        sequence(s) that end generation when the output tail matches (the
        matched tokens are included); ``min_new_tokens`` masks eos until
        reached; ``repetition_penalty`` is the CTRL rule over
        prompt+output history; ``logits_processor(history, row) -> row``
        runs last, before sampling.

        ``speculative="prompt_lookup"`` (greedy only; beyond the reference):
        each decode step drafts up to ``num_draft_tokens`` by matching the
        trailing ``draft_ngram`` against earlier context (Saxena's
        prompt-lookup decoding — no draft model) and verifies them in ONE
        forward via window logits; accepted drafts land m+1 tokens per
        dispatch, rejected ones roll back in place. Memory-bound decode is
        where this pays: the verify pass re-reads the same weights a plain
        step would.

        ``fused_decode_window``: cap on greedy multi-step fused decode (K
        steps per dispatch, ``fused_decode_steps``). Default: 16 on TPU
        (per-dispatch latency dominates single-token steps there), 1 (off)
        on CPU. Applies only to plain greedy generation — any sampling
        control, logprobs, or speculative mode uses the per-step path."""
        stop = self.normalize_stop(stop)
        if fused_decode_window is None:
            from ...ops.registry import on_tpu
            fused_steps_cap = 16 if on_tpu() else 1
        else:
            fused_steps_cap = int(fused_decode_window)
        if speculative is not None:
            if speculative != "prompt_lookup":
                raise ValueError(f"unknown speculative mode {speculative!r}")
            if return_logprobs:
                # the rejection-sampled token's "logprob" under the target
                # distribution is not the probability it was emitted with
                # — refuse rather than report a misleading number
                raise ValueError("speculative decoding does not return "
                                 "logprobs")
            if (min_new_tokens or repetition_penalty != 1.0
                    or logits_processor is not None):
                # temperature/top-k/top-p COMPOSE (rejection sampling
                # against the point-mass drafts — see
                # ops/sampling.spec_verify_window), but history-dependent
                # LOGIT edits would make the verified distribution
                # position-dependent in ways the single window forward
                # can't reproduce. (``stop`` composes: it only truncates
                # outputs at retirement, like eos.)
                raise ValueError("speculative decoding does not compose "
                                 "with min_new_tokens/"
                                 "repetition_penalty/logits_processor")

        def _controls(row, u):
            block_eos = len(outputs[u]) < min_new_tokens
            if (repetition_penalty == 1.0 and not block_eos
                    and logits_processor is None):
                return row  # controls off: skip the O(context) history copy
            return self.process_logits(
                row, prompts[u] + outputs[u],
                repetition_penalty=repetition_penalty,
                eos_token_id=eos_token_id,
                block_eos=block_eos,
                logits_processor=logits_processor)

        rng = np.random.default_rng(seed)
        # on-device sampling (ops/sampling): any request the host-only
        # logits_processor doesn't claim runs controls + sampling in ONE
        # batched device dispatch per step — and becomes eligible for the
        # fused K-step program below. Plain greedy without logprobs keeps
        # the zero-dispatch host argmax.
        scfg = getattr(self._config, "sampling", None)
        device_sampled = (scfg is not None and scfg.device_sampling
                          and logits_processor is None
                          and (temperature != 0.0 or return_logprobs
                               or repetition_penalty != 1.0
                               or min_new_tokens > 0))
        base_key = jax.random.PRNGKey(int(seed)) if device_sampled else None
        spec_sampled = speculative is not None and temperature != 0.0
        if spec_sampled and not device_sampled:
            # the rejection-sampling verify draws from the per-sequence
            # jax key chains; without them there is no reproducible (or
            # fused-parity) stream to offer
            raise ValueError("speculative sampling requires "
                             "sampling.device_sampling")
        # accept-rate observability for the convenience loop (the serving
        # daemon keeps its own per-request counters)
        self.last_spec_stats = {"drafted": 0, "accepted": 0}
        spec_match_window = (self.spec_ring_window(num_draft_tokens)
                             if speculative is not None else 0)
        spec_match_cache = {}

        def _spec(u):
            return SampleSpec(
                temperature=temperature, top_k=top_k, top_p=top_p,
                repetition_penalty=repetition_penalty,
                eos_token_id=eos_token_id,
                block_eos=len(outputs[u]) < min_new_tokens,
                history=(prompts[u] + outputs[u])
                if repetition_penalty != 1.0 else None,
                want_logprobs=return_logprobs,
                n_out=len(outputs[u]), min_new=min_new_tokens)

        def _ensure_keys(us):
            # per-sequence streams derived from the one generate() seed —
            # decorrelated across sequences, reproducible per (seed, u)
            for u in us:
                if u not in self._sample_keys:
                    self.seed_sampler(u, key=jax.random.fold_in(base_key, u))

        def _sample_wave(us, rows):
            """(token, logprob) per row: one batched device dispatch for
            eligible configs, the numpy oracle otherwise."""
            if device_sampled:
                _ensure_keys(us)
                toks, lps = self.sample_rows(us, rows,
                                             [_spec(u) for u in us])
                return list(zip(toks, lps))
            return [self._sample_with_logprob(
                _controls(rows[i], u), temperature, rng, top_k, top_p,
                want_lp=return_logprobs) for i, u in enumerate(us)]

        if num_return_sequences > 1:
            # parallel sampling (MII n-sampling): N samples per prompt,
            # flattened [p0_s0, p0_s1, ..., p1_s0, ...]. With prefix caching
            # on, each unique prompt's prefill is computed ONCE up front and
            # every sample adopts the cached blocks.
            pc0 = self._state_manager.prefix_cache
            if pc0 is not None:
                scratch = 1 << 27
                seen_prompts = set()
                for p in prompts:
                    arr = np.asarray(p, np.int32).reshape(-1)
                    key = arr.tobytes()
                    if (key in seen_prompts
                            or arr.size <= self._state_manager.block_size):
                        continue
                    seen_prompts.add(key)
                    try:
                        self.put([scratch], [arr], do_checks=False)
                    except SchedulingError:
                        break  # cache full; samples just recompute
                    self.flush(scratch)  # blocks stay cached for adoption
                    scratch += 1
            prompts = [p for p in prompts for _ in range(num_return_sequences)]
        prompts = [list(map(int, np.asarray(p).reshape(-1))) for p in prompts]
        uids = list(range(len(prompts)))
        outputs = {u: [] for u in uids}
        logprobs = {u: [] for u in uids}
        # tokens to prefill on (re)admission: prompt, or prompt + generated
        # so far after an eviction
        feed = {u: list(prompts[u]) for u in uids}
        waiting = list(uids)
        live: list = []
        last_tok = {}
        sm = self._config.state_manager
        max_batch_tokens = sm.max_ragged_batch_size
        # the decode batch feeds one token per live sequence, so live count is
        # bounded by BOTH sequence and token limits
        max_seqs = min(sm.max_ragged_sequence_count, max_batch_tokens)

        def _future_blocks(seq_desc, extra: int) -> int:
            # the allocator's own arithmetic, not a re-derivation: blocks
            # `extra` more tokens would need given an unlimited budget
            _, req = self._model.get_kv_requirements(seq_desc, extra, 1 << 30)
            return req

        def _live_reserve() -> int:
            return sum(
                _future_blocks(self._state_manager.get_sequence(u),
                               max(0, max_new_tokens - len(outputs[u])))
                for u in live)

        def _prefill_chunked(u) -> None:
            """Solo SplitFuse prefill for a feed longer than one ragged batch
            (an evicted replay); only the final chunk's logits matter."""
            for ofs in range(0, len(feed[u]), max_batch_tokens):
                logits = np.asarray(self.put(
                    [u], [feed[u][ofs:ofs + max_batch_tokens]],
                    do_checks=False))[0]
            (last_tok[u], lp), = _sample_wave([u], [logits])
            outputs[u].append(last_tok[u])
            logprobs[u].append(lp)
            live.append(u)

        while waiting or live:
            free = self._state_manager.free_blocks - _live_reserve()
            admit, admit_blocks = [], 0
            for u in list(waiting):
                if len(live) + len(admit) >= max_seqs:
                    break
                if len(feed[u]) > sm.max_context:
                    # chunked prefill bypasses put()'s checks, so the context
                    # ceiling must be enforced here (a mid-chunk ValueError
                    # from extend_kv_cache would leak the allocated blocks)
                    raise SchedulingError(SchedulingResult.SequenceTokenLimitExceeded)
                if _future_blocks(PlaceholderSequenceDescriptor(), len(feed[u])) \
                        > self._state_manager.kv_cache.num_blocks:
                    # can never prefill even with the whole cache to itself
                    raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
                need = _future_blocks(
                    PlaceholderSequenceDescriptor(),
                    len(feed[u]) + max(0, max_new_tokens - len(outputs[u])))
                if len(feed[u]) > max_batch_tokens:
                    if admit or need > free:
                        break
                    waiting.remove(u)
                    _prefill_chunked(u)
                    break
                trial = admit + [u]
                if self.can_schedule(trial, [len(feed[t]) for t in trial]) \
                        != SchedulingResult.Success:
                    break
                if admit_blocks + need > free:
                    break
                admit.append(u)
                admit_blocks += need
                waiting.remove(u)
            if not admit and not live and waiting:
                # full decode headroom will never fit — admit ONE sequence on
                # prefill feasibility alone (the eviction path below truncates
                # it if the cache truly runs out) rather than deadlocking
                u = waiting[0]
                if len(feed[u]) > max_batch_tokens:
                    # chunked prefill bypasses put()'s checks: the FEED must
                    # fit the blocks actually free NOW (external put()-created
                    # sequences may pin part of the cache), else the
                    # allocator would raise a raw error mid-chunk
                    if _future_blocks(PlaceholderSequenceDescriptor(),
                                      len(feed[u])) \
                            > self._state_manager.free_blocks:
                        raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
                    waiting.remove(u)
                    _prefill_chunked(u)
                else:
                    check = self.can_schedule([u], [len(feed[u])])
                    if check != SchedulingResult.Success:
                        raise SchedulingError(check)
                    admit = [waiting.pop(0)]
            if admit:
                logits = np.asarray(self.put(admit, [feed[u] for u in admit],
                                             do_checks=False))
                picks = _sample_wave(admit, [logits[i]
                                             for i in range(len(admit))])
                for i, u in enumerate(admit):
                    last_tok[u], lp = picks[i]
                    outputs[u].append(last_tok[u])
                    logprobs[u].append(lp)
                    live.append(u)
            for u in list(live):
                if self.decode_finished(u, outputs[u], max_new_tokens,
                                        eos_token_id, stop):
                    live.remove(u)
                    self.flush(u)
            if not live:
                continue

            def _absorb_new_tokens(u, new_toks, new_lps=None):
                """Shared trim protocol for multi-token waves (fused decode
                and speculative verification): append, cut at the earliest
                eos, then at the earliest stop-sequence END inside the
                appended window, cap at the output budget. Overshot KV needs
                no rollback — a trimmed sequence retires and flushes."""
                outputs[u].extend(new_toks)
                logprobs[u].extend(new_lps if new_lps is not None
                                   else [None] * len(new_toks))
                if eos_token_id is not None and eos_token_id in new_toks:
                    cut = len(outputs[u]) - len(new_toks) \
                        + new_toks.index(eos_token_id) + 1
                    outputs[u] = outputs[u][:cut]
                if stop:
                    out = outputs[u]
                    first = len(out) - len(new_toks) + 1
                    for end in range(max(first, 1), len(out) + 1):
                        if self.hit_stop(out[:end], stop):
                            outputs[u] = out[:end]
                            break
                if len(outputs[u]) > max_new_tokens:
                    outputs[u] = outputs[u][:max_new_tokens]
                if len(logprobs[u]) > len(outputs[u]):
                    logprobs[u] = logprobs[u][:len(outputs[u])]
                last_tok[u] = outputs[u][-1]

            # fused multi-step fast path runs K steps per dispatch — the
            # CUDA-graph-replay analog (see fused_decode_steps). Plain
            # greedy uses the original argmax program; device-sampled
            # requests (temperature/top-k/top-p/logprobs/penalty/min_new)
            # ride the sampled scan program — only host-only
            # logits_processor callbacks and speculative drafting stay
            # per-token. eos and ``stop`` compose by trim-and-retire:
            # overshoot tokens belong to sequences that retire this wave,
            # so their KV needs no rollback (same argument as the
            # speculative window-overshoot path below).
            fused_plain = (speculative is None and temperature == 0.0
                           and not return_logprobs and min_new_tokens == 0
                           and repetition_penalty == 1.0
                           and logits_processor is None)
            fused_ok = fused_steps_cap > 1 and (
                fused_plain or (device_sampled and speculative is None
                                and scfg.fused_sampled_decode))
            if fused_ok:
                # mixed-progress waves SPLIT rather than demote: sequences
                # with >= 2 tokens of room fuse at the largest window THEY
                # support; a near-budget straggler (solo) ticks per-step in
                # the SAME iteration — it is within a token or two of
                # retiring, so the inline single put is bounded, and the
                # fused subset keeps streaming K tokens per dispatch
                fusable, K, solo = self.fused_partition(
                    live, [max_new_tokens - len(outputs[u]) for u in live],
                    fused_steps_cap)
                toks = lps_wave = None
                if K >= 2:
                    try:
                        if fused_plain:
                            toks = self.fused_decode_steps(
                                fusable, [last_tok[u] for u in fusable], K)
                        else:
                            _ensure_keys(fusable)
                            toks, lps_wave = self.fused_decode_steps(
                                fusable, [last_tok[u] for u in fusable], K,
                                specs=[_spec(u) for u in fusable])
                    except SchedulingError:
                        pass  # KV pressure: the single-step path below owns
                        # the evict-and-replay protocol
                if toks is not None:
                    for i, u in enumerate(fusable):
                        _absorb_new_tokens(
                            u, list(map(int, toks[i])),
                            list(map(float, lps_wave[i]))
                            if lps_wave is not None else None)
                        if not self.decode_finished(u, outputs[u],
                                                    max_new_tokens,
                                                    eos_token_id, stop):
                            # deferred bookkeeping for sequences that decode
                            # on; retiring ones just flush at the top of the
                            # loop (pending garbage past eos never registers)
                            seq = self._state_manager.get_sequence(u)
                            self._register_pending(seq)
                            self._model.maybe_free_kv(seq)
                    for u in solo:
                        try:
                            logits_u = np.asarray(
                                self.put([u], [[last_tok[u]]]))[0]
                        except SchedulingError:
                            continue  # replayed by the per-step path's
                            # evict-and-replay protocol next iteration
                        (last_tok[u], lp), = _sample_wave([u], [logits_u])
                        outputs[u].append(last_tok[u])
                        logprobs[u].append(lp)
                    # retirement for both groups happens at the top of the
                    # next loop iteration (the shared decode_finished scan)
                    continue

            # fused SPECULATIVE fast path: drafting, verification, and
            # (for sampled requests) rejection sampling all run inside one
            # K-window scan — one dispatch and one host fetch per
            # K × (accepted+1) tokens (fused_spec_decode_steps). Gate-off
            # (fused_speculative_decode=False) keeps the per-token window
            # path below as the parity oracle.
            fused_spec_ok = (speculative is not None and fused_steps_cap > 1
                             and scfg is not None
                             and scfg.fused_speculative_decode
                             and logits_processor is None
                             and draft_ngram <= scfg.spec_max_ngram)
            if fused_spec_ok:
                fusable, K, solo = self.fused_spec_partition(
                    live, [max_new_tokens - len(outputs[u]) for u in live],
                    num_draft_tokens, fused_steps_cap)
                res = None
                if K >= 2:
                    try:
                        sp = None
                        if spec_sampled:
                            _ensure_keys(fusable)
                            sp = [_spec(u) for u in fusable]
                        res = self.fused_spec_decode_steps(
                            fusable,
                            [prompts[u] + outputs[u] for u in fusable], K,
                            num_draft_tokens=num_draft_tokens,
                            draft_ngram=draft_ngram, specs=sp)
                    except SchedulingError:
                        pass  # KV pressure: the per-token path below owns
                        # the evict-and-replay protocol
                if res is not None:
                    toks_lists, drafted_n, accepted_n = res
                    for i, u in enumerate(fusable):
                        self.last_spec_stats["drafted"] += drafted_n[i]
                        self.last_spec_stats["accepted"] += accepted_n[i]
                        _absorb_new_tokens(u, toks_lists[i])
                        if not self.decode_finished(u, outputs[u],
                                                    max_new_tokens,
                                                    eos_token_id, stop):
                            seq = self._state_manager.get_sequence(u)
                            self._register_pending(seq)
                            self._model.maybe_free_kv(seq)
                    for u in solo:
                        # near-retirement rows tick per-step draft-free —
                        # they have at most a token or two left
                        try:
                            logits_u = np.asarray(
                                self.put([u], [[last_tok[u]]]))[0]
                        except SchedulingError:
                            continue
                        (last_tok[u], lp), = _sample_wave([u], [logits_u])
                        outputs[u].append(last_tok[u])
                        logprobs[u].append(lp)
                    continue

            # total drafted tokens are bounded by the ragged-batch budget
            # (each live seq is guaranteed its 1 real token first) and each
            # sequence's room by its context AND output budgets
            draft_budget = max(0, max_batch_tokens - len(live)) \
                if speculative else 0

            def _draft(u, budget):
                seq = self._state_manager.get_sequence(u)
                room = min(num_draft_tokens, budget,
                           sm.max_context - seq.seen_tokens - 2,
                           max_new_tokens - len(outputs[u]) - 1)
                return self.prompt_lookup_draft(
                    prompts[u] + outputs[u], draft_ngram=draft_ngram,
                    max_tokens=room, match_window=spec_match_window,
                    match_cache=spec_match_cache.setdefault(u, {}))

            drafts = {}
            for u in live:
                drafts[u] = _draft(u, draft_budget) if speculative else []
                draft_budget -= len(drafts[u])
            use_window = any(drafts[u] for u in live)
            while live:
                try:
                    step_feed = [[last_tok[u]] + drafts[u] for u in live]
                    logits = np.asarray(self.put(
                        live, step_feed, window_logits=use_window,
                        defer_register=(
                            {u for u in live if drafts[u]}
                            if use_window else frozenset())))
                    break
                except SchedulingError:
                    if use_window:
                        # drafts don't justify evicting a healthy sequence:
                        # retry the step draft-free before giving up KV
                        drafts = {u: [] for u in live}
                        use_window = False
                        continue
                    u = live.pop()  # newest first: oldest finish soonest
                    self.flush(u)
                    if live:
                        feed[u] = prompts[u] + outputs[u]
                        waiting.insert(0, u)  # replay once blocks free up
                    # else: lone sequence exhausted the whole cache — its
                    # generation is truncated at the tokens produced so far
            if not live:
                continue
            if use_window:
                # draft verification: greedy rows accept the longest
                # argmax-agreeing prefix (accept_drafts — shared with the
                # serving daemon); sampled rows run the rejection-sampling
                # verify (accept_drafts_sampled — the fused program's host
                # twin). Both emit the correction/bonus token and roll the
                # rejected tail back in place.
                if spec_sampled:
                    _ensure_keys(live)
                for i, u in enumerate(live):
                    if spec_sampled:
                        new_toks, m = self.accept_drafts_sampled(
                            u, drafts[u], logits[i], _spec(u),
                            num_draft_tokens)
                    else:
                        new_toks, m = self.accept_drafts(u, drafts[u],
                                                         logits[i])
                    self.last_spec_stats["drafted"] += len(drafts[u])
                    self.last_spec_stats["accepted"] += m
                    seq = self._state_manager.get_sequence(u)
                    # window puts defer the trailing-window free for EVERY
                    # sequence in the batch — resume it here
                    self._model.maybe_free_kv(seq)
                    _absorb_new_tokens(u, new_toks)
            elif spec_sampled:
                # a draft-free step of a SAMPLED speculative request still
                # verifies through the window math (with zero drafts): the
                # per-window key discipline must match the fused program's,
                # which burns one split per window regardless of drafts
                _ensure_keys(live)
                for i, u in enumerate(live):
                    new_toks, _ = self.accept_drafts_sampled(
                        u, [], logits[i], _spec(u), num_draft_tokens)
                    _absorb_new_tokens(u, new_toks)
            else:
                picks = _sample_wave(live, [logits[i]
                                            for i in range(len(live))])
                for i, u in enumerate(live):
                    last_tok[u], lp = picks[i]
                    outputs[u].append(last_tok[u])
                    logprobs[u].append(lp)
        if return_logprobs:
            return [outputs[u] for u in uids], [logprobs[u] for u in uids]
        return [outputs[u] for u in uids]

    def adopt_handoff(self, uid: int, tokens, blocks, seen_tokens: int) -> None:
        """Take over a sequence whose prefix KV was computed on ANOTHER
        engine (disaggregated prefill) and landed into ``blocks`` of THIS
        engine's paged pool: create the descriptor with its history marked
        seen, and register the landed full blocks with the prefix cache so
        adoption/eviction accounting treats them exactly like locally
        computed prefill. ``blocks`` must already be allocated from this
        engine's state manager; ``tokens`` is the seen history (prompt +
        force-fed replay outputs) backing those blocks."""
        sm = self._state_manager
        if sm.get_sequence(uid) is not None:
            raise ValueError(f"uid {uid} already tracked; cannot adopt handoff")
        seq = sm.get_or_create_sequence(uid)
        seq.extend_kv_cache(np.asarray(blocks, np.int64))
        seq.pre_forward(int(seen_tokens))
        seq.post_forward()
        if sm.prefix_cache is not None:
            tokens = np.asarray(tokens, np.int32).reshape(-1)[:int(seen_tokens)]
            self._append_pending(seq, tokens)
            self._register_pending(seq)

    def flush(self, uid: int) -> None:
        self._state_manager.flush_sequence(uid)
        self._sample_keys.pop(uid, None)
        if self._adapters is not None:
            self._adapters.unpin(uid)

    def serialize(self, save_path: str) -> None:
        """Flat param snapshot (reference :251 → flat_model_helpers)."""
        os.makedirs(save_path, exist_ok=True)
        flat, treedef = jax.tree_util.tree_flatten(self._model.params)
        np.savez(os.path.join(save_path, "params.npz"),
                 **{str(i): np.asarray(x) for i, x in enumerate(flat)})
        with open(os.path.join(save_path, "metadata.pkl"), "wb") as f:
            pickle.dump({"treedef": treedef, "config": self._model.config}, f)


def load_engine(save_path: str, builder=None, **engine_kwargs):
    """Rebuild a serving engine from an ``InferenceEngineV2.serialize`` dir
    (params.npz + metadata.pkl). ``engine_kwargs`` forward to the builder
    (engine_config, kv_cache_dtype, ...) — :func:`build_llama_engine` by
    default; pass ``disagg.build_disagg_llama`` to stand up the
    disaggregated prefill/decode pair from the same snapshot."""
    with open(os.path.join(save_path, "metadata.pkl"), "rb") as f:
        meta = pickle.load(f)
    with np.load(os.path.join(save_path, "params.npz")) as z:
        flat = [z[str(i)] for i in range(len(z.files))]
    params = jax.tree_util.tree_unflatten(meta["treedef"], flat)
    builder = builder if builder is not None else build_llama_engine
    return builder(meta["config"], params=params, **engine_kwargs)


def build_llama_engine(config: Optional[LlamaConfig] = None,
                       params=None,
                       engine_config: Optional[RaggedInferenceEngineConfig] = None,
                       seed: int = 0,
                       dtype=None,
                       kv_block_size: int = 64,
                       quantize=None,
                       kv_cache_dtype=None,
                       attn_backend: str = "auto",
                       devices=None) -> InferenceEngineV2:
    """Factory (reference ``engine_factory.py build_hf_engine``): build a
    ragged engine from a Llama config + trained params (random if None)."""
    import jax.numpy as jnp
    config = config or LlamaConfig.tiny()
    engine_config = engine_config or RaggedInferenceEngineConfig()
    mode = engine_config.quantization.quantization_mode
    if mode:
        # the reference spells WoQ via quantization_mode ('wf6af16' =
        # weight-fp6 / activation-fp16, the FP6-LLM mode) — map it onto the
        # model's quantize knob instead of accepting-and-ignoring it
        mapped = {"wf6af16": "fp6", "fp6": "fp6",
                  "int8": "int8", "int4": "int4"}.get(mode)
        if mapped is None:
            raise ValueError(f"unknown quantization_mode {mode!r}; "
                             "supported: wf6af16 (fp6), fp6, int8, int4")
        if quantize is not None and quantize != mapped:
            raise ValueError(
                f"quantize={quantize!r} conflicts with "
                f"quantization_mode={mode!r} (= {mapped!r}) — set one")
        quantize = mapped
    if params is None:
        _, params = init_llama(config, seed=seed)
    tp_cfg = engine_config.tensor_parallel
    model = RaggedLlamaModel(config, params, dtype=dtype or jnp.bfloat16,
                             kv_block_size=kv_block_size, quantize=quantize,
                             attn_backend=attn_backend,
                             kv_cache_dtype=kv_cache_dtype,
                             tp_size=tp_cfg.tp_size,
                             tp_wire_dtype=tp_cfg.tp_wire_dtype,
                             tp_wire_overrides=tp_cfg.tp_wire_overrides,
                             tp_wire_block=tp_cfg.tp_wire_block,
                             devices=devices)
    if engine_config.adapters.enabled:
        # attach BEFORE the engine warms up: the bank operand is part of
        # every traced program's signature, so it must exist before the
        # first dispatch (hot loads after that are pure value writes)
        from .adapters import AdapterRegistry
        model.set_adapter_registry(AdapterRegistry(engine_config.adapters,
                                                   model))
    return InferenceEngineV2(model, engine_config)
