"""Ragged batch construction.

Reference: ``deepspeed/inference/v2/ragged/ragged_wrapper.py``
(RaggedBatchWrapper) — host-side assembly of the dense metadata a ragged
forward needs. TPU twist: XLA requires static shapes, so every array is
padded to a **bucket** (next power of two) and the jitted forward is cached
per bucket signature — the compile-cache analog of the reference's CUDA-graph
ambitions, with padding in place of true dynamism.

Arrays shipped to device per forward:
  tokens[T], token_seq[T], token_pos[T], token_slot[T] (flat KV write index;
  padding points one-past-the-end so the scatter drops it), seq_start[S],
  seq_n_new[S], seq_seen[S], block_table[S, B], last_token_idx[S].
"""

from typing import List, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from ..config_v2 import DSStateManagerConfig
from .sequence_descriptor import DSSequenceDescriptor


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


class RaggedBatch(NamedTuple):
    """Device-side dense view of one ragged batch."""
    tokens: jnp.ndarray        # int32 [T]
    token_seq: jnp.ndarray     # int32 [T] slot in [0, S)
    token_pos: jnp.ndarray     # int32 [T] absolute position in sequence
    token_slot: jnp.ndarray    # int32 [T] flat KV slot (OOB for padding)
    seq_start: jnp.ndarray     # int32 [S] first token index
    seq_n_new: jnp.ndarray     # int32 [S] new tokens this forward (0 = pad)
    seq_seen: jnp.ndarray      # int32 [S] history length
    block_table: jnp.ndarray   # int32 [S, B]
    last_token_idx: jnp.ndarray  # int32 [S] token index of final token
    q_tok_idx: jnp.ndarray     # int32 [S, N] token index of each seq's n-th
    # new token (N buckets the max burst: 1 for pure decode — the attention
    # einsum is S×N×L, so N decoupled from T is the decode fast path)

    @property
    def bucket_key(self):
        return (self.tokens.shape[0], self.seq_start.shape[0],
                self.block_table.shape[1], self.q_tok_idx.shape[1])


class RaggedBatchWrapper:

    def __init__(self, config: DSStateManagerConfig, block_size: int = 128):
        self._config = config
        self._block_size = block_size
        self.clear()

    def clear(self) -> None:
        self._uids: List[int] = []
        self._token_lists: List[np.ndarray] = []
        self._seqs: List[DSSequenceDescriptor] = []
        self._batch = None

    def insert_sequence(self, seq_desc: DSSequenceDescriptor, tokens, do_checks: bool = True) -> None:
        tokens = np.asarray(tokens, dtype=np.int32).reshape(-1)
        if do_checks:
            if len(self._seqs) + 1 > self._config.max_ragged_sequence_count:
                raise RuntimeError("batch sequence limit exceeded")
            if self.current_tokens + tokens.size > self._config.max_ragged_batch_size:
                raise RuntimeError("batch token limit exceeded")
        self._uids.append(seq_desc.uid)
        self._token_lists.append(tokens)
        self._seqs.append(seq_desc)

    @property
    def current_sequences(self) -> int:
        return len(self._seqs)

    @property
    def current_tokens(self) -> int:
        return int(sum(t.size for t in self._token_lists))

    def finalize(self, total_slots: int) -> RaggedBatch:
        """Build the padded dense arrays. `total_slots` = num_blocks*block_size
        of the KV cache (used as the drop target for padding writes)."""
        bs = self._block_size
        S = _bucket(max(1, len(self._seqs)), floor=1)
        T = _bucket(max(1, self.current_tokens))
        max_blocks = max((s.cur_allocated_blocks for s in self._seqs), default=1)
        B = _bucket(max(1, max_blocks), floor=1)

        N = _bucket(max((t.size for t in self._token_lists), default=1), floor=1)

        tokens = np.zeros(T, dtype=np.int32)
        token_seq = np.zeros(T, dtype=np.int32)
        token_pos = np.zeros(T, dtype=np.int32)
        token_slot = np.full(T, total_slots, dtype=np.int32)  # OOB → scatter drop
        seq_start = np.zeros(S, dtype=np.int32)
        seq_n_new = np.zeros(S, dtype=np.int32)
        seq_seen = np.zeros(S, dtype=np.int32)
        block_table = np.zeros((S, B), dtype=np.int32)
        last_token_idx = np.zeros(S, dtype=np.int32)
        q_tok_idx = np.zeros((S, N), dtype=np.int32)

        Sq = len(self._seqs)
        if Sq and all(t.size == 1 for t in self._token_lists):
            # pure-decode fast path (the steady state of serving): the whole
            # assembly collapses to vector ops — one token per sequence,
            # token index == sequence index
            ar = np.arange(Sq, dtype=np.int32)
            seen = np.fromiter((s.seen_tokens for s in self._seqs),
                               np.int32, Sq)
            for i, seq in enumerate(self._seqs):
                block_table[i] = seq.block_table(B)  # cached per descriptor
            tokens[:Sq] = np.fromiter((t[0] for t in self._token_lists),
                                      np.int32, Sq)
            token_seq[:Sq] = ar
            token_pos[:Sq] = seen
            token_slot[:Sq] = block_table[ar, seen // bs] * bs + seen % bs
            seq_start[:Sq] = ar
            seq_n_new[:Sq] = 1
            seq_seen[:Sq] = seen
            last_token_idx[:Sq] = ar
            q_tok_idx[:Sq, 0] = ar
        else:
            cursor = 0
            for i, (seq, toks) in enumerate(zip(self._seqs, self._token_lists)):
                n = toks.size
                seq_start[i] = cursor
                seq_n_new[i] = n
                seq_seen[i] = seq.seen_tokens
                bt = seq.block_table(B)
                block_table[i] = bt
                tokens[cursor:cursor + n] = toks
                token_seq[cursor:cursor + n] = i
                pos = seq.seen_tokens + np.arange(n, dtype=np.int32)
                token_pos[cursor:cursor + n] = pos
                token_slot[cursor:cursor + n] = bt[pos // bs] * bs + pos % bs
                last_token_idx[i] = cursor + n - 1
                q_tok_idx[i, :n] = cursor + np.arange(n, dtype=np.int32)
                cursor += n

        # ONE batched host->device transfer for all ten metadata arrays —
        # ten separate puts cost ~0.3 ms dispatch overhead EACH, which at
        # decode batch sizes rivals the forward itself
        (tokens, token_seq, token_pos, token_slot, seq_start, seq_n_new,
         seq_seen, block_table, last_token_idx, q_tok_idx) = jax.device_put(
            (tokens, token_seq, token_pos, token_slot, seq_start, seq_n_new,
             seq_seen, block_table, last_token_idx, q_tok_idx))
        self._batch = RaggedBatch(
            tokens=tokens, token_seq=token_seq,
            token_pos=token_pos, token_slot=token_slot,
            seq_start=seq_start, seq_n_new=seq_n_new,
            seq_seen=seq_seen, block_table=block_table,
            last_token_idx=last_token_idx, q_tok_idx=q_tok_idx)
        return self._batch

    @property
    def batch(self) -> RaggedBatch:
        return self._batch

    @property
    def uids(self) -> List[int]:
        return list(self._uids)
