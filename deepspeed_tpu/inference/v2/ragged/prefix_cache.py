"""Radix prefix cache: content-addressed KV block reuse with COW forking.

Beyond the reference's FastGen (vLLM/SGLang-class feature): prompt blocks are
keyed by the exact chain of their token contents, forming a radix tree whose
edges are token RUNS, not just whole-block hashes. A later prompt sharing a
block-aligned prefix ADOPTS the cached blocks read-only — prefill compute and
KV writes are skipped for the matched region. A prompt that diverges
MID-block no longer loses the partial match: ``match_fork`` returns the
child entry sharing the longest token-run prefix so the engine can
copy-on-write its block (one jitted gather/scatter on device) and keep only
the diverging tail to prefill.

Entry kinds:

* FULL entries (``len(tokens) == block_size``) — the classic chain nodes;
  they are what ``match``/``match_with_key`` walk and what ``len()`` counts.
* PARTIAL entries (``0 < len(tokens) < block_size``, via ``register_tail``) —
  leaf-only fork sources capturing a flushed sequence's sub-block tail (the
  common "system prompt shorter than a block boundary" case). They never
  gain children and are never adopted whole; they exist to be forked.

Ownership model (host-side, no device traffic — block ids only):

* while the sequence that computed a block is alive, the block belongs to
  that sequence; the cache entry just points at it.
* at sequence flush, ownership of registered blocks transfers to the cache
  (they are NOT returned to the allocator); unregistered blocks free
  normally.
* adopters take a reference (``refs``); flushing an adopter drops it.
  ``match_fork`` also takes a TRANSIENT reference on the fork-source entry
  so eviction cannot free it between the match and the device copy; the
  engine drops it via ``release([src_block])`` once the copy is dispatched.
* under allocator pressure the state manager evicts LRU leaf entries
  (``refs == 0`` and no cached children) back to the allocator — a parent
  is never evicted before its children, so every cached chain stays
  matchable root-first.

Safety: adopted blocks are never written (new tokens start at the
``seen_tokens`` boundary inside a PRIVATE block — after a fork that block is
the COW copy, never the shared source), and prefix caching is disabled for
sliding-window models whose mid-sequence trailing-window release would free
shared blocks. COW whole-block copies are safe because attention is causal:
the first ``p`` slots of the source block are bit-identical to what the
forking sequence would have computed, and slots past ``p`` are overwritten
by the fork's own prefill before any read can see them.
"""

from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np


class _Entry:
    __slots__ = ("block", "refs", "children", "last_use", "parent", "owned",
                 "tokens")

    def __init__(self, block: int, parent, tokens: np.ndarray):
        self.block = int(block)
        self.refs = 0          # live sequences currently adopting this block
        self.children = 0      # cached entries chained after this one
        self.last_use = 0
        self.parent = parent   # parent key or None
        self.owned = False     # True once the computing sequence flushed
        self.tokens = tokens   # this block's token run (len <= block_size)


class PrefixKVCache:

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._entries: Dict[tuple, _Entry] = {}
        self._by_block: Dict[int, tuple] = {}
        # radix child index: parent key (None = root) -> child keys; lets
        # match_fork scan divergence candidates without hashing every entry
        self._kids: Dict[Optional[tuple], Set[tuple]] = {}
        self._clock = 0
        # single source of truth for the saved-prefill accounting: the
        # serving layer mirrors these into Prometheus counters, and the
        # bench cross-checks that mirror against this dict exactly
        self.stats = {"hits": 0, "misses": 0, "saved_tokens": 0,
                      "cow_forks": 0}
        self._depth_samples: deque = deque(maxlen=512)

    # ---- keys ----

    def _keys_for(self, tokens: np.ndarray) -> List[tuple]:
        bs = self.block_size
        keys, parent = [], None
        for i in range(len(tokens) // bs):
            parent = (parent, tokens[i * bs:(i + 1) * bs].tobytes())
            keys.append(parent)
        return keys

    # ---- lookup / adoption ----

    def match(self, tokens: np.ndarray) -> List[int]:
        """Block ids of the longest cached full-block prefix of ``tokens``
        (all matched entries' refcounts are incremented — the caller's
        sequence adopts them)."""
        ids, _ = self.match_with_key(tokens)
        return ids

    def match_with_key(self, tokens: np.ndarray) -> Tuple[List[int], Optional[tuple]]:
        """Like match(), also returning the LAST matched chain key so the
        caller can continue registering the chain without re-hashing the
        matched region."""
        self._clock += 1
        matched: List[_Entry] = []
        last_key = None
        for key in self._keys_for(np.asarray(tokens, np.int32)):
            e = self._entries.get(key)
            if e is None:
                break
            matched.append(e)
            last_key = key
        for e in matched:
            e.refs += 1
            e.last_use = self._clock
        return [e.block for e in matched], last_key

    def match_fork(self, tokens: np.ndarray
                   ) -> Tuple[List[int], Optional[tuple],
                              Optional[Tuple[tuple, int, int]]]:
        """Radix lookup: the full-block walk of ``match_with_key`` PLUS a
        fork candidate at the divergence point.

        Returns ``(full_block_ids, last_key, fork)`` where ``fork`` is
        ``None`` or ``(child_key, block_id, p)``: a child of ``last_key``
        whose token run shares ``p >= 1`` leading tokens with the remainder
        of ``tokens``. Matched full entries are adopted (refs bumped) as in
        ``match``; the fork source additionally takes one TRANSIENT ref the
        caller must drop with ``release([block_id])`` after the COW copy —
        that pin is what keeps the source alive while it is simultaneously
        an eviction candidate.

        Stats (hits/misses/saved_tokens/depth) are counted HERE and only
        here: this is the engine's adoption entry point, so direct test
        calls to ``match``/``match_with_key`` don't pollute the counters.
        """
        tokens = np.asarray(tokens, np.int32)
        matched, last_key = self.match_with_key(tokens)
        remaining = tokens[len(matched) * self.block_size:]
        fork = None
        if len(remaining) > 0:
            best = None  # (p, child_key, entry)
            for ck in self._kids.get(last_key, ()):
                e = self._entries.get(ck)
                if e is None or e.tokens is None:
                    continue
                m = min(len(e.tokens), len(remaining))
                if m == 0:
                    continue
                eq = e.tokens[:m] == remaining[:m]
                p = m if eq.all() else int(np.argmin(eq))
                if p >= 1 and (best is None or p > best[0]):
                    best = (p, ck, e)
            if best is not None:
                p, ck, e = best
                e.refs += 1                     # transient fork pin
                e.last_use = self._clock
                fork = (ck, e.block, p)
        saved = len(matched) * self.block_size
        if matched or fork is not None:
            self.stats["hits"] += 1
            self.stats["saved_tokens"] += saved
            self._depth_samples.append(saved + (fork[2] if fork else 0))
        else:
            self.stats["misses"] += 1
        return matched, last_key, fork

    def commit_fork(self, p: int) -> None:
        """The engine landed a COW copy covering ``p`` forked tokens: fold
        them into the saved-prefill accounting (kept out of ``match_fork``
        so an aborted fork — allocator full — never over-counts)."""
        self.stats["cow_forks"] += 1
        self.stats["saved_tokens"] += int(p)

    def release(self, block_ids: Sequence[int]) -> None:
        """An adopter flushed (or a fork pin is dropped): drop references."""
        for b in block_ids:
            key = self._by_block.get(int(b))
            if key is not None:
                self._entries[key].refs -= 1

    # ---- registration / ownership ----

    def register(self, tokens: np.ndarray, block_ids: Sequence[int]) -> List[int]:
        """Associate ``tokens``' full blocks with ``block_ids`` (the
        computing sequence's blocks, KV already written). Returns the ids
        actually registered; blocks whose chain is already cached are NOT
        re-registered (the duplicate computation keeps its own blocks,
        freed normally at flush)."""
        _, registered = self.register_from(None, tokens, block_ids)
        return registered

    def register_from(self, parent_key: Optional[tuple], tokens: np.ndarray,
                      block_ids: Sequence[int]) -> Tuple[Optional[tuple], List[int]]:
        """Chain-continuation registration: ``tokens`` (a multiple of
        block_size) continue the chain ending at ``parent_key`` (None =
        chain root). Lets a live sequence register each newly completed
        block in O(block) instead of re-hashing its whole history. Returns
        (new tail key, registered block ids)."""
        self._clock += 1
        registered = []
        bs = self.block_size
        tokens = np.asarray(tokens, np.int32)
        key = parent_key
        for i, b in zip(range(len(tokens) // bs), block_ids):
            parent = key
            run = tokens[i * bs:(i + 1) * bs]
            key = (parent, run.tobytes())
            b = int(b)
            e = self._entries.get(key)
            if e is not None:
                continue  # chain already cached by another sequence
            if b in self._by_block:
                continue  # block already backs another entry (shouldn't happen)
            e = _Entry(b, parent, run.copy())
            e.last_use = self._clock
            self._entries[key] = e
            self._by_block[b] = key
            self._kids.setdefault(parent, set()).add(key)
            if parent is not None and parent in self._entries:
                self._entries[parent].children += 1
            registered.append(b)
        return key, registered

    def register_tail(self, parent_key: Optional[tuple], tokens: np.ndarray,
                      block_id: int) -> bool:
        """Register a PARTIAL leaf entry: a flushed sequence's sub-block
        tail (``0 < len(tokens) < block_size`` tokens already written into
        ``block_id`` at slots ``[0, len)``). Partial entries never appear
        in the full-block walk and never gain children — they exist purely
        as fork sources for ``match_fork``. Returns True if inserted."""
        tokens = np.asarray(tokens, np.int32)
        if not 0 < len(tokens) < self.block_size:
            return False
        block_id = int(block_id)
        key = (parent_key, tokens.tobytes())
        if key in self._entries or block_id in self._by_block:
            return False
        self._clock += 1
        e = _Entry(block_id, parent_key, tokens.copy())
        e.last_use = self._clock
        self._entries[key] = e
        self._by_block[block_id] = key
        self._kids.setdefault(parent_key, set()).add(key)
        if parent_key is not None and parent_key in self._entries:
            self._entries[parent_key].children += 1
        return True

    def owns(self, block_id: int) -> bool:
        return int(block_id) in self._by_block

    def take_ownership(self, block_ids: Sequence[int]) -> List[int]:
        """The computing sequence flushed: registered blocks stay cached
        (returned list = blocks the CACHE now owns, i.e. must not be freed
        by the caller)."""
        kept = []
        for b in block_ids:
            key = self._by_block.get(int(b))
            if key is not None:
                self._entries[key].owned = True
                kept.append(int(b))
        return kept

    # ---- accounting / eviction ----

    @property
    def reclaimable_blocks(self) -> int:
        """Exactly what evict() could hand back right now: owned,
        unreferenced entries whose ENTIRE cached subtree is also owned and
        unreferenced (leaf-first eviction cannot pass a pinned or live
        child — counting those would let the scheduler admit work the
        allocator can never satisfy)."""
        memo: Dict[tuple, bool] = {}

        def evictable(key) -> bool:
            if key in memo:
                return memo[key]
            e = self._entries.get(key)
            ok = (e is not None and e.owned and e.refs <= 0
                  and all(evictable(k) for k in self._kids.get(key, ())))
            memo[key] = ok
            return ok

        return sum(1 for key in self._entries if evictable(key))

    def evict(self, n_blocks: int) -> List[int]:
        """Free up to ``n_blocks`` cache-owned LRU leaf blocks back to the
        caller (leaf-first keeps every remaining chain matchable)."""
        freed: List[int] = []
        while len(freed) < n_blocks:
            victims = [(e.last_use, key) for key, e in self._entries.items()
                       if e.owned and e.refs <= 0 and e.children == 0]
            if not victims:
                break
            victims.sort()
            for _, key in victims:
                if len(freed) >= n_blocks:
                    break
                e = self._entries.pop(key)
                self._by_block.pop(e.block, None)
                self._forget_kid(e.parent, key)
                if e.parent is not None and e.parent in self._entries:
                    self._entries[e.parent].children -= 1
                freed.append(e.block)
        return freed

    def _forget_kid(self, parent, key) -> None:
        kids = self._kids.get(parent)
        if kids is not None:
            kids.discard(key)
            if not kids:
                self._kids.pop(parent, None)

    def clear(self) -> List[int]:
        """Drop every entry (weights changed — cached KV content is stale).
        Returns the blocks the CACHE owned, for the caller to free; entries
        whose computing sequence is still live are forgotten without
        freeing (that sequence still owns its blocks)."""
        owned = [e.block for e in self._entries.values() if e.owned]
        self._entries.clear()
        self._by_block.clear()
        self._kids.clear()
        return owned

    # ---- reporting ----

    def report(self) -> Dict[str, object]:
        """Counters + structure snapshot for /health, env_report and the
        bench cross-check. ``saved_prefill_tokens`` is the exact number of
        prompt tokens adoption + COW forks kept out of prefill."""
        s = dict(self.stats)
        lookups = s["hits"] + s["misses"]
        samples = sorted(self._depth_samples)
        return {
            "hits": s["hits"],
            "misses": s["misses"],
            "hit_rate": (s["hits"] / lookups) if lookups else 0.0,
            "saved_prefill_tokens": s["saved_tokens"],
            "cow_forks": s["cow_forks"],
            "p50_match_depth": int(samples[len(samples) // 2]) if samples else 0,
            "entries": len(self._entries),
            "full_entries": len(self),
            "blocks": len(self._by_block),
        }

    def __len__(self):
        # full-block chain entries only: the unit every accounting contract
        # (and the engine's chain_blocks bookkeeping) is written in; partial
        # fork-source tails are auxiliary and counted via report()["entries"]
        bs = self.block_size
        return sum(1 for e in self._entries.values() if len(e.tokens) == bs)
