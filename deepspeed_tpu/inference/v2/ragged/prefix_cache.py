"""Automatic prefix caching: content-addressed KV block reuse.

Beyond the reference's FastGen (vLLM-class feature): FULL prompt blocks are
keyed by the exact chain of their token contents; a later prompt sharing a
block-aligned prefix ADOPTS the cached blocks read-only — prefill compute
and KV writes are skipped for the matched region, and the engine feeds only
the uncached suffix.

Ownership model (host-side, no device traffic — block ids only):

* while the sequence that computed a block is alive, the block belongs to
  that sequence; the cache entry just points at it.
* at sequence flush, ownership of registered blocks transfers to the cache
  (they are NOT returned to the allocator); unregistered blocks free
  normally.
* adopters take a reference (``refs``); flushing an adopter drops it.
* under allocator pressure the state manager evicts LRU leaf entries
  (``refs == 0`` and no cached children) back to the allocator — a parent
  is never evicted before its children, so every cached chain stays
  matchable root-first.

Safety: adopted blocks are never written (new tokens start at the
block-aligned ``seen_tokens`` boundary, i.e. a fresh block), and prefix
caching is disabled for sliding-window models whose mid-sequence
trailing-window release would free shared blocks.
"""

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class _Entry:
    __slots__ = ("block", "refs", "children", "last_use", "parent", "owned")

    def __init__(self, block: int, parent):
        self.block = int(block)
        self.refs = 0          # live sequences currently adopting this block
        self.children = 0      # cached entries chained after this one
        self.last_use = 0
        self.parent = parent   # parent key or None
        self.owned = False     # True once the computing sequence flushed


class PrefixKVCache:

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._entries: Dict[tuple, _Entry] = {}
        self._by_block: Dict[int, tuple] = {}
        self._clock = 0

    # ---- keys ----

    def _keys_for(self, tokens: np.ndarray) -> List[tuple]:
        bs = self.block_size
        keys, parent = [], None
        for i in range(len(tokens) // bs):
            parent = (parent, tokens[i * bs:(i + 1) * bs].tobytes())
            keys.append(parent)
        return keys

    # ---- lookup / adoption ----

    def match(self, tokens: np.ndarray) -> List[int]:
        """Block ids of the longest cached full-block prefix of ``tokens``
        (all matched entries' refcounts are incremented — the caller's
        sequence adopts them)."""
        ids, _ = self.match_with_key(tokens)
        return ids

    def match_with_key(self, tokens: np.ndarray) -> Tuple[List[int], Optional[tuple]]:
        """Like match(), also returning the LAST matched chain key so the
        caller can continue registering the chain without re-hashing the
        matched region."""
        self._clock += 1
        matched: List[_Entry] = []
        last_key = None
        for key in self._keys_for(np.asarray(tokens, np.int32)):
            e = self._entries.get(key)
            if e is None:
                break
            matched.append(e)
            last_key = key
        for e in matched:
            e.refs += 1
            e.last_use = self._clock
        return [e.block for e in matched], last_key

    def release(self, block_ids: Sequence[int]) -> None:
        """An adopter flushed: drop its references."""
        for b in block_ids:
            key = self._by_block.get(int(b))
            if key is not None:
                self._entries[key].refs -= 1

    # ---- registration / ownership ----

    def register(self, tokens: np.ndarray, block_ids: Sequence[int]) -> List[int]:
        """Associate ``tokens``' full blocks with ``block_ids`` (the
        computing sequence's blocks, KV already written). Returns the ids
        actually registered; blocks whose chain is already cached are NOT
        re-registered (the duplicate computation keeps its own blocks,
        freed normally at flush)."""
        _, registered = self.register_from(None, tokens, block_ids)
        return registered

    def register_from(self, parent_key: Optional[tuple], tokens: np.ndarray,
                      block_ids: Sequence[int]) -> Tuple[Optional[tuple], List[int]]:
        """Chain-continuation registration: ``tokens`` (a multiple of
        block_size) continue the chain ending at ``parent_key`` (None =
        chain root). Lets a live sequence register each newly completed
        block in O(block) instead of re-hashing its whole history. Returns
        (new tail key, registered block ids)."""
        self._clock += 1
        registered = []
        bs = self.block_size
        tokens = np.asarray(tokens, np.int32)
        key = parent_key
        for i, b in zip(range(len(tokens) // bs), block_ids):
            parent = key
            key = (parent, tokens[i * bs:(i + 1) * bs].tobytes())
            b = int(b)
            e = self._entries.get(key)
            if e is not None:
                continue  # chain already cached by another sequence
            if b in self._by_block:
                continue  # block already backs another entry (shouldn't happen)
            e = _Entry(b, parent)
            e.last_use = self._clock
            self._entries[key] = e
            self._by_block[b] = key
            if parent is not None and parent in self._entries:
                self._entries[parent].children += 1
            registered.append(b)
        return key, registered

    def owns(self, block_id: int) -> bool:
        return int(block_id) in self._by_block

    def take_ownership(self, block_ids: Sequence[int]) -> List[int]:
        """The computing sequence flushed: registered blocks stay cached
        (returned list = blocks the CACHE now owns, i.e. must not be freed
        by the caller)."""
        kept = []
        for b in block_ids:
            key = self._by_block.get(int(b))
            if key is not None:
                self._entries[key].owned = True
                kept.append(int(b))
        return kept

    # ---- accounting / eviction ----

    @property
    def reclaimable_blocks(self) -> int:
        """Exactly what evict() could hand back right now: owned,
        unreferenced entries whose ENTIRE cached subtree is also owned and
        unreferenced (leaf-first eviction cannot pass a pinned or live
        child — counting those would let the scheduler admit work the
        allocator can never satisfy)."""
        kids: Dict[Optional[tuple], List[tuple]] = {}
        for key, e in self._entries.items():
            kids.setdefault(e.parent, []).append(key)
        memo: Dict[tuple, bool] = {}

        def evictable(key) -> bool:
            if key in memo:
                return memo[key]
            e = self._entries[key]
            ok = (e.owned and e.refs <= 0
                  and all(evictable(k) for k in kids.get(key, ())))
            memo[key] = ok
            return ok

        return sum(1 for key in self._entries if evictable(key))

    def evict(self, n_blocks: int) -> List[int]:
        """Free up to ``n_blocks`` cache-owned LRU leaf blocks back to the
        caller (leaf-first keeps every remaining chain matchable)."""
        freed: List[int] = []
        while len(freed) < n_blocks:
            victims = [(e.last_use, key) for key, e in self._entries.items()
                       if e.owned and e.refs <= 0 and e.children == 0]
            if not victims:
                break
            victims.sort()
            for _, key in victims:
                if len(freed) >= n_blocks:
                    break
                e = self._entries.pop(key)
                self._by_block.pop(e.block, None)
                if e.parent is not None and e.parent in self._entries:
                    self._entries[e.parent].children -= 1
                freed.append(e.block)
        return freed

    def clear(self) -> List[int]:
        """Drop every entry (weights changed — cached KV content is stale).
        Returns the blocks the CACHE owned, for the caller to free; entries
        whose computing sequence is still live are forgotten without
        freeing (that sequence still owns its blocks)."""
        owned = [e.block for e in self._entries.values() if e.owned]
        self._entries.clear()
        self._by_block.clear()
        return owned

    def __len__(self):
        return len(self._entries)
