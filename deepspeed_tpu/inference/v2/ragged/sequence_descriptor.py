"""Host-side sequence metadata.

Reference: ``deepspeed/inference/v2/ragged/sequence_descriptor.py``
(DSSequenceDescriptor / PlaceholderSequenceDescriptor). The reference keeps
per-sequence views into pinned device tensors; on TPU all metadata stays
host-numpy and is shipped once per forward inside the RaggedBatch arrays.
"""

from typing import List

import numpy as np


class BaseSequenceDescriptor:

    @property
    def seen_tokens(self) -> int:
        raise NotImplementedError()

    @property
    def cur_allocated_blocks(self) -> int:
        raise NotImplementedError()


class PlaceholderSequenceDescriptor(BaseSequenceDescriptor):
    """Stand-in for unknown UIDs during scheduling dry runs
    (reference sequence_descriptor.py:PlaceholderSequenceDescriptor)."""

    def __init__(self, seen_tokens: int = 0, cur_allocated_blocks: int = 0):
        self._seen_tokens = seen_tokens
        self._cur_allocated_blocks = cur_allocated_blocks

    @property
    def seen_tokens(self) -> int:
        return self._seen_tokens

    @property
    def cur_allocated_blocks(self) -> int:
        return self._cur_allocated_blocks


class DSSequenceDescriptor(BaseSequenceDescriptor):

    def __init__(self, uid: int, max_blocks_per_seq: int):
        self.uid = uid
        self._seen_tokens = 0
        self._in_flight_tokens = 0
        self._max_blocks = max_blocks_per_seq
        self._blocks: List[int] = []

    @property
    def seen_tokens(self) -> int:
        return self._seen_tokens

    @property
    def in_flight_tokens(self) -> int:
        return self._in_flight_tokens

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self._blocks)

    @property
    def kv_blocks(self) -> List[int]:
        return self._blocks

    def block_table(self, width: int) -> np.ndarray:
        """Dense int32 block table padded to `width` with 0 (padded entries are
        masked out by position bounds in the attention kernel)."""
        t = np.zeros(width, dtype=np.int32)
        n = min(len(self._blocks), width)
        t[:n] = self._blocks[:n]
        return t

    def extend_kv_cache(self, new_blocks) -> None:
        blocks = [int(b) for b in np.atleast_1d(new_blocks)]
        if len(self._blocks) + len(blocks) > self._max_blocks:
            raise ValueError(f"Sequence {self.uid} exceeds max_blocks_per_seq={self._max_blocks}")
        self._blocks.extend(blocks)

    def pre_forward(self, num_tokens: int) -> None:
        """Reference sequence_descriptor: record in-flight tokens."""
        self._in_flight_tokens = num_tokens

    def post_forward(self) -> None:
        """Commit in-flight tokens to history."""
        self._seen_tokens += self._in_flight_tokens
        self._in_flight_tokens = 0
