"""Host-side sequence metadata.

Reference: ``deepspeed/inference/v2/ragged/sequence_descriptor.py``
(DSSequenceDescriptor / PlaceholderSequenceDescriptor). The reference keeps
per-sequence views into pinned device tensors; on TPU all metadata stays
host-numpy and is shipped once per forward inside the RaggedBatch arrays.
"""

from typing import List

import numpy as np


class BaseSequenceDescriptor:

    @property
    def seen_tokens(self) -> int:
        raise NotImplementedError()

    @property
    def cur_allocated_blocks(self) -> int:
        raise NotImplementedError()


class PlaceholderSequenceDescriptor(BaseSequenceDescriptor):
    """Stand-in for unknown UIDs during scheduling dry runs
    (reference sequence_descriptor.py:PlaceholderSequenceDescriptor)."""

    def __init__(self, seen_tokens: int = 0, cur_allocated_blocks: int = 0):
        self._seen_tokens = seen_tokens
        self._cur_allocated_blocks = cur_allocated_blocks

    @property
    def seen_tokens(self) -> int:
        return self._seen_tokens

    @property
    def cur_allocated_blocks(self) -> int:
        return self._cur_allocated_blocks


class DSSequenceDescriptor(BaseSequenceDescriptor):

    def __init__(self, uid: int, max_blocks_per_seq: int):
        self.uid = uid
        self._seen_tokens = 0
        self._in_flight_tokens = 0
        self._max_blocks = max_blocks_per_seq
        self._blocks: List[int] = []
        self._freed_through = 0  # table indices < this are released (None)
        self._table_cache = {}  # width -> padded np table (decode hot path)

    @property
    def seen_tokens(self) -> int:
        return self._seen_tokens

    @property
    def in_flight_tokens(self) -> int:
        return self._in_flight_tokens

    @property
    def cur_allocated_blocks(self) -> int:
        return len(self._blocks)

    @property
    def kv_blocks(self) -> List[int]:
        """LIVE block ids (prefix entries released by free_prefix_blocks are
        excluded — they belong to the allocator again)."""
        return [b for b in self._blocks if b is not None]

    def block_table(self, width: int) -> np.ndarray:
        """Dense int32 block table padded to `width` with 0 (padded entries are
        masked out by position bounds in the attention kernel; freed-prefix
        entries keep their POSITION with a 0 placeholder — every reader of
        those positions is masked by the attention window that justified the
        free). Cached per width — the block list changes once per
        ``block_size`` decoded tokens, not per decode step; callers must
        not mutate the returned array."""
        t = self._table_cache.get(width)
        if t is None:
            t = np.zeros(width, dtype=np.int32)
            n = min(len(self._blocks), width)
            t[:n] = [0 if b is None else b for b in self._blocks[:n]]
            self._table_cache[width] = t
        return t

    def free_prefix_blocks(self, through_block: int) -> List[int]:
        """Release the blocks at table indices [0, through_block) — their
        whole token range has fallen out of every attention window. Returns
        the freed block ids; table positions are retained (the position→block
        mapping for live tail blocks must not shift)."""
        freed = []
        # cursor: each block is visited exactly once over a generation, not
        # O(dead prefix) per decoded token
        for i in range(self._freed_through, min(through_block, len(self._blocks))):
            if self._blocks[i] is not None:
                freed.append(self._blocks[i])
                self._blocks[i] = None
        self._freed_through = max(self._freed_through,
                                  min(through_block, len(self._blocks)))
        if freed:
            self._table_cache.clear()
        return freed

    def extend_kv_cache(self, new_blocks) -> None:
        blocks = [int(b) for b in np.atleast_1d(new_blocks)]
        if len(self._blocks) + len(blocks) > self._max_blocks:
            raise ValueError(f"Sequence {self.uid} exceeds max_blocks_per_seq={self._max_blocks}")
        self._blocks.extend(blocks)
        self._table_cache.clear()

    def pre_forward(self, num_tokens: int) -> None:
        """Reference sequence_descriptor: record in-flight tokens."""
        self._in_flight_tokens = num_tokens

    def post_forward(self) -> None:
        """Commit in-flight tokens to history."""
        self._seen_tokens += self._in_flight_tokens
        self._in_flight_tokens = 0

    def rollback(self, n: int) -> None:
        """Drop the last ``n`` tokens from history — speculative decode
        rejected them. Their KV slots stay allocated and are overwritten
        in place by the next accepted tokens at the same positions (slot =
        f(position), so the scatter self-heals)."""
        if not 0 <= n <= self._seen_tokens:
            raise ValueError(f"rollback({n}) with seen={self._seen_tokens}")
        self._seen_tokens -= n
