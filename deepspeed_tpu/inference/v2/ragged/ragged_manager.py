"""Persistent state manager for ragged serving.

Reference: ``deepspeed/inference/v2/ragged/ragged_manager.py:19 DSStateManager``
— tracks live sequences, owns the block allocator + paged KV cache.
"""

from typing import Dict, Optional

from ..config_v2 import DSStateManagerConfig, KVCacheConfig
from .blocked_allocator import BlockedAllocator
from .kv_cache import BlockedKVCache
from .prefix_cache import PrefixKVCache
from .sequence_descriptor import DSSequenceDescriptor


class DSStateManager:

    def __init__(self,
                 config: DSStateManagerConfig,
                 kv_config: KVCacheConfig,
                 num_blocks: Optional[int] = None,
                 enable_prefix_caching: bool = False):
        self._config = config
        self._kv_config = kv_config
        if num_blocks is None:
            num_blocks = self._size_from_memory_config(config, kv_config)
        self._allocator = BlockedAllocator(num_blocks)
        self._kv_cache = BlockedKVCache(kv_config, num_blocks)
        self._seqs: Dict[int, DSSequenceDescriptor] = {}
        self.prefix_cache = (PrefixKVCache(kv_config.block_size)
                             if enable_prefix_caching else None)

    @staticmethod
    def _size_from_memory_config(config: DSStateManagerConfig,
                                 kv_config: KVCacheConfig) -> int:
        """Reference memory_config sizing (manager_configs.py): 'allocate' =
        memory_config_size IS the block count; 'reserve' = that fraction of
        free HBM becomes KV blocks. Reserve engages only on a real TPU
        (PJRT memory stats); elsewhere the deterministic default keeps CPU
        tests from sizing a cache off host RAM."""
        if config.memory_config_mode == "allocate":
            return max(1, int(config.memory_config_size))
        from ....ops.registry import on_tpu
        if on_tpu():
            try:
                from ....accelerator import get_accelerator
                free = get_accelerator().available_memory()
            except Exception:  # noqa: BLE001 — stats are best-effort
                free = None
            if free and free > 0:
                from .kv_cache import estimate_kv_blocks
                return max(64, estimate_kv_blocks(
                    kv_config, free, config.memory_config_size))
        return max(64, config.max_tracked_sequences)

    # ---- sequence tracking (reference ragged_manager.py:96-160) ----

    @property
    def n_tracked_sequences(self) -> int:
        return len(self._seqs)

    @property
    def tracked_sequences(self) -> Dict[int, DSSequenceDescriptor]:
        return self._seqs

    def get_sequence(self, uid: int) -> Optional[DSSequenceDescriptor]:
        return self._seqs.get(uid)

    def get_or_create_sequence(self, uid: int) -> DSSequenceDescriptor:
        seq = self._seqs.get(uid)
        if seq is not None:
            return seq
        return self._create_sequence(uid)

    def _create_sequence(self, uid: int) -> DSSequenceDescriptor:
        if uid in self._seqs:
            raise ValueError(f"Sequence {uid} already exists")
        if len(self._seqs) >= self._config.max_tracked_sequences:
            raise RuntimeError("max_tracked_sequences exceeded")
        max_blocks = (self._config.max_context + self._kv_config.block_size - 1) \
            // self._kv_config.block_size
        seq = DSSequenceDescriptor(uid, max_blocks)
        self._seqs[uid] = seq
        return seq

    def flush_sequence(self, uid: int) -> None:
        """Free a sequence's KV blocks + tracking (reference :147). With
        prefix caching on, adopted blocks drop their reference and
        registered blocks transfer ownership to the cache instead of
        returning to the allocator."""
        seq = self._seqs.pop(uid, None)
        if seq is None:
            return
        blocks = seq.kv_blocks
        if self.prefix_cache is not None:
            adopted = set(getattr(seq, "adopted_blocks", ()))
            self.prefix_cache.release([b for b in blocks if b in adopted])
            self._register_tail(seq, blocks, adopted)
            kept = set(self.prefix_cache.take_ownership(
                [b for b in blocks if b not in adopted]))
            blocks = [b for b in blocks if b not in adopted and b not in kept]
        if blocks:
            self._allocator.free(blocks)

    def _register_tail(self, seq, blocks, adopted) -> None:
        """At flush, hand the sequence's sub-block TAIL to the radix cache
        as a partial (fork-source) entry: the common "system prompt shorter
        than a block" case would otherwise evaporate on every flush. Runs
        before take_ownership so the tail block transfers with the rest.
        The seen_tokens consistency check skips sequences whose staged
        tail no longer reflects block contents (mid-rollback flushes)."""
        pend = getattr(seq, "pending_tokens", None)
        start = int(getattr(seq, "chain_blocks", 0))
        bs = self.block_size
        if (pend is None or not 0 < len(pend) < bs or start >= len(blocks)
                or seq.seen_tokens != start * bs + len(pend)):
            return
        tail_block = blocks[start]
        if tail_block in adopted:
            return
        self.prefix_cache.register_tail(
            getattr(seq, "chain_key", None), pend, tail_block)

    # ---- KV accounting ----

    @property
    def free_blocks(self) -> int:
        """Allocator-free plus what prefix-cache eviction could reclaim —
        the scheduling view (allocate_blocks evicts on demand)."""
        n = self._allocator.free_blocks
        if self.prefix_cache is not None:
            n += self.prefix_cache.reclaimable_blocks
        return n

    @property
    def kv_cache(self) -> BlockedKVCache:
        return self._kv_cache

    @property
    def block_size(self) -> int:
        return self._kv_config.block_size

    def reset_prefix_cache(self) -> None:
        """Invalidate all cached prefixes (the hybrid engine's weight swap:
        KV content computed under old weights must never be adopted).

        Live sequences are flushed FIRST: their entire KV history is
        old-weight state too (continuing them post-swap would mix weights),
        and flushing through the normal path settles every refcount and
        chain bookkeeping — so clear() only ever frees blocks with no live
        adopters, and no stale chain_key can re-register contaminated KV
        into the fresh cache."""
        if self.prefix_cache is None:
            return
        for uid in list(self._seqs):
            self.flush_sequence(uid)
        freed = self.prefix_cache.clear()
        if freed:
            self._allocator.free(freed)

    def allocate_blocks(self, n_blocks: int):
        if (self.prefix_cache is not None
                and n_blocks > self._allocator.free_blocks):
            # evict LRU cached prefixes back to the allocator on demand
            evicted = self.prefix_cache.evict(
                n_blocks - self._allocator.free_blocks)
            if evicted:
                self._allocator.free(evicted)
            if n_blocks > self._allocator.free_blocks:
                # free_blocks promised space eviction couldn't deliver (or
                # the scheduler was raced) — surface the catchable scheduling
                # error, not the allocator's raw ValueError, so generate()'s
                # evict-and-replay recovery can engage
                from ..scheduling_utils import SchedulingError, SchedulingResult
                raise SchedulingError(SchedulingResult.KVCacheLimitExceeded)
        return self._allocator.allocate(n_blocks)

    def release_blocks(self, blocks) -> None:
        """Return individual blocks mid-sequence (trailing-window release,
        model.maybe_free_kv) without touching sequence tracking."""
        if len(blocks):
            self._allocator.free(blocks)
