from .blocked_allocator import BlockedAllocator
from .sequence_descriptor import DSSequenceDescriptor, PlaceholderSequenceDescriptor
from .kv_cache import BlockedKVCache
from .ragged_manager import DSStateManager
from .ragged_wrapper import RaggedBatchWrapper, RaggedBatch
