"""Paged (blocked) KV cache on device.

Reference: ``deepspeed/inference/v2/ragged/kv_cache.py`` (BlockedKVCache).
TPU design: ONE device array per allocation group shaped
``[num_layers, 2, num_kv_heads, num_blocks * block_size, head_dim]`` — flat
slot addressing means the model writes new K/V with a single scatter of
per-token flat indices (``block_table[pos // bs] * bs + pos % bs``). The
(layer, k/v, head)-major layout makes one KV page a contiguous
``[block_size, head_dim]`` strip: exactly the DMA unit of the Pallas
blocked-flash kernel (``ops/paged_attention.py``), which scalar-prefetches
the block table and streams pages without ever materializing a gathered
history window.

The cache is functional state: the jitted forward takes it as a donated
argument and returns the updated array (no in-place mutation semantics to
fight — donation makes it zero-copy on device).
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from ..config_v2 import KVCacheConfig

from ....utils.dtypes import resolve_dtype


class BlockedKVCache:

    def __init__(self, config: KVCacheConfig, num_blocks: int):
        self._config = config
        self.num_blocks = num_blocks
        self.block_size = config.block_size
        n_layers, n_kv, head_dim = config.cache_shape
        self.dtype = resolve_dtype(config.cache_dtype, jnp.bfloat16)
        self.shape = (n_layers, 2, n_kv, num_blocks * config.block_size, head_dim)
        if config.cache_sharding is not None:
            # allocate DIRECTLY under the sharding (TP serving: head dim
            # over the model axis) — a default-placement zeros would OOM
            # exactly the tp-sized caches the sharding exists for
            self.cache = jax.jit(lambda: jnp.zeros(self.shape, self.dtype),
                                 out_shardings=config.cache_sharding)()
        else:
            self.cache = jnp.zeros(self.shape, dtype=self.dtype)

    @property
    def per_token_bytes(self) -> int:
        n_layers, n_kv, head_dim = self._config.cache_shape
        return n_layers * 2 * n_kv * head_dim * jnp.dtype(self.dtype).itemsize

    def update(self, new_cache: jax.Array) -> None:
        """Install the updated cache returned by a forward (donated swap)."""
        self.cache = new_cache

    @staticmethod
    def required_blocks(tokens: int, block_size: int) -> int:
        return (tokens + block_size - 1) // block_size


def estimate_kv_blocks(config: KVCacheConfig, hbm_bytes: int, fraction: float) -> int:
    """Size the cache from an HBM budget (reference memory_config 'reserve')."""
    n_layers, n_kv, head_dim = config.cache_shape
    per_block = (n_layers * 2 * n_kv * head_dim *
                 jnp.dtype(resolve_dtype(config.cache_dtype, jnp.bfloat16)).itemsize *
                 config.block_size)
    return max(1, int(hbm_bytes * fraction) // per_block)
