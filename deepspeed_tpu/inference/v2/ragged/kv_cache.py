"""Paged (blocked) KV cache on device.

Reference: ``deepspeed/inference/v2/ragged/kv_cache.py`` (BlockedKVCache).
TPU design: ONE device array per allocation group shaped
``[num_layers, 2, num_kv_heads, num_blocks * block_size, head_dim]`` — flat
slot addressing means the model writes new K/V with a single scatter of
per-token flat indices (``block_table[pos // bs] * bs + pos % bs``). The
(layer, k/v, head)-major layout makes one KV page a contiguous
``[block_size, head_dim]`` strip: exactly the DMA unit of the Pallas
blocked-flash kernel (``ops/paged_attention.py``), which scalar-prefetches
the block table and streams pages without ever materializing a gathered
history window.

The cache is functional state: the jitted forward takes it as a donated
argument and returns the updated array (no in-place mutation semantics to
fight — donation makes it zero-copy on device).
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from ..config_v2 import KVCacheConfig

from ....utils.dtypes import resolve_dtype


class BlockedKVCache:

    def __init__(self, config: KVCacheConfig, num_blocks: int):
        self._config = config
        self.num_blocks = num_blocks
        self.block_size = config.block_size
        n_layers, n_kv, head_dim = config.cache_shape
        self.quantized = str(config.cache_dtype) == "int8"
        self.dtype = (jnp.int8 if self.quantized
                      else resolve_dtype(config.cache_dtype, jnp.bfloat16))
        slots = num_blocks * config.block_size
        self.shape = (n_layers, 2, n_kv, slots, head_dim)
        if config.cache_sharding is not None:
            # allocate DIRECTLY under the sharding (TP serving: head dim
            # over the model axis) — a default-placement zeros would OOM
            # exactly the tp-sized caches the sharding exists for
            if self.quantized:
                # scales [L, 2, KV, slots] shard like the cache minus the
                # head_dim axis
                from jax.sharding import NamedSharding, PartitionSpec as P
                spec = tuple(config.cache_sharding.spec)[:4]
                ssharding = NamedSharding(config.cache_sharding.mesh, P(*spec))
                self.cache = (
                    jax.jit(lambda: jnp.zeros(self.shape, jnp.int8),
                            out_shardings=config.cache_sharding)(),
                    jax.jit(lambda: jnp.zeros(self.shape[:4], jnp.float32),
                            out_shardings=ssharding)())
            else:
                self.cache = jax.jit(lambda: jnp.zeros(self.shape, self.dtype),
                                     out_shardings=config.cache_sharding)()
        elif self.quantized:
            # int8 data + per-slot-vector fp32 dequant scales: 1 +
            # 4/head_dim bytes per element instead of 2 — half the KV HBM,
            # double the schedulable batch at the same budget
            self.cache = (jnp.zeros(self.shape, jnp.int8),
                          jnp.zeros(self.shape[:4], jnp.float32))
        else:
            self.cache = jnp.zeros(self.shape, dtype=self.dtype)

    @property
    def per_token_bytes(self) -> int:
        return per_token_kv_bytes(self._config)

    def update(self, new_cache: jax.Array) -> None:
        """Install the updated cache returned by a forward (donated swap)."""
        self.cache = new_cache

    @staticmethod
    def required_blocks(tokens: int, block_size: int) -> int:
        return (tokens + block_size - 1) // block_size


def per_token_kv_bytes(config: KVCacheConfig) -> int:
    """One source of truth for KV bytes/token: int8 data + fp32 per-vector
    scale, or the plain dtype itemsize."""
    n_layers, n_kv, head_dim = config.cache_shape
    if str(config.cache_dtype) == "int8":
        return n_layers * 2 * n_kv * (head_dim * 1 + 4)  # int8 + scale
    itemsize = jnp.dtype(resolve_dtype(config.cache_dtype, jnp.bfloat16)).itemsize
    return n_layers * 2 * n_kv * head_dim * itemsize


def estimate_kv_blocks(config: KVCacheConfig, hbm_bytes: int, fraction: float) -> int:
    """Size the cache from an HBM budget (reference memory_config 'reserve')."""
    per_block = per_token_kv_bytes(config) * config.block_size
    return max(1, int(hbm_bytes * fraction) // per_block)
