"""Paged (blocked) KV cache on device.

Reference: ``deepspeed/inference/v2/ragged/kv_cache.py`` (BlockedKVCache).
TPU design: ONE device array per allocation group shaped
``[2 * num_layers, num_blocks * block_size, num_kv_heads * head_dim]`` —
k at row ``2l``, v at row ``2l+1``, flat slot addressing
(``block_table[pos // bs] * bs + pos % bs``). This slot-major folded layout
is SCATTER-NATIVE: the model appends new K/V with a single in-place donated
scatter along the slot dim, with zero HLO temps — the earlier
(layer, k/v, head)-major layout forced XLA to materialize two transposed
copies of the entire cache per forward (2 GB of temps on a 1 GB cache;
the 32k-context serving sweep OOMed on it, 8/1 window). The minor dim
``KV*D`` is 128-lane aligned for typical shapes, so there is no tiling
padding either. The Pallas blocked-flash kernel
(``ops/paged_attention.py``) views it as ``[2L, pages, page_size, KV*D]``
(a free reshape) and DMAs one ``[2, page_size, KV*head_dim]`` all-heads
k+v page block per (layer, page) grid step.

Int8 scales are ``[2L, slots, num_kv_heads]`` — slot-major like the data,
so the per-token scale write is the same in-place scatter, and the kernel
views them ``[2L, pages, page_size, KV]`` (legal block minor dims).

The cache is functional state: the jitted forward takes it as a donated
argument and returns the updated array (no in-place mutation semantics to
fight — donation makes it zero-copy on device).
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from ..config_v2 import KVCacheConfig

from ....utils.dtypes import resolve_dtype


class BlockedKVCache:

    def __init__(self, config: KVCacheConfig, num_blocks: int):
        self._config = config
        self.num_blocks = num_blocks
        self.block_size = config.block_size
        n_layers, n_kv, head_dim = config.cache_shape
        self.quantized = str(config.cache_dtype) == "int8"
        self.dtype = (jnp.int8 if self.quantized
                      else resolve_dtype(config.cache_dtype, jnp.bfloat16))
        slots = num_blocks * config.block_size
        self.shape = (2 * n_layers, slots, n_kv * head_dim)
        self.scales_shape = (2 * n_layers, slots, n_kv)
        if config.cache_sharding is not None:
            # allocate DIRECTLY under the sharding (TP serving: the folded
            # head dim over the model axis) — a default-placement zeros
            # would OOM exactly the tp-sized caches the sharding exists for
            if self.quantized:
                # scales [2L, slots, KV] shard on the head dim like the data
                # (a replicated data spec — the dense nondivisible-GQA
                # fallback — replicates the scales too, and P(None,)*3
                # degrades to replicated for it). A non-named sharding
                # (disagg single-device group pinning) applies as-is.
                from jax.sharding import NamedSharding, PartitionSpec as P
                if isinstance(config.cache_sharding, NamedSharding):
                    spec = tuple(config.cache_sharding.spec)
                    head_axis = spec[2] if len(spec) > 2 else None
                    ssharding = NamedSharding(config.cache_sharding.mesh,
                                              P(None, None, head_axis))
                else:
                    ssharding = config.cache_sharding
                self.cache = (
                    jax.jit(lambda: jnp.zeros(self.shape, jnp.int8),
                            out_shardings=config.cache_sharding)(),
                    jax.jit(lambda: jnp.zeros(self.scales_shape, jnp.float32),
                            out_shardings=ssharding)())
            else:
                self.cache = jax.jit(lambda: jnp.zeros(self.shape, self.dtype),
                                     out_shardings=config.cache_sharding)()
        elif self.quantized:
            # int8 data + per-slot-vector fp32 dequant scales: 1 +
            # 4/head_dim bytes per element instead of 2 — half the KV HBM,
            # double the schedulable batch at the same budget
            self.cache = (jnp.zeros(self.shape, jnp.int8),
                          jnp.zeros(self.scales_shape, jnp.float32))
        else:
            self.cache = jnp.zeros(self.shape, dtype=self.dtype)

    @property
    def per_token_bytes(self) -> int:
        return per_token_kv_bytes(self._config)

    def update(self, new_cache: jax.Array) -> None:
        """Install the updated cache returned by a forward (donated swap)."""
        self.cache = new_cache

    @staticmethod
    def required_blocks(tokens: int, block_size: int) -> int:
        return (tokens + block_size - 1) // block_size


def per_token_kv_bytes(config: KVCacheConfig) -> int:
    """One source of truth for KV bytes/token: int8 data + fp32 per-vector
    scale, or the plain dtype itemsize."""
    n_layers, n_kv, head_dim = config.cache_shape
    if str(config.cache_dtype) == "int8":
        return n_layers * 2 * n_kv * (head_dim * 1 + 4)  # int8 + scale
    itemsize = jnp.dtype(resolve_dtype(config.cache_dtype, jnp.bfloat16)).itemsize
    return n_layers * 2 * n_kv * head_dim * itemsize


def estimate_kv_blocks(config: KVCacheConfig, hbm_bytes: int, fraction: float) -> int:
    """Size the cache from an HBM budget (reference memory_config 'reserve')."""
    per_block = per_token_kv_bytes(config) * config.block_size
    return max(1, int(hbm_bytes * fraction) // per_block)
