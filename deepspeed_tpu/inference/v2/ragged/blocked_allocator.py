"""Free-list allocator for paged KV-cache blocks.

Reference: ``deepspeed/inference/v2/ragged/blocked_allocator.py:11`` — the
same linked-list free list, but host-side numpy (no device traffic: block ids
only ever reach the device inside the batch's dense block-table array).
"""

from typing import Iterable, Union

import numpy as np


class BlockedAllocator:

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 1:
            raise ValueError(f"Blocked KV-cache must have at least 1 block, provided {num_blocks}")
        self._num_blocks = num_blocks
        self._blocks = np.arange(1, num_blocks + 1, dtype=np.int32)
        self._head = 0
        self._free_blocks = num_blocks

    def allocate(self, num_blocks: int) -> np.ndarray:
        if num_blocks > self._free_blocks:
            raise ValueError(f"Not enough free blocks in the KV-cache to allocate {num_blocks}")
        allocated = np.empty(num_blocks, dtype=np.int32)
        for i in range(num_blocks):
            allocated[i] = self._head
            self._head = int(self._blocks[self._head])
            self._blocks[allocated[i]] = -1  # mark used
            self._free_blocks -= 1
        return allocated

    def free(self, blocks: Union[Iterable[int], int]) -> None:
        if isinstance(blocks, (int, np.integer)):
            blocks = [int(blocks)]
        blocks = [int(b) for b in blocks]
        seen = set()
        for b in blocks:
            if b < 0 or b >= self._num_blocks:
                raise ValueError(f"Invalid block {b} provided to free")
            if self._blocks[b] != -1 or b in seen:
                raise ValueError(f"Block {b} is already free")
            seen.add(b)
        for b in blocks:
            self._blocks[b] = self._head
            self._head = b
            self._free_blocks += 1

    @property
    def free_blocks(self) -> int:
        return self._free_blocks

    @property
    def total_blocks(self) -> int:
        return self._num_blocks
