"""Ragged continuous-batching inference engine (FastGen-class).

Rebuild of reference ``deepspeed/inference/v2`` for TPU: paged KV cache with
dense int32 block tables, bucketed compile cache instead of dynamic shapes,
Dynamic SplitFuse scheduling semantics (``can_schedule``/``query``).
"""

from .config_v2 import (RaggedInferenceEngineConfig, DSStateManagerConfig,
                        KVCacheConfig, SamplingConfig,
                        ServingResilienceConfig, DurableServingConfig)
from .scheduling_utils import (SchedulingResult, SchedulingError,
                               DeadlineExceeded, SchedulerOverloaded)
from .engine_v2 import (InferenceEngineV2, SampleSpec, build_llama_engine,
                        load_engine)
from .journal import RequestJournal, JournalEntry, ServingCrash, journal_dir
from .server import (ServingScheduler, RequestHandle, serve,
                     install_sigterm_handoff)
from .supervisor import ServingSupervisor
from .pipeline import InferencePipeline, pipeline
