"""Ragged continuous-batching inference engine (FastGen-class).

Rebuild of reference ``deepspeed/inference/v2`` for TPU: paged KV cache with
dense int32 block tables, bucketed compile cache instead of dynamic shapes,
Dynamic SplitFuse scheduling semantics (``can_schedule``/``query``).
"""

from .config_v2 import (RaggedInferenceEngineConfig, DSStateManagerConfig,
                        KVCacheConfig, SamplingConfig,
                        ServingResilienceConfig)
from .scheduling_utils import (SchedulingResult, SchedulingError,
                               DeadlineExceeded, SchedulerOverloaded)
from .engine_v2 import (InferenceEngineV2, SampleSpec, build_llama_engine,
                        load_engine)
from .server import ServingScheduler, RequestHandle, serve
from .pipeline import InferencePipeline, pipeline
