"""Crash-consistent write-ahead request journal for the serving daemon.

Durability contract: every admitted request is journaled (prompt, sampling
params, seed, deadlines) before the caller's submit() returns, and the
emitted-token high-water mark + PRNG key-burn count are appended as the
request progresses.  After a daemon crash the journal is scanned, unfinished
requests are re-admitted with their original uids, and — because the
per-request key chains are deterministic (one counted burn per sampled
token/window, batch-composition independent) — the resumed streams continue
byte-identically to an uninterrupted run.

On-disk format: a single append-only segment of CRC-framed records::

    MAGIC(4 = b"DSJ1") | u32 payload_len | u32 crc32(payload) | JSON payload

Three ops: ``admit`` (full request spec), ``progress`` (token delta +
cumulative key burns, optionally logprobs), ``finish`` (request left the
scheduler: done/cancelled/errored/expired).  Admit and finish records are
fsync'd; progress records are flushed (fsync'd too under
``fsync_policy="always"``) — losing the tail of the progress chain only
means re-generating a few tokens deterministically, never corrupting state.

Recovery is per-record: a CRC mismatch quarantines that record alone.  If
the frame boundary is still trustworthy (the next bytes are a frame MAGIC,
or EOF) the scan resumes at the next record; a torn frame (bad length /
truncated payload) resyncs by scanning forward for the next MAGIC.  A
quarantined progress record freezes that request's high-water mark at the
last consistent prefix — deterministic replay regenerates the lost suffix,
and reconnecting clients dedupe by ``from_token`` offset, so nothing
double-emits.

Compaction rewrites the live (unfinished) state through the same
torn-write-safe tmp + fsync + ``os.replace`` idiom as
``checkpoint/engine.py``, triggered every ``compact_every`` finish records
and once on recovery (healing torn tails).

The journal directory is never inside the repo tree: ``$DS_TPU_JOURNAL_DIR``
else ``$XDG_CACHE_HOME/deepspeed_tpu/journal`` else
``~/.cache/deepspeed_tpu/journal`` — the same precedence chain as the
compile/attn caches.
"""

import json
import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ...observability import get_registry
from ...utils.fault_injection import get_fault_injector
from ...utils.logging import logger

# Durability-cost observability (process registry, resolved at import):
# where a request's time goes when the WAL is on — append (encode+write),
# fsync (the durability boundary), and boot-time replay scans.
_obs = get_registry()
_append_seconds = _obs.histogram(
    "ds_journal_append_seconds", "One journal record append (write+flush)")
_fsync_seconds = _obs.histogram(
    "ds_journal_fsync_seconds", "One journal fsync (durability boundary)")
_replay_seconds = _obs.histogram(
    "ds_journal_replay_seconds", "One recover() scan+compact at boot")
_appends_total = _obs.counter(
    "ds_journal_appends_total", "Journal records appended")
_fsyncs_total = _obs.counter("ds_journal_fsyncs_total", "Journal fsyncs")

MAGIC = b"DSJ1"
_HEADER = struct.Struct("<II")  # payload_len, crc32
# a single record is a request spec or a token delta — anything beyond this
# is a corrupt length field, not a real record; resync instead of allocating
_MAX_RECORD = 1 << 26
SEGMENT_NAME = "requests.wal"


def journal_dir() -> str:
    """Resolved journal directory (not created). Env override first, then
    XDG, then ``~/.cache`` — never a repo-relative default."""
    env = os.environ.get("DS_TPU_JOURNAL_DIR")
    if env:
        return os.path.expanduser(env)
    xdg = os.environ.get("XDG_CACHE_HOME")
    base = os.path.expanduser(xdg) if xdg else os.path.expanduser("~/.cache")
    return os.path.join(base, "deepspeed_tpu", "journal")


@dataclass
class JournalEntry:
    """One unfinished request recovered from the journal."""
    uid: int
    prompt: List[int]
    params: Dict
    tokens: List[int] = field(default_factory=list)
    logprobs: List[float] = field(default_factory=list)
    key_burns: int = 0
    deadline_wall: Optional[float] = None
    queue_deadline_wall: Optional[float] = None


def _frame(payload: bytes) -> bytes:
    return MAGIC + _HEADER.pack(len(payload), zlib.crc32(payload) & 0xffffffff) + payload


def _encode(rec: dict) -> bytes:
    return _frame(json.dumps(rec, separators=(",", ":")).encode("utf-8"))


def _apply(state: Dict[int, dict], order: List[int], rec: dict) -> None:
    """Fold one valid record into the per-uid recovery state."""
    op, uid = rec.get("op"), rec.get("uid")
    if op == "admit" and isinstance(uid, int):
        state[uid] = {"admit": rec, "tokens": [], "lps": [], "burns": 0,
                      "frozen": False}
        if uid not in order:
            order.append(uid)
    elif op == "progress":
        st = state.get(uid)
        if st is None or st["frozen"]:
            return
        toks = rec.get("tokens", [])
        if rec.get("n_out") != len(st["tokens"]) + len(toks):
            # a progress record in the chain was lost/quarantined: freeze the
            # high-water mark at the last consistent prefix — deterministic
            # replay regenerates the suffix, so this only costs recompute
            st["frozen"] = True
            return
        st["tokens"].extend(toks)
        st["burns"] = int(rec.get("burns", st["burns"]))
        if "lps" in rec:
            st["lps"].extend(rec["lps"])
    elif op == "finish":
        state.pop(uid, None)


def _scan(buf: bytes) -> Tuple[Dict[int, dict], List[int], int]:
    """Decode a segment, quarantining bad records individually.

    Returns ``(state_by_uid, admit_order, quarantined_count)``."""
    state: Dict[int, dict] = {}
    order: List[int] = []
    bad = 0
    i, n = 0, len(buf)
    while i < n:
        if buf[i:i + 4] != MAGIC:
            bad += 1
            j = buf.find(MAGIC, i + 1)
            if j < 0:
                break
            i = j
            continue
        if i + 12 > n:
            bad += 1
            break
        length, crc = _HEADER.unpack_from(buf, i + 4)
        end = i + 12 + length
        if length > _MAX_RECORD or end > n:
            # torn frame: the length field overruns the segment (or is
            # garbage) — resync on the next frame magic
            bad += 1
            j = buf.find(MAGIC, i + 4)
            if j < 0:
                break
            i = j
            continue
        payload = buf[i + 12:end]
        if zlib.crc32(payload) & 0xffffffff != crc:
            bad += 1
            # in-place corruption with an intact frame boundary (next bytes
            # are a frame start, or EOF): quarantine this record only
            if end == n or buf[end:end + 4] == MAGIC:
                i = end
                continue
            j = buf.find(MAGIC, i + 4)
            if j < 0:
                break
            i = j
            continue
        try:
            rec = json.loads(payload)
        except ValueError:
            bad += 1
            i = end
            continue
        _apply(state, order, rec)
        i = end
    return state, order, bad


def _entries_from_state(state: Dict[int, dict],
                        order: List[int]) -> List[JournalEntry]:
    """Unfinished requests in admit order from a scanned per-uid state."""
    entries = []
    for uid in order:
        st = state.get(uid)
        if st is None:
            continue
        adm = st["admit"]
        entries.append(JournalEntry(
            uid=uid, prompt=list(adm.get("prompt", [])),
            params=dict(adm.get("params", {})),
            tokens=list(st["tokens"]), logprobs=list(st["lps"]),
            key_burns=int(st["burns"]),
            deadline_wall=adm.get("dl"),
            queue_deadline_wall=adm.get("qdl")))
    return entries


def _state_frames(state: Dict[int, dict], order: List[int]) -> bytes:
    """Serialize the unfinished per-uid state back into the portable
    CRC-framed wire format (one admit + at most one folded progress record
    per request) — the same shape ``_compact_locked`` writes to disk, and
    the payload ``GET /journal/export`` ships between replicas."""
    out = []
    for uid in order:
        st = state.get(uid)
        if st is None:
            continue
        out.append(_encode(st["admit"]))
        if st["tokens"] or st["burns"]:
            rec = {"op": "progress", "uid": uid, "tokens": st["tokens"],
                   "n_out": len(st["tokens"]), "burns": st["burns"]}
            if st["lps"]:
                rec["lps"] = st["lps"]
            out.append(_encode(rec))
    return b"".join(out)


def entries_from_frames(buf: bytes) -> Tuple[List[JournalEntry], int]:
    """Decode a portable frame stream (a ``/journal/export`` body, or a
    dead replica's raw WAL segment) into unfinished entries. Damaged
    records quarantine individually exactly like boot-time recovery.
    Returns ``(entries, quarantined_count)``."""
    state, order, bad = _scan(buf)
    return _entries_from_state(state, order), bad


class RequestJournal:
    """Append-only WAL over one segment file, with in-memory mirror.

    Thread-safe: ``submit()`` appends admit records from HTTP threads while
    the scheduler thread appends progress/finish records."""

    def __init__(self, directory: Optional[str] = None,
                 fsync_policy: str = "admit", compact_every: int = 64):
        if fsync_policy not in ("admit", "always", "never"):
            raise ValueError(f"fsync_policy must be admit|always|never, "
                             f"got {fsync_policy!r}")
        self.dir = os.path.expanduser(directory) if directory else journal_dir()
        self.path = os.path.join(self.dir, SEGMENT_NAME)
        self.fsync_policy = fsync_policy
        self.compact_every = max(1, int(compact_every))
        self.quarantined_records = 0
        self._lock = threading.Lock()
        self._fh = None
        self._finished_since_compact = 0
        # mirror of the unfinished on-disk state, by uid — drives depth and
        # compaction without re-scanning the segment
        self._state: Dict[int, dict] = {}
        self._order: List[int] = []

    # ------------------------------------------------------------------ io

    def _open(self):
        if self._fh is None:
            os.makedirs(self.dir, exist_ok=True)
            self._fh = open(self.path, "ab")
        return self._fh

    def _sync(self, force: bool):
        fh = self._fh
        if fh is None:
            return
        fh.flush()
        if force or self.fsync_policy == "always":
            t0 = time.monotonic()
            os.fsync(fh.fileno())
            _fsync_seconds.record(time.monotonic() - t0)
            _fsyncs_total.inc()

    def _append(self, rec: dict, sync: bool):
        t_app = time.monotonic()
        frame = _encode(rec)
        fh = self._open()
        inj = get_fault_injector()
        if inj.enabled:
            if inj.fire("journal.torn_write", path=self.path) is not None:
                # simulate a crash mid-write: only half the frame lands
                fh.write(frame[:max(5, len(frame) // 2)])
                self._sync(sync and self.fsync_policy != "never")
                return
            if inj.fire("journal.corrupt_record", path=self.path) is not None:
                # flip a payload byte; the CRC header stays stale so the
                # scanner quarantines exactly this record
                mut = bytearray(frame)
                mut[12 + (len(frame) - 12) // 2] ^= 0xFF
                frame = bytes(mut)
        fh.write(frame)
        self._sync(sync and self.fsync_policy != "never")
        _append_seconds.record(time.monotonic() - t_app)
        _appends_total.inc()

    # ------------------------------------------------------------- records

    def record_admit(self, uid: int, prompt: List[int], params: dict,
                     deadline_wall: Optional[float] = None,
                     queue_deadline_wall: Optional[float] = None):
        rec = {"op": "admit", "uid": int(uid), "prompt": list(prompt),
               "params": params, "dl": deadline_wall,
               "qdl": queue_deadline_wall}
        with self._lock:
            self._append(rec, sync=True)
            _apply(self._state, self._order, rec)

    def record_progress(self, uid: int, new_tokens: List[int], n_out: int,
                        key_burns: int, logprobs: Optional[List[float]] = None):
        rec = {"op": "progress", "uid": int(uid),
               "tokens": [int(t) for t in new_tokens], "n_out": int(n_out),
               "burns": int(key_burns)}
        if logprobs is not None:
            rec["lps"] = [float(x) for x in logprobs]
        with self._lock:
            self._append(rec, sync=False)
            _apply(self._state, self._order, rec)

    def record_finish(self, uid: int):
        rec = {"op": "finish", "uid": int(uid)}
        with self._lock:
            self._append(rec, sync=True)
            _apply(self._state, self._order, rec)
            self._finished_since_compact += 1
            if self._finished_since_compact >= self.compact_every:
                self._compact_locked()

    def checkpoint(self):
        """Flush + fsync whatever has been appended (SIGTERM handoff)."""
        with self._lock:
            self._sync(force=True)

    @property
    def depth(self) -> int:
        """Unfinished (admitted, not finished) requests on record."""
        with self._lock:
            return len(self._state)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._sync(force=True)
                self._fh.close()
                self._fh = None

    # ---------------------------------------------------------- compaction

    def _compact_locked(self):
        """Rewrite the segment with only the unfinished state — tmp, fsync,
        atomic replace (same torn-write-safe commit as checkpoint/engine)."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.makedirs(self.dir, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_state_frames(self._state, self._order))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._order = [u for u in self._order if u in self._state]
        self._finished_since_compact = 0

    def compact(self):
        with self._lock:
            self._compact_locked()

    def export_frames(self) -> Tuple[bytes, int]:
        """Snapshot the unfinished state as a portable frame stream (the
        ``GET /journal/export`` body): byte-compatible with the on-disk
        segment, so the importer reuses the recovery scanner verbatim.
        Returns ``(frames, depth)``."""
        with self._lock:
            self._sync(force=True)
            return (_state_frames(self._state, self._order),
                    len(self._state))

    # ------------------------------------------------------------ recovery

    def recover(self) -> List[JournalEntry]:
        """Scan the segment, rebuild the mirror, compact (healing any torn
        tail), and return the unfinished requests in admit order."""
        with self._lock:
            t_rec = time.monotonic()
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            buf = b""
            if os.path.exists(self.path):
                with open(self.path, "rb") as f:
                    buf = f.read()
            state, order, bad = _scan(buf)
            self.quarantined_records = bad
            if bad:
                logger.warning(
                    "[journal] quarantined %d unreadable record(s) in %s; "
                    "remaining requests replay from their last consistent "
                    "high-water mark", bad, self.path)
            self._state, self._order = state, order
            self._compact_locked()
            entries = _entries_from_state(state, order)
            _replay_seconds.record(time.monotonic() - t_rec)
            return entries


class ServingCrash(BaseException):
    """Injected daemon crash (``serve.crash`` mode="drop").

    Derives from BaseException on purpose: it must sail past the per-tick
    ``retry_with_backoff(exceptions=(Exception,))`` boundary AND the bisect
    quarantine, killing the scheduler loop exactly like a real abort — the
    journal is preserved and the next boot replays it."""
