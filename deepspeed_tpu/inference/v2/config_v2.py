"""Inference-v2 configuration tree.

Reference: ``deepspeed/inference/v2/config_v2.py`` (RaggedInferenceEngineConfig)
and ``inference/v2/ragged/manager_configs.py`` (DSStateManagerConfig,
KVCacheConfig). Same knobs, pydantic-validated, TPU notes where semantics
shift (static shapes → bucketing).
"""

from typing import Any, Dict, Optional, Tuple

from pydantic import Field, model_validator
from pydantic_core import PydanticCustomError

from ...config.config_utils import ConfigModel


class KVCacheConfig(ConfigModel):
    """Per-token cache geometry (reference manager_configs.py:28)."""
    type: str = "dense"
    block_size: int = 128
    num_allocation_groups: int = 1
    # (num_layers, num_kv_heads, head_size) per token
    cache_shape: Tuple[int, int, int] = (1, 1, 64)
    cache_dtype: str = "bfloat16"
    max_blocks_per_allocation_group: int = 64
    # TP serving: NamedSharding the cache is ALLOCATED under (head dim over
    # the model axis) — allocating unsharded first would OOM exactly the
    # tp-sized caches the sharding exists for. None = default placement.
    cache_sharding: Optional[Any] = None


class DSStateManagerConfig(ConfigModel):
    """Reference manager_configs.py:DSStateManagerConfig."""
    max_tracked_sequences: int = 2048
    """Max sequences the state manager tracks (KV + metadata slots)."""

    max_ragged_batch_size: int = 768
    """Max total tokens in one ragged forward (Dynamic SplitFuse budget)."""

    max_ragged_sequence_count: int = 512
    """Max distinct sequences composable into one ragged batch."""

    max_context: int = 8192
    """Max per-sequence length (history + new)."""

    memory_config_mode: str = "reserve"
    memory_config_size: float = 0.85
    """'reserve': fraction of free HBM for KV blocks; 'allocate': block count."""

    offload: bool = False

    @model_validator(mode="after")
    def _check(self):
        if self.max_ragged_sequence_count > self.max_tracked_sequences:
            raise ValueError("max_ragged_sequence_count cannot exceed max_tracked_sequences")
        if self.max_ragged_sequence_count > self.max_ragged_batch_size:
            raise ValueError("max_ragged_sequence_count cannot exceed max_ragged_batch_size")
        if self.offload:
            # reference manager_configs.py:171: "Currently unsupported" —
            # reject loudly rather than accept-and-ignore. The custom error
            # type is the machine-readable reason slug: pydantic wraps any
            # ValueError raised here into a ValidationError, and the slug
            # (scheduling_utils.error_reason) is what survives the wrap for
            # the HTTP layer's structured 400 body.
            raise PydanticCustomError(
                "kv_offload_unsupported",
                "KV-cache offload is not supported")
        if self.memory_config_mode == "reserve":
            if not 0.0 < self.memory_config_size <= 1.0:
                raise ValueError(
                    "memory_config_mode='reserve' takes a fraction of free "
                    f"HBM: 0 < memory_config_size <= 1, got {self.memory_config_size}")
        elif self.memory_config_mode == "allocate":
            if self.memory_config_size < 1 or self.memory_config_size != int(self.memory_config_size):
                raise ValueError(
                    "memory_config_mode='allocate' takes an integral block "
                    f"count >= 1, got {self.memory_config_size}")
        else:
            raise ValueError("memory_config_mode must be 'reserve' or 'allocate'")
        return self


class SamplingConfig(ConfigModel):
    """On-device sampling / fused-decode knobs (TPU-specific, beyond the
    reference: the numpy sampler costs one host round-trip per token, so
    sampled requests would otherwise never see the fused K-step path)."""

    device_sampling: bool = True
    """Run temperature/top-k/top-p sampling + logit controls on device
    (ops/sampling) for requests without a host ``logits_processor``.
    False restores the per-token numpy sampler everywhere."""

    fused_sampled_decode: bool = True
    """Let device-sampled requests ride the fused K-step decode program
    (sampling inside the lax.scan). Requires ``device_sampling``. False
    keeps fused dispatch greedy-only (pre-sampling behavior)."""

    fused_speculative_decode: bool = True
    """Run speculative requests through the fused draft/verify program:
    on-device prompt-lookup drafting from a per-sequence token-history
    ring buffer, window verification, and rejection sampling inside one
    ``lax.scan`` over K windows (one dispatch + one host fetch per K
    windows). False keeps the per-token host draft/verify path — the
    parity oracle — for every speculative request."""

    spec_history_window: int = 128
    """Token-history window for prompt-lookup drafting: the device ring
    buffer holds this many trailing tokens per sequence, and the host
    fallback bounds its backward n-gram scan to the same window (the
    unbounded scan was O(history × draft) per token). Must exceed
    ``num_draft_tokens + draft_ngram`` for drafting to ever match."""

    spec_max_ngram: int = 8
    """Largest ``draft_ngram`` the fused matcher supports (the vectorized
    comparison is masked over this static width). Requests with a larger
    ngram fall back to the per-token host path."""


class ServingResilienceConfig(ConfigModel):
    """Serving-side fault tolerance (the MII front end's analog of the
    training ``resilience`` block): request deadlines, overload shedding,
    and scheduler crash isolation. Defaults are safe — the fault boundary
    (tick retry + request quarantine) is on, every policy that can refuse
    or expire a request is off until sized for a deployment."""

    enabled: bool = True
    """Master gate. False restores the pre-resilience scheduler exactly:
    no deadlines, no shedding, no tick retry/quarantine, no watchdog."""

    default_deadline_s: Optional[float] = None
    """End-to-end deadline applied to requests that don't pass their own
    ``deadline_s``. Expired requests (queued OR mid-decode) finish with
    ``DeadlineExceeded`` (HTTP 504) and release their KV. None = no
    default deadline."""

    default_queue_ttl_s: Optional[float] = None
    """Max time a request may wait UNADMITTED before it expires with
    ``DeadlineExceeded`` — bounds queue staleness under backlog without
    capping decode time. None = queued requests wait indefinitely."""

    max_queued: int = 0
    """Load shedding: reject ``submit()`` with ``SchedulerOverloaded``
    (HTTP 429 + Retry-After) once this many requests sit unadmitted.
    0 = unbounded queue (pre-resilience behavior)."""

    max_queued_tokens: int = 0
    """Shed on total queued PROMPT tokens instead of / in addition to
    request count (a few huge prompts can be as heavy as many small
    ones). A request never sheds against an empty queue, so one
    over-sized prompt still gets its admission attempt. 0 = off."""

    retry_after_s: float = 1.0
    """Client back-off hint carried by ``SchedulerOverloaded`` and the
    HTTP 429 ``Retry-After`` header."""

    max_stream_backlog: int = 256
    """Bound on each STREAMING request's undelivered-token queue: a
    consumer that stops draining (disconnected client) gets the request
    cancelled once this many tokens pile up, instead of growing host
    memory without bound. Non-streaming submits are exempt (nothing
    drains their queue by design). 0 = unbounded."""

    tick_retries: int = 2
    """Transient-fault budget of the per-tick boundary: a failing
    scheduler tick is retried this many times (with backoff) before the
    fault is treated as reproducible and bisected to the poisoning
    request."""

    tick_retry_backoff_s: float = 0.05
    """Base delay of the tick retry backoff (doubles per attempt)."""

    watchdog_s: float = 0.0
    """Stuck-tick detector: with work in flight and no scheduler progress
    for this long, ``/health`` flips to ``degraded`` (503) carrying the
    last-progress age; it recovers automatically when ticks resume.
    0 = watchdog off."""

    http_timeout_s: float = 600.0
    """Cap on how long a blocking HTTP thread waits on one request (the
    non-streaming ``result()`` and per-token stream gaps). A hung
    scheduler then returns 504 instead of pinning HTTP threads forever;
    requests with a deadline use the tighter of the two."""


class DurableServingConfig(ConfigModel):
    """Crash durability for the serving daemon: a write-ahead request
    journal plus warm-restart replay. With the journal on, a daemon crash
    (or SIGTERM handoff) loses no admitted request — the next boot re-admits
    every unfinished request with its original uid and deadline, force-feeds
    the already-emitted tokens as prefix, and fast-forwards the sampling key
    chain by the journaled burn count, so resumed greedy AND sampled streams
    continue byte-identically to an uninterrupted run."""

    enabled: bool = False
    """Master gate. False (default) keeps serving journal-free: no WAL
    writes, no replay on start — exactly the pre-durability scheduler."""

    journal_dir: Optional[str] = None
    """Journal directory. None resolves ``$DS_TPU_JOURNAL_DIR`` →
    ``$XDG_CACHE_HOME/deepspeed_tpu/journal`` → ``~/.cache/...`` (never a
    repo-relative path). Point daemon generations that should hand off to
    each other at the same directory."""

    fsync_policy: str = "admit"
    """``admit``: fsync admit/finish records (the durability boundary),
    flush-only progress records — losing a progress tail only costs
    deterministic regeneration. ``always``: fsync every record.
    ``never``: flush only (tests / throwaway deployments)."""

    compact_every: int = 64
    """Rewrite the segment (dropping finished requests) every this many
    finish records. Compaction also runs once on every recovery."""

    replay_on_start: bool = True
    """Re-admit journaled unfinished requests when the scheduler starts.
    False boots with a clean slate but keeps journaling new requests."""


class ContinuousFusionConfig(ConfigModel):
    """Continuous fused serving: keep the K-step fused decode wave hot
    under live traffic. The scheduler dispatches the fused decode program
    (JAX dispatch is async), then feeds prefill chunks and admits newly
    feasible requests WHILE the wave runs on device, harvesting the fused
    fetch only after the overlap work is enqueued — prefill and the K-step
    amortization stop being mutually exclusive modes. KV safety needs no
    extra partition machinery: the wave allocates every one of its K steps'
    blocks before dispatch (allocation IS the reservation), so an overlap
    put can only draw from what the wave left, and the eviction path is
    fenced from flushing in-flight wave members."""

    enabled: bool = True
    """Master gate. False restores the exclusive-mode scheduler exactly:
    the fused wave only runs when no prefill/admission work exists, so
    sustained arrivals degrade every decode to per-token dispatches."""

    prefill_budget_frac: float = 0.5
    """Fraction of the SplitFuse token budget spendable on prefill chunks
    inside the overlap window (while the fused program runs on device).
    The remainder tick after harvest can still feed prefills from its
    spare budget, so this bounds overlap-window work, not total prefill
    throughput per tick."""

    queue_depth_per_halving: int = 8
    """Adaptive K, queue-pressure axis: the fused window is halved once
    per this many waiting + inbox requests, shrinking toward per-token
    mode as backlog builds so a K-step wave never delays admission of a
    deep queue by more than a bounded amount. 0 disables the shrink."""

    deadline_slack_frac: float = 0.5
    """Adaptive K, deadline axis: K is capped so the wave's estimated
    duration (EWMA of measured per-step time) fits within this fraction
    of the slack to the nearest live/waiting deadline. Ignored until a
    first wave has been measured."""

    @model_validator(mode="after")
    def _check(self):
        if not 0.0 <= self.prefill_budget_frac <= 1.0:
            raise ValueError("prefill_budget_frac must be in [0, 1], got "
                             f"{self.prefill_budget_frac}")
        if self.queue_depth_per_halving < 0:
            raise ValueError("queue_depth_per_halving must be >= 0")
        if not 0.0 < self.deadline_slack_frac <= 1.0:
            raise ValueError("deadline_slack_frac must be in (0, 1], got "
                             f"{self.deadline_slack_frac}")
        return self


class DisaggregationConfig(ConfigModel):
    """Disaggregated prefill/decode serving: carve the local device set
    into a PREFILL group and a DECODE group, so long-prompt prefill chunks
    run on their own chips concurrently with the decode group's fused
    K-step wave — the continuous-fusion overlap extended from time into
    space. Completed prefix KV pages migrate to the decode group's paged
    pool through a double-buffered async ``device_put`` handoff queue
    (``inference/v2/disagg.py``); token streams stay bit-identical to the
    single-group path because routing only changes WHERE the same compiled
    programs run, never the per-sequence PRNG key chains or the sampled
    values they produce."""

    enabled: bool = False
    """Master gate. When the local device set cannot yield two non-empty
    groups (single-device hosts, ``prefill_fraction`` rounding to zero)
    the planner falls back to plain time-overlap continuous fusion rather
    than failing — unless explicit device lists were given, which must be
    honorable."""

    prefill_fraction: float = 0.5
    """Fraction of local devices carved into the prefill group (rounded,
    clamped to leave at least one decode device). Ignored when explicit
    ``prefill_devices``/``decode_devices`` lists are set."""

    prefill_devices: Optional[Tuple[int, ...]] = None
    """Explicit prefill-group device ids (``jax.local_devices()`` ids).
    Must be disjoint from ``decode_devices``; both lists are validated
    against the live device set at plan time."""

    decode_devices: Optional[Tuple[int, ...]] = None
    """Explicit decode-group device ids. When only one of the two lists is
    given, the other group takes the remaining local devices."""

    prefill_tp_size: int = 1
    """Tensor-parallel degree inside the prefill group (PR 12 sharding on
    a private per-group mesh). Must divide the prefill group size."""

    prefill_kv_blocks: Optional[int] = None
    """KV pool size of the prefill group's engine. None inherits the
    decode engine's ``num_kv_blocks`` sizing. The prefill pool only holds
    prompts in flight toward handoff, so it can run much smaller."""

    max_inflight_transfers: int = 2
    """Handoff queue depth: transfer batches in flight at once. 2 =
    double-buffered (transfer of chunk N overlaps prefill of chunk N+1);
    submitting past the cap drains the oldest batch first."""

    stall_timeout_s: float = 5.0
    """Watchdog: a handoff transfer not ready after this long counts as
    wedged — the request degrades to in-group (decode-side) prefill and
    the disagg router latches degraded, so admission never stalls behind
    a dead interconnect."""

    @model_validator(mode="after")
    def _check(self):
        if not 0.0 <= self.prefill_fraction < 1.0:
            raise ValueError("prefill_fraction must be in [0, 1), got "
                             f"{self.prefill_fraction}")
        if self.prefill_tp_size < 1:
            raise ValueError("prefill_tp_size must be >= 1")
        if self.max_inflight_transfers < 1:
            raise ValueError("max_inflight_transfers must be >= 1")
        if self.stall_timeout_s <= 0:
            raise ValueError("stall_timeout_s must be > 0")
        if (self.prefill_devices is not None and self.decode_devices is not None
                and set(self.prefill_devices) & set(self.decode_devices)):
            raise ValueError("prefill_devices and decode_devices overlap: "
                             f"{set(self.prefill_devices) & set(self.decode_devices)}")
        return self


class ObservabilityConfig(ConfigModel):
    """Serving observability: the metrics registry, per-request span
    tracer, and on-demand profiler capture (``deepspeed_tpu/observability``).
    Recording is host-side and allocation-light (pre-resolved handles, one
    bisect + bucket bump per sample), so the default is ON; every ring is
    bounded so a long-lived daemon cannot grow."""

    enabled: bool = True
    """Master gate. False skips every recording site (the scheduler holds
    no instruments object) and the HTTP observability endpoints answer
    404 — exactly the pre-observability daemon."""

    trace_requests: int = 512
    """Max request timelines held live (oldest evicted first)."""

    trace_spans_per_request: int = 512
    """Max spans retained per request timeline (a ring: a pathological
    million-token request keeps its most recent spans)."""

    trace_waves: int = 2048
    """Global ring of daemon-level spans (fused waves, restarts) backing
    the bulk ``GET /debug/trace`` Chrome export."""

    profile_dir: Optional[str] = None
    """Directory for ``POST /debug/profile`` captures. None resolves
    ``$DS_TPU_PROFILE_DIR`` → ``$XDG_CACHE_HOME/deepspeed_tpu/profiles``
    (the journal_dir resolution pattern)."""

    profile_max_seconds: float = 60.0
    """Hard cap on one profiler capture's duration; requests asking for
    longer are clamped, and an auto-stop timer enforces it."""

    @model_validator(mode="after")
    def _check(self):
        if self.trace_requests < 1 or self.trace_spans_per_request < 1:
            raise ValueError("trace ring sizes must be >= 1")
        if self.trace_waves < 1:
            raise ValueError("trace_waves must be >= 1")
        if self.profile_max_seconds <= 0:
            raise ValueError("profile_max_seconds must be > 0")
        return self


class QuantizationConfig(ConfigModel):
    quantization_mode: Optional[str] = None  # e.g. 'wf6af16' in reference


class TensorParallelConfig(ConfigModel):
    tp_size: int = 1
    # Wire dtype for the per-layer TP output collectives: None defers to
    # the DS_TPU_TP_WIRE env then the "fp" default (parallel/tp.py
    # resolve_tp_wire precedence ladder); "fp" keeps the bit-identical
    # implicit-GSPMD psum, "int8" runs the explicit blockwise-int8
    # reduce-scatter → all-gather two-step from comm/bucketing.py.
    tp_wire_dtype: Optional[str] = None
    # quantization block for the int8 wire (elements per fp32 scale+zero)
    tp_wire_block: int = 256
    # per-layer-class wire overrides, e.g. {"lm_head": "fp"} — classes are
    # parallel/tp.TP_WIRE_CLASSES ("attn_out", "mlp_out", "lm_head")
    tp_wire_overrides: dict = Field(default_factory=dict)

    @model_validator(mode="after")
    def _check(self):
        from ...parallel.tp import TP_WIRE_CLASSES, TP_WIRE_DTYPES
        if self.tp_wire_dtype is not None and \
                self.tp_wire_dtype not in TP_WIRE_DTYPES:
            raise ValueError(f"tp_wire_dtype must be one of {TP_WIRE_DTYPES} "
                             f"(or None to defer to env), got "
                             f"{self.tp_wire_dtype!r}")
        if self.tp_wire_block < 2:
            raise ValueError("tp_wire_block must be >= 2, got "
                             f"{self.tp_wire_block}")
        for cls, val in self.tp_wire_overrides.items():
            if cls not in TP_WIRE_CLASSES:
                raise ValueError(f"unknown tp_wire_overrides class {cls!r}; "
                                 f"expected one of {TP_WIRE_CLASSES}")
            if val not in TP_WIRE_DTYPES:
                raise ValueError(f"tp_wire_overrides[{cls!r}] must be one of "
                                 f"{TP_WIRE_DTYPES}, got {val!r}")
        return self


class AdaptersConfig(ConfigModel):
    """Multi-LoRA adapter serving (beyond the reference — the serving-side
    use of ``linear/config.LoRAConfig``): hot-swappable adapters batched
    into ONE fused decode wave. The registry keeps up to
    ``max_live_adapters`` adapters device-resident as slots of a stacked
    factor bank; every request row carries a slot index and the fused
    programs apply ``y += B[slot] @ (A[slot] @ x) * scale`` via the
    sort-by-slot grouped matmul, so a mixed-adapter wave stays one
    dispatch per K window and the compile key never depends on WHICH
    adapters are live — ``POST /adapters/load`` writes factor values into
    a pre-shaped bank (no recompile, no restart)."""

    enabled: bool = False
    """Master gate. False builds no registry and leaves every traced
    program byte-identical to the adapter-free engine."""

    registry_dir: Optional[str] = None
    """Directory scanned at boot: each subdirectory holding an
    ``adapter_config.json`` + ``weights.npz`` pair registers as one
    adapter (name = subdirectory name). ``POST /adapters/load`` can add
    more at runtime from any path under this root."""

    max_live_adapters: int = 8
    """Device-resident adapter slots (slot 0 is the always-present
    identity adapter and does not count). Loading past the cap evicts the
    least-recently-used UNPINNED slot; slots pinned by in-flight requests
    never evict."""

    slot_rank_pad: int = 16
    """Every slot's factors are zero-padded to this rank, so adapters of
    different true ranks share one bank shape (zero rank columns are
    mathematically inert). Adapters with ``lora_r`` above this are
    refused at load."""

    targets: Tuple[str, ...] = ("q_proj", "v_proj")
    """Projection kernels the bank covers (``linear.config.LORA_TARGETS``
    subset). An adapter may cover a subset of these; targets it omits get
    zero factors. Adapters targeting kernels OUTSIDE this set are refused
    at load — silently dropping a trained factor would serve wrong
    weights."""

    @model_validator(mode="after")
    def _check(self):
        from ...linear.config import LORA_TARGETS
        if self.max_live_adapters < 1:
            raise ValueError("max_live_adapters must be >= 1, got "
                             f"{self.max_live_adapters}")
        if self.slot_rank_pad < 1:
            raise ValueError("slot_rank_pad must be >= 1, got "
                             f"{self.slot_rank_pad}")
        if not self.targets:
            raise ValueError("adapters.targets must name at least one kernel")
        for t in self.targets:
            if t not in LORA_TARGETS:
                raise ValueError(f"unknown adapter target {t!r}; expected a "
                                 f"subset of {LORA_TARGETS}")
        return self


class TenantConfig(ConfigModel):
    """One tenant's scheduling contract (beyond the reference — the
    multi-tenant scenario layer). Tenants are soft-isolated: admission and
    the prefill budget are divided by WEIGHTED FAIR SHARE (a tenant at
    weight 3 gets 3× the delivered tokens of a weight-1 tenant under
    contention), idle share redistributes work-conservingly, and the
    per-tenant caps shed a noisy tenant before it can starve the wave."""

    weight: float = 1.0
    """Fair-share weight (> 0): delivered-token ratio under contention."""

    priority: int = 0
    """Strict admission tier: higher-priority tenants admit first; weights
    arbitrate WITHIN a tier."""

    max_live_tokens: int = 0
    """Cap on this tenant's concurrently live tokens (prompt + generated
    budget of admitted requests); 0 = uncapped. A capped tenant's unused
    share flows to others (work-conserving)."""

    max_queued: int = 0
    """Per-tenant admission queue cap (sheds with 429 like the global
    ``serving_resilience.max_queued``); 0 = only the global cap applies."""

    default_adapter: Optional[str] = None
    """LoRA adapter applied to this tenant's requests that carry no
    explicit ``adapter`` field (resolved against the adapter registry at
    submit; unknown names fail the submit with a structured 400, never a
    silent fallback to base weights). None = base model."""

    @model_validator(mode="after")
    def _check(self):
        if self.weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {self.weight}")
        if self.max_live_tokens < 0 or self.max_queued < 0:
            raise ValueError("tenant caps must be >= 0 (0 = uncapped)")
        return self


class RaggedInferenceEngineConfig(ConfigModel):
    """Reference config_v2.py:RaggedInferenceEngineConfig."""
    tensor_parallel: TensorParallelConfig = Field(default_factory=TensorParallelConfig)
    state_manager: DSStateManagerConfig = Field(default_factory=DSStateManagerConfig)
    quantization: QuantizationConfig = Field(default_factory=QuantizationConfig)
    sampling: SamplingConfig = Field(default_factory=SamplingConfig)
    serving_resilience: ServingResilienceConfig = Field(
        default_factory=ServingResilienceConfig)
    durable_serving: DurableServingConfig = Field(
        default_factory=DurableServingConfig)
    continuous_fusion: ContinuousFusionConfig = Field(
        default_factory=ContinuousFusionConfig)
    disaggregation: DisaggregationConfig = Field(
        default_factory=DisaggregationConfig)
    observability: ObservabilityConfig = Field(
        default_factory=ObservabilityConfig)

    # TPU-specific: number of KV blocks to allocate (overrides memory_config
    # sizing when set — tests and CPU runs need deterministic small caches).
    num_kv_blocks: Optional[int] = None

    # Automatic prefix caching (beyond the reference — vLLM-class):
    # content-addressed reuse of full prompt KV blocks across sequences.
    # Disabled for sliding-window models (their trailing-window release
    # would free shared blocks).
    enable_prefix_caching: bool = False

    # Multi-tenant weighted-fair scheduling: per-tenant contracts keyed by
    # the ``tenant`` id requests carry. Unknown tenants get the "default"
    # entry if present, else TenantConfig() (weight 1, no caps) — an empty
    # dict keeps the scheduler exactly single-tenant.
    tenants: Dict[str, TenantConfig] = Field(default_factory=dict)

    # Multi-LoRA adapter serving: hot-swappable adapters batched into one
    # fused decode wave (inference/v2/adapters).
    adapters: AdaptersConfig = Field(default_factory=AdaptersConfig)
