"""``pipeline()`` — text-in/text-out convenience over the v2 engine.

The MII surface the reference ecosystem deploys FastGen through
(``mii.pipeline("model-name")`` → callable): here it composes the in-tree
pieces — ``module_inject.convert_hf_safetensors`` (streaming HF checkpoint
conversion, arch auto-detected from ``config.json``'s ``model_type``),
``build_llama_engine`` (ragged serving engine; reference
``engine_factory.py build_hf_engine``), and an optional HF tokenizer — into
one call. Token-id prompts work without a tokenizer; text prompts need one.
"""

import json
import os
from typing import List, Optional, Sequence, Union

from .config_v2 import RaggedInferenceEngineConfig
from .engine_v2 import InferenceEngineV2, build_llama_engine


def _encode_stop(tokenizer, s: str):
    """Tokenize a stop string WITHOUT special tokens: a BOS prepended by
    the default encode() can never appear in an output tail, so the stop
    sequence would silently never fire."""
    try:
        return tokenizer.encode(s, add_special_tokens=False)
    except TypeError:  # tokenizer without the kwarg
        return tokenizer.encode(s)


class InferencePipeline:
    """Callable bundle of a serving engine + (optional) tokenizer."""

    def __init__(self, engine: InferenceEngineV2, tokenizer=None):
        self.engine = engine
        self.tokenizer = tokenizer

    def __call__(self, prompts: Union[str, Sequence],
                 max_new_tokens: int = 64, **gen_kwargs):
        """Generate for one prompt or a batch. Strings are tokenized (and
        the outputs detokenized); token-id lists pass through as ids."""
        import numpy as np
        single = isinstance(prompts, str) or (
            len(prompts) > 0 and isinstance(prompts[0], (int, np.integer)))
        batch = [prompts] if single else list(prompts)
        text_in = any(isinstance(p, str) for p in batch)
        if text_in:
            if self.tokenizer is None:
                raise ValueError("text prompts need a tokenizer; pass "
                                 "tokenizer= to pipeline() or use token ids")
            batch = [self.tokenizer.encode(p) if isinstance(p, str) else p
                     for p in batch]
        if self.tokenizer is not None and gen_kwargs.get(
                "eos_token_id", None) is None:
            eos = getattr(self.tokenizer, "eos_token_id", None)
            if eos is not None:
                gen_kwargs["eos_token_id"] = eos
        stop = gen_kwargs.get("stop")
        if stop is not None and self.tokenizer is not None:
            if isinstance(stop, str):
                stop = [stop]
            gen_kwargs["stop"] = [
                _encode_stop(self.tokenizer, s) if isinstance(s, str) else s
                for s in stop]
        outs = self.engine.generate(batch, max_new_tokens=max_new_tokens,
                                    **gen_kwargs)
        if text_in:
            outs = [self.tokenizer.decode(o) for o in outs]
        return outs[0] if single else outs

    def serve(self, host: str = "127.0.0.1", port: int = 8000,
              block: bool = True):
        """Lift this pipeline into the HTTP serving daemon (mii.serve)."""
        from .server import serve
        return serve(self.engine, host, port, self.tokenizer, block=block)


def pipeline(model_dir: str,
             arch: Optional[str] = None,
             engine_config: Optional[RaggedInferenceEngineConfig] = None,
             dtype=None,
             tokenizer: Union[None, str, object] = "auto",
             lora: Optional[str] = None,
             **engine_kwargs) -> InferencePipeline:
    """Build a text-generation pipeline from a HF checkpoint directory.

    Args:
      model_dir: directory with ``config.json`` + ``*.safetensors`` shards.
      arch: injection-policy name; default = ``config.json``'s
        ``model_type`` (reference replace_policy auto-selection).
      tokenizer: "auto" loads from model_dir via transformers when
        available (silently none if not), None disables, or pass a
        ready tokenizer object / name.
      lora: PEFT adapter directory (adapter_config.json +
        adapter_model.safetensors) merged into the base weights before
        the engine is built.
      engine_kwargs: forwarded to ``build_llama_engine`` (quantize,
        kv_cache_dtype, kv_block_size, ...).
    """
    import jax.numpy as jnp

    from ...module_inject import convert_hf_safetensors, merge_peft_adapter

    with open(os.path.join(model_dir, "config.json")) as f:
        hf_config = json.load(f)
    arch = arch or hf_config.get("model_type")
    if not arch:
        raise ValueError("config.json has no model_type; pass arch=")
    cfg, params = convert_hf_safetensors(arch, model_dir, hf_config,
                                         dtype=dtype or jnp.bfloat16)
    if lora is not None:
        params = merge_peft_adapter(arch, cfg, params, adapter_dir=lora)
    engine = build_llama_engine(cfg, params=params,
                                engine_config=engine_config,
                                dtype=dtype, **engine_kwargs)

    tok = None
    if tokenizer == "auto":
        try:
            from transformers import AutoTokenizer
            tok = AutoTokenizer.from_pretrained(model_dir)
        except Exception:  # noqa: BLE001 — tokenizer files optional
            tok = None
    elif isinstance(tokenizer, str):
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(tokenizer)
    else:
        tok = tokenizer
    return InferencePipeline(engine, tok)
