"""Replica fleet router: health-gated balancing, WAL live migration,
queue-depth autoscaling.

One ``ds_serve`` daemon is a total outage waiting to happen; the reference
DeepSpeed survives node churn with its elasticity subsystem. This module is
the serving-side analog: a front-tier daemon that supervises N replicas
(:class:`ReplicaFleet`, the pool generalization of
``supervisor.ServingSupervisor``) and fronts them with one HTTP surface
(:func:`create_router_server`).

Why this is *correct* and not merely available: every replica runs the
write-ahead request journal (PR 8), whose frame stream is portable — any
unfinished entry replays byte-identically on any identically-built peer.
So replica death is not request death:

* **crash** (SIGKILL, OOM) — the dead replica's WAL segment is read
  straight off disk (the on-disk bytes ARE the export format) and POSTed
  to a healthy peer's ``/journal/import``; the peer re-admits every
  unfinished request mid-run and regenerates each stream's suffix
  deterministically.
* **scale-down / sustained degraded** — the live replica's
  ``GET /journal/export`` drains it first (readiness flips to
  ``migrating``), then the same import path adopts the entries.

Clients never see the topology: submits balance onto the least-loaded
healthy replica (queue depth + live count from the probe loop's ``/health``
snapshots), refused/timed-out submits retry against a peer with
full-jittered backoff (``utils/retry``), and a stream severed mid-decode
re-attaches to the request's new owner at the client's own token
high-water mark (``GET /requests/<uid>/stream?from_token=N``) — zero gap,
zero duplicates. uid collisions across replicas cannot happen by
construction: each replica *generation* mints uids in its own stride
(``DS_SERVE_UID_BASE`` = generation x stride) and imports never bump the
peer's iterator.

An autoscaler loop grows the pool when mean queue depth or
``fused_occupancy`` run hot for ``hysteresis`` consecutive evaluations and
shrinks it (live migration first) when cold, with a cooldown between
actions so the two thresholds cannot flap. The pool ceiling defaults to
the available world size probed by ``elasticity.probe_available_world``.

Every failure leg is deterministically testable via fault sites:
``router.replica_crash`` (probe-time SIGKILL), ``router.probe_timeout``
(probe behaves timed out → quarantine after a streak → healthy probe
re-admits), ``router.migrate_stall`` (an export/import leg wedges → the
stall budget trips), ``router.split_brain_uid`` (import-side uid
collision → the entry is refused and surfaced here). When no healthy peer
exists to adopt a drained journal, the router degrades gracefully:
affected uids are error-finished with a ``Retry-After`` hint instead of
hanging the fleet.
"""

import json
import os
import random
import shlex
import signal
import socket
import subprocess
import threading
import time
import urllib.error
import urllib.request
from http.client import HTTPConnection, HTTPException
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

from ...observability import get_registry
from ...utils.fault_injection import get_fault_injector
from ...utils.logging import logger
from ...utils.retry import backoff_delay
from .journal import SEGMENT_NAME, entries_from_frames

# Fleet accounting (process registry, resolved at import).
_obs = get_registry()
_submits = _obs.counter("ds_router_submits_total",
                        "Requests admitted through the router")
_retries = _obs.counter("ds_router_retries_total",
                        "Submits retried against a peer replica")
_probe_failures = _obs.counter("ds_router_probe_failures_total",
                               "Replica health probes that failed/timed out")
_quarantines = _obs.counter("ds_router_quarantines_total",
                            "Replicas quarantined after a probe-failure streak")
_unavailable = _obs.counter("ds_router_unavailable_total",
                            "Requests refused: no healthy replica")
_reattaches = _obs.counter("ds_router_stream_reattaches_total",
                           "Severed client streams re-attached to a new owner")
_pool_size = _obs.gauge("ds_router_pool_size", "Live replica count")
_migration_seconds = _obs.histogram(
    "ds_router_migration_seconds",
    "Journal drain -> peer import wall time", lo=1e-3, hi=1e3,
    buckets_per_decade=10)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class MigrationFailed(RuntimeError):
    """No healthy peer adopted the drained journal (or a leg stalled)."""


class _Replica:
    """One supervised serving process + the router's view of its health."""

    def __init__(self, generation: int, port: int, uid_base: int,
                 journal_dir: str):
        self.generation = generation
        self.port = int(port)
        self.uid_base = int(uid_base)
        self.journal_dir = journal_dir
        self.proc: Optional[subprocess.Popen] = None
        self.state = "starting"   # starting|ok|degraded|quarantined|
        #                           migrating|dead|stopped
        self.fail_streak = 0
        self.stats: dict = {}
        self.t_launched = 0.0

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def routable(self) -> bool:
        """May new submits land here? Degraded stays routable (the
        watchdog owns recovery); quarantined/migrating/dead do not."""
        return self.state in ("ok", "degraded")

    def score(self, tenant: Optional[str] = None,
              adapter: Optional[str] = None) -> float:
        """Load score for balanced admission: queue depth + in-flight.
        With a ``tenant``, that tenant's own backlog on this replica
        (from the probed per-tenant stats) weighs in too, so one tenant's
        burst spreads across replicas instead of piling behind itself
        while the others stay globally balanced. With an ``adapter``, a
        replica already holding that adapter DEVICE-RESIDENT scores a
        bonus (one point: roughly "worth one queued request") — requests
        for one adapter gravitate to replicas that won't pay a slot write
        or an LRU eviction, without ever overriding health or gross load."""
        st = self.stats or {}
        base = float(st.get("waiting") or 0) + float(st.get("live") or 0)
        if tenant:
            t = ((st.get("tenants") or {}).get(tenant)) or {}
            base += float(t.get("queued") or 0) + float(t.get("live") or 0)
        if adapter:
            ad = st.get("adapters") or {}
            live = ad.get("live") or {}
            name = adapter.split("@", 1)[0]
            if not any(aid == adapter or aid.split("@", 1)[0] == name
                       for aid in live):
                base += 1.0
        return base

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def describe(self) -> dict:
        return {"generation": self.generation, "port": self.port,
                "state": self.state, "uid_base": self.uid_base,
                "fail_streak": self.fail_streak,
                "score": self.score(),
                "pid": self.proc.pid if self.proc else None,
                "waiting": (self.stats or {}).get("waiting"),
                "live": (self.stats or {}).get("live"),
                "fused_occupancy": (self.stats or {}).get("fused_occupancy")}


class ReplicaFleet:
    """Supervise a pool of serving replicas with health-gated membership.

    ``replica_cmd`` is the daemon argv with ``{port}`` placeholders; each
    launched generation gets a fresh journal directory and a disjoint uid
    stride via env (``DS_TPU_JOURNAL_DIR``, ``DS_SERVE_UID_BASE``), so two
    generations can never double-replay one journal or mint one uid twice.
    """

    def __init__(self, replica_cmd: Sequence[str],
                 replicas: int = 2,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 journal_root: Optional[str] = None,
                 uid_stride: int = 1_000_000,
                 probe_interval: float = 1.0,
                 probe_timeout: float = 2.0,
                 quarantine_after: int = 3,
                 ready_timeout_s: float = 120.0,
                 grace_s: float = 15.0,
                 migrate_stall_s: float = 30.0,
                 retry_after_s: float = 5.0,
                 autoscale: bool = True,
                 queue_high: float = 8.0,
                 queue_low: float = 1.0,
                 occupancy_high: float = 0.95,
                 queue_eval_interval: float = 2.0,
                 hysteresis: int = 3,
                 cooldown_s: float = 10.0,
                 env: Optional[dict] = None,
                 jitter_seed: Optional[int] = None):
        if max_replicas is None:
            from ...elasticity import probe_available_world
            max_replicas = max(int(replicas), probe_available_world())
        self.replica_cmd = list(replica_cmd)
        self.target = int(replicas)
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.journal_root = journal_root or os.path.join(
            os.path.expanduser(os.environ.get("DS_TPU_JOURNAL_DIR")
                               or "~/.cache/deepspeed_tpu/journal"), "fleet")
        self.uid_stride = int(uid_stride)
        self.probe_interval = float(probe_interval)
        self.probe_timeout = float(probe_timeout)
        self.quarantine_after = int(quarantine_after)
        self.ready_timeout_s = float(ready_timeout_s)
        self.grace_s = float(grace_s)
        self.migrate_stall_s = float(migrate_stall_s)
        self.retry_after_s = float(retry_after_s)
        self.autoscale = bool(autoscale)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.occupancy_high = float(occupancy_high)
        self.queue_eval_interval = float(queue_eval_interval)
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown_s = float(cooldown_s)
        self.base_env = dict(env if env is not None else os.environ)
        self.rng = random.Random(jitter_seed)
        self._lock = threading.RLock()
        self._pool: List[_Replica] = []
        self._generation = 0
        # uid -> replica currently owning the request (submit + migration
        # keep this current; the reattach surface routes through it)
        self._owners: Dict[int, _Replica] = {}
        # uid -> wall deadline after which a client may retry: requests
        # whose journal could not be adopted anywhere (graceful degradation)
        self._lost: Dict[int, float] = {}
        self._hot_streak = 0
        self._cold_streak = 0
        self._t_scaled = 0.0
        self._t_eval = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.migrations: List[dict] = []

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ReplicaFleet":
        os.makedirs(self.journal_root, exist_ok=True)
        for _ in range(self.target):
            self._launch_replica()
        self._thread = threading.Thread(target=self._control_loop,
                                        name="ds-router-control", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.probe_interval * 4 + 5.0)
            self._thread = None
        with self._lock:
            pool = list(self._pool)
        for r in pool:
            self._terminate(r)
        with self._lock:
            self._pool.clear()
            _pool_size.set(0)

    def _launch_replica(self) -> _Replica:
        with self._lock:
            self._generation += 1
            g = self._generation
            r = _Replica(
                generation=g, port=_free_port(),
                uid_base=g * self.uid_stride,
                journal_dir=os.path.join(self.journal_root, f"gen{g:04d}"))
            cmd = [a.replace("{port}", str(r.port)) for a in self.replica_cmd]
            env = dict(self.base_env)
            env["DS_SERVE_UID_BASE"] = str(r.uid_base)
            env["DS_TPU_JOURNAL_DIR"] = r.journal_dir
            logger.info(f"ReplicaFleet: launching replica gen{g} "
                        f"port={r.port} uid_base={r.uid_base}")
            r.proc = subprocess.Popen(cmd, env=env)
            r.t_launched = time.monotonic()
            self._pool.append(r)
            _pool_size.set(len(self._pool))
            return r

    def _terminate(self, r: _Replica) -> None:
        if r.proc is None or r.proc.poll() is not None:
            return
        r.proc.send_signal(signal.SIGTERM)
        try:
            r.proc.wait(timeout=self.grace_s)
        except subprocess.TimeoutExpired:
            logger.warning(f"ReplicaFleet: gen{r.generation} ignored SIGTERM "
                           f"for {self.grace_s}s — killing")
            r.proc.kill()
            r.proc.wait()
        r.state = "stopped"

    # ------------------------------------------------------------ probing

    def _probe(self, r: _Replica) -> None:
        """One health probe; owns the ok/degraded/quarantined transitions
        and fires crash handling when the process is gone."""
        inj = get_fault_injector()
        if inj.fire("router.replica_crash") is not None and r.alive():
            logger.warning(f"[fault-injection] SIGKILL replica "
                           f"gen{r.generation}")
            r.proc.kill()
            r.proc.wait()
        if not r.alive():
            if r.state not in ("dead", "stopped"):
                r.state = "dead"
                self._on_replica_dead(r)
            return
        timed_out = inj.fire("router.probe_timeout") is not None
        payload = None
        if not timed_out:
            try:
                req = urllib.request.Request(r.base_url + "/health")
                with urllib.request.urlopen(
                        req, timeout=self.probe_timeout) as resp:
                    payload = json.loads(resp.read())
            except urllib.error.HTTPError as e:
                # 503 carries the full stats payload (draining/degraded/
                # migrating) — the server answered; it is not timed out
                try:
                    payload = json.loads(e.read())
                except (ValueError, OSError):
                    payload = {"status": "degraded"}
            except (urllib.error.URLError, OSError, TimeoutError, ValueError):
                timed_out = True
        if timed_out:
            if r.state == "starting":
                return  # still booting: refused connects are not a signal
            r.fail_streak += 1
            _probe_failures.inc()
            if (r.fail_streak >= self.quarantine_after
                    and r.state != "quarantined"):
                logger.warning(
                    f"ReplicaFleet: gen{r.generation} quarantined after "
                    f"{r.fail_streak} probe failures")
                r.state = "quarantined"
                _quarantines.inc()
            return
        r.fail_streak = 0
        r.stats = payload
        status = payload.get("status", "ok")
        if status == "ok":
            if r.state in ("starting", "degraded", "quarantined"):
                if r.state == "quarantined":
                    logger.info(f"ReplicaFleet: gen{r.generation} healthy "
                                f"again — re-admitted")
                r.state = "ok"
        elif status == "degraded":
            r.state = "degraded"
        elif status == "migrating":
            r.state = "migrating"
        # draining/stopped answer 503 and keep their last state; the
        # process-exit path owns the dead transition

    def _control_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            with self._lock:
                pool = list(self._pool)
            for r in pool:
                if self._stop.is_set():
                    return
                try:
                    self._probe(r)
                except Exception as e:  # noqa: BLE001 — keep the loop alive
                    logger.warning(f"ReplicaFleet: probe gen{r.generation} "
                                   f"raised: {e}")
            try:
                self._reap()
                if self.autoscale:
                    self._autoscale_tick()
            except Exception as e:  # noqa: BLE001
                logger.warning(f"ReplicaFleet: control tick raised: {e}")

    def _reap(self) -> None:
        """Drop dead/stopped replicas from the pool and backfill up to the
        current target so a crash never silently shrinks capacity."""
        with self._lock:
            self._pool = [r for r in self._pool
                          if r.state not in ("dead", "stopped")]
            _pool_size.set(len(self._pool))
            deficit = self.target - len(self._pool)
        for _ in range(max(0, deficit)):
            self._launch_replica()

    # ------------------------------------------------------------ selection

    def healthy(self) -> List[_Replica]:
        with self._lock:
            return [r for r in self._pool if r.routable]

    def pick(self, exclude: Sequence[_Replica] = (),
             tenant: Optional[str] = None,
             adapter: Optional[str] = None) -> Optional[_Replica]:
        """Least-loaded routable replica (health-gated balanced admission);
        ties break by uid_base for determinism. ``tenant`` biases the
        score by that tenant's per-replica backlog; ``adapter`` biases
        toward replicas already holding the adapter device-resident."""
        cands = [r for r in self.healthy() if r not in exclude]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.score(tenant, adapter),
                                         r.uid_base))

    def owner_of(self, uid: int) -> Optional[_Replica]:
        with self._lock:
            return self._owners.get(uid)

    def note_owner(self, uid: int, r: _Replica) -> None:
        with self._lock:
            self._owners[uid] = r

    def lost_retry_after(self, uid: int) -> Optional[float]:
        """Seconds a client should wait before retrying a request whose
        journal migration failed; None if the uid is not marked lost."""
        with self._lock:
            dl = self._lost.get(uid)
        if dl is None:
            return None
        return max(1.0, dl - time.monotonic())

    # ------------------------------------------------------------ migration

    def _drain_frames(self, r: _Replica) -> bytes:
        """The replica's unfinished journal as portable CRC frames: over
        HTTP while it lives (``/journal/export`` drains it first), straight
        off its WAL segment when it is already dead — the on-disk bytes ARE
        the wire format, so a SIGKILL'd replica exports posthumously."""
        if get_fault_injector().fire("router.migrate_stall") is not None:
            time.sleep(self.migrate_stall_s)
            raise MigrationFailed(
                f"journal drain from gen{r.generation} stalled past "
                f"{self.migrate_stall_s}s")
        if r.alive():
            req = urllib.request.Request(r.base_url + "/journal/export")
            with urllib.request.urlopen(
                    req, timeout=self.migrate_stall_s) as resp:
                return resp.read()
        path = os.path.join(r.journal_dir, SEGMENT_NAME)
        try:
            with open(path, "rb") as fh:
                return fh.read()
        except FileNotFoundError:
            return b""

    def _import_into(self, target: _Replica, frames: bytes) -> dict:
        req = urllib.request.Request(
            target.base_url + "/journal/import", data=frames,
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(
                req, timeout=self.migrate_stall_s) as resp:
            return json.loads(resp.read())

    def migrate_from(self, source: _Replica) -> dict:
        """Drain ``source``'s journal and hand every unfinished request to
        a healthy peer. Peers are tried least-loaded-first with full-jitter
        backoff between attempts; with no adopter, the affected uids are
        error-finished with a Retry-After hint (graceful degradation — the
        fleet keeps serving fresh traffic) and :class:`MigrationFailed`
        raises."""
        t0 = time.monotonic()
        if source.alive():
            # a dead source stays "dead" — overwriting it would make the
            # probe loop re-detect the death and migrate the WAL twice
            source.state = "migrating"
        try:
            frames = self._drain_frames(source)
        except (MigrationFailed, urllib.error.URLError, OSError,
                TimeoutError) as e:
            # nothing drained -> nothing to mark lost here; whatever the
            # WAL held stays on disk for a later manual replay
            logger.warning(f"ReplicaFleet: drain from gen{source.generation} "
                           f"failed: {e}")
            raise MigrationFailed(str(e)) from e
        entries, bad = entries_from_frames(frames)
        uids = [e.uid for e in entries]
        if not uids:
            logger.info(f"ReplicaFleet: gen{source.generation} had no "
                        f"unfinished requests — nothing to migrate")
            return {"migrated": 0, "refused_uids": [], "uids": []}
        last_err: Optional[Exception] = None
        for attempt in range(3):
            target = self.pick(exclude=(source, ))
            if target is None:
                break
            try:
                res = self._import_into(target, frames)
            except (urllib.error.URLError, OSError, TimeoutError,
                    ValueError) as e:
                last_err = e
                logger.warning(
                    f"ReplicaFleet: import into gen{target.generation} "
                    f"failed ({e}); retrying elsewhere")
                time.sleep(backoff_delay(attempt, base_delay=0.1,
                                         max_delay=2.0, jitter="full",
                                         rng=self.rng))
                continue
            refused = set(res.get("refused_uids") or [])
            with self._lock:
                for uid in uids:
                    if uid in refused:
                        if self._owners.get(uid) is target:
                            # the target ALREADY owns it (an earlier leg
                            # of this migration landed) — not a conflict
                            continue
                        # split brain: the peer owns a uid it was never
                        # handed — surface it instead of double-serving
                        self._lost[uid] = (time.monotonic()
                                           + self.retry_after_s)
                        self._owners.pop(uid, None)
                    else:
                        self._owners[uid] = target
            dt = time.monotonic() - t0
            _migration_seconds.record(dt)
            rec = {"source_gen": source.generation,
                   "target_gen": target.generation,
                   "mode": "live" if source.alive() else "crash",
                   "migrated": len(uids) - len(refused),
                   "refused_uids": sorted(refused),
                   "quarantined_records": bad,
                   "seconds": round(dt, 4)}
            self.migrations.append(rec)
            logger.info(f"ReplicaFleet: migrated {rec['migrated']} "
                        f"request(s) gen{source.generation} -> "
                        f"gen{target.generation} in {dt:.2f}s")
            return {**rec, "uids": uids}
        # no adopter: error-finish with a retry hint instead of hanging
        with self._lock:
            dl = time.monotonic() + self.retry_after_s
            for uid in uids:
                self._lost[uid] = dl
                self._owners.pop(uid, None)
        _unavailable.inc()
        logger.error(f"ReplicaFleet: no healthy peer adopted "
                     f"gen{source.generation}'s journal — {len(uids)} "
                     f"request(s) error-finished with Retry-After")
        raise MigrationFailed(
            f"no healthy peer for {len(uids)} request(s)") from last_err

    def _on_replica_dead(self, r: _Replica) -> None:
        logger.warning(f"ReplicaFleet: replica gen{r.generation} died "
                       f"(rc={r.proc.returncode if r.proc else None})")
        try:
            self.migrate_from(r)
        except MigrationFailed:
            pass
        # _reap() backfills the pool on the next control tick

    # ------------------------------------------------------------ scaling

    def scale_up(self) -> Optional[_Replica]:
        with self._lock:
            if len(self._pool) >= self.max_replicas:
                return None
            self.target = min(self.max_replicas, self.target + 1)
        logger.info(f"ReplicaFleet: scale up -> target {self.target}")
        return self._launch_replica()

    def scale_down(self) -> bool:
        """Shrink by one: the least-loaded replica live-migrates its
        journal to a peer, then terminates (SIGTERM)."""
        with self._lock:
            if len(self._pool) <= self.min_replicas:
                return False
            victim = min((r for r in self._pool if r.routable),
                         key=lambda r: (r.score(), -r.generation),
                         default=None)
            if victim is None:
                return False
            self.target = max(self.min_replicas, self.target - 1)
        logger.info(f"ReplicaFleet: scale down gen{victim.generation} "
                    f"-> target {self.target}")
        try:
            self.migrate_from(victim)
        except MigrationFailed:
            pass  # uids already error-finished with Retry-After
        self._terminate(victim)
        return True

    def _autoscale_tick(self) -> None:
        now = time.monotonic()
        if now - self._t_eval < self.queue_eval_interval:
            return
        self._t_eval = now
        healthy = self.healthy()
        if not healthy:
            return
        depth = sum(r.score() for r in healthy) / len(healthy)
        occs = [float((r.stats or {}).get("fused_occupancy") or 0.0)
                for r in healthy]
        occ = max(occs) if occs else 0.0
        hot = depth >= self.queue_high or occ >= self.occupancy_high
        cold = depth <= self.queue_low
        self._hot_streak = self._hot_streak + 1 if hot else 0
        self._cold_streak = self._cold_streak + 1 if cold else 0
        if now - self._t_scaled < self.cooldown_s:
            return
        # hysteresis: one threshold crossing is noise; `hysteresis`
        # consecutive evaluations is a trend — and hot wins over cold
        if self._hot_streak >= self.hysteresis:
            if self.scale_up() is not None:
                self._t_scaled = now
            self._hot_streak = self._cold_streak = 0
        elif self._cold_streak >= self.hysteresis:
            if self.scale_down():
                self._t_scaled = now
            self._hot_streak = self._cold_streak = 0

    # ------------------------------------------------------------ status

    def status(self) -> dict:
        with self._lock:
            pool = [r.describe() for r in self._pool]
            lost = len(self._lost)
        healthy = sum(1 for p in pool if p["state"] in ("ok", "degraded"))
        return {"replicas": pool, "pool_size": len(pool),
                "healthy": healthy, "target": self.target,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "lost_uids": lost,
                "migrations": len(self.migrations)}

    def wait_ready(self, timeout_s: Optional[float] = None,
                   n: Optional[int] = None) -> bool:
        """Block until ``n`` (default: target) replicas probe healthy."""
        need = self.target if n is None else int(n)
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.ready_timeout_s)
        while time.monotonic() < deadline:
            if len(self.healthy()) >= need:
                return True
            time.sleep(0.05)
        return False


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------


def create_router_server(fleet: ReplicaFleet, host: str = "127.0.0.1",
                         port: int = 8080,
                         submit_retries: int = 3,
                         reattach_timeout_s: float = 60.0):
    """One client-facing surface over the fleet.

    POST /generate | /v1/completions | /v1/chat/completions — balanced
      onto the least-loaded healthy replica; refused/timed-out submits
      retry a peer with full-jitter backoff. Streaming responses proxy
      chunk-for-chunk; a replica dying mid-stream is invisible — the
      router waits for the journal migration to land and re-attaches to
      the new owner at the exact token count already forwarded.
    GET /requests/<uid>[/stream?from_token=N] — proxied to the uid's
      current owner (migration keeps the mapping fresh).
    GET /health — fleet status: 200 with >=1 routable replica, else 503
      with Retry-After. GET /metrics — ds_router_* + process registry.
    """

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet by default
            pass

        def _json(self, code: int, obj, headers=()) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _no_replica(self):
            _unavailable.inc()
            self._json(503, {"error": "no healthy replica"},
                       headers=(("Retry-After",
                                 str(max(1, round(fleet.retry_after_s)))), ))

        # -------------------------------------------------- GET surface

        def do_GET(self):
            if self.path == "/health":
                st = fleet.status()
                ok = st["healthy"] > 0
                status = "ok" if ok else "unavailable"
                hdrs = () if ok else (
                    ("Retry-After", str(max(1, round(fleet.retry_after_s)))),)
                self._json(200 if ok else 503,
                           {"status": status, **st}, headers=hdrs)
            elif self.path == "/metrics":
                body = _obs.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/requests/"):
                self._proxy_request_get()
            else:
                self._json(404, {"error": "not found"})

        def _uid_from_path(self) -> Optional[int]:
            parts = [p for p in self.path.split("?")[0].split("/") if p]
            try:
                return int(parts[1])
            except (IndexError, ValueError):
                return None

        def _proxy_request_get(self):
            uid = self._uid_from_path()
            if uid is None:
                self._json(400, {"error": "bad request id"})
                return
            ra = fleet.lost_retry_after(uid)
            if ra is not None:
                self._json(503, {"error": f"request {uid} was lost in "
                                          f"migration; retry"},
                           headers=(("Retry-After", str(max(1, round(ra)))),))
                return
            owner = fleet.owner_of(uid)
            if owner is None or not owner.routable:
                # unknown to the router (e.g. router restarted): ask around
                owner = next((r for r in fleet.healthy()
                              if self._uid_known(r, uid)), None)
                if owner is None:
                    self._json(404, {"error": f"unknown request {uid}"})
                    return
                fleet.note_owner(uid, owner)
            self._proxy_stream(owner, "GET", self.path, None, uid=uid)

        @staticmethod
        def _uid_known(r: _Replica, uid: int) -> bool:
            try:
                req = urllib.request.Request(f"{r.base_url}/requests/{uid}")
                with urllib.request.urlopen(req, timeout=2.0):
                    return True
            except urllib.error.HTTPError as e:
                return e.code != 404
            except (urllib.error.URLError, OSError, TimeoutError):
                return False

        # -------------------------------------------------- POST surface

        def do_POST(self):
            if self.path not in ("/generate", "/v1/completions",
                                 "/v1/chat/completions"):
                self._json(404, {"error": "not found"})
                return
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            try:  # tenant backlog + adapter residency bias the balancer
                parsed = json.loads(body or b"{}")
                tenant = parsed.get("tenant")
                adapter = parsed.get("adapter")
            except (ValueError, AttributeError):
                tenant = adapter = None
            tried: List[_Replica] = []
            for attempt in range(max(1, submit_retries)):
                r = fleet.pick(exclude=tried, tenant=tenant,
                               adapter=adapter)
                if r is None:
                    break
                if attempt:
                    _retries.inc()
                    time.sleep(backoff_delay(attempt - 1, base_delay=0.05,
                                             max_delay=1.0, jitter="full",
                                             rng=fleet.rng))
                tried.append(r)
                if self._forward_submit(r, body):
                    return
            self._no_replica()

        def _forward_submit(self, r: _Replica, body: bytes) -> bool:
            """One submit attempt against one replica. Returns True when a
            response was relayed to the client (success OR a definitive
            per-request error); False means "try a peer"."""
            conn = HTTPConnection("127.0.0.1", r.port,
                                  timeout=fleet.migrate_stall_s)
            try:
                conn.request("POST", self.path, body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
            except (OSError, TimeoutError):
                conn.close()
                return False  # refused/timed out pre-admission: idempotent
            if resp.status in (429, 503):
                # overloaded/draining — definitive from this replica, but a
                # peer may have room
                resp.read()
                conn.close()
                return False
            if resp.getheader("Transfer-Encoding", "").lower() == "chunked":
                uid_hdr = resp.getheader("X-DS-Request-Id")
                uid = int(uid_hdr) if uid_hdr else None
                if uid is not None:
                    fleet.note_owner(uid, r)
                _submits.inc()
                self._relay_stream(resp, conn, uid)
                return True
            payload = resp.read()
            uid_hdr = resp.getheader("X-DS-Request-Id")
            if uid_hdr:
                fleet.note_owner(int(uid_hdr), r)
            if resp.status == 200:
                _submits.inc()
            self.send_response(resp.status)
            self.send_header("Content-Type",
                             resp.getheader("Content-Type",
                                            "application/json"))
            self.send_header("Content-Length", str(len(payload)))
            if uid_hdr:
                self.send_header("X-DS-Request-Id", uid_hdr)
            self.end_headers()
            self.wfile.write(payload)
            conn.close()
            return True

        # -------------------------------------------------- streaming

        def _begin_chunked(self, uid: Optional[int]) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonl")
            self.send_header("Transfer-Encoding", "chunked")
            if uid is not None:
                self.send_header("X-DS-Request-Id", str(uid))
            self.end_headers()

        def _send_chunk(self, line: bytes) -> None:
            self.wfile.write(hex(len(line))[2:].encode() + b"\r\n"
                             + line + b"\r\n")

        def _end_chunks(self) -> None:
            self.wfile.write(b"0\r\n\r\n")

        def _pump_chunks(self, resp) -> "tuple":
            """Parse the upstream's chunked framing raw off the response
            socket, forwarding each non-empty line to the client. Returns
            ``(lines_forwarded, clean)`` — ``clean`` only when the proper
            0-length terminator arrived. http.client's own readers can't
            make this distinction (peek swallows IncompleteRead and a torn
            EOF looks identical to a clean close), and the difference is
            exactly what separates "stream done" from "replica died"."""
            fp, n = resp.fp, 0
            buf = b""
            try:
                while True:
                    size_line = fp.readline(65536)
                    if not size_line:
                        return n, False  # EOF before terminator: severed
                    try:
                        size = int(size_line.strip().split(b";")[0], 16)
                    except ValueError:
                        return n, False
                    if size == 0:
                        fp.readline(65536)  # trailing CRLF
                        return n, True
                    data = fp.read(size + 2)
                    if data is None or len(data) < size:
                        return n, False
                    buf += data[:size]
                    *lines, buf = buf.split(b"\n")
                    for line in lines:
                        if line.strip():
                            self._send_chunk(line.strip() + b"\n")
                            n += 1
            except (OSError, TimeoutError, HTTPException):
                return n, False

        def _relay_stream(self, resp, conn, uid: Optional[int],
                          already_sent: int = 0,
                          started: bool = False) -> None:
            """Proxy a chunked token stream; on a severed upstream (the
            replica died mid-decode) re-attach to the uid's new owner at
            the forwarded-token high-water mark and keep going — the
            client sees one uninterrupted stream."""
            sent = already_sent
            if not started:
                self._begin_chunked(uid)
            while True:
                n, clean = self._pump_chunks(resp)
                sent += n
                conn.close()
                if clean:
                    self._end_chunks()
                    return
                logger.warning(f"ds_router: upstream stream severed "
                               f"(uid={uid})")
                # mid-stream death: wait for the migration to land
                if uid is None:
                    self._end_chunks()
                    return
                resp, conn = self._reattach(uid, sent)
                if resp is None:
                    ra = fleet.lost_retry_after(uid) or fleet.retry_after_s
                    self._send_chunk(json.dumps(
                        {"error": f"request {uid} lost in migration",
                         "retry_after_s": round(ra, 1)}).encode() + b"\n")
                    self._end_chunks()
                    return
                # loop: relay the resumed stream (byte-identical suffix)

        def _reattach(self, uid: int, sent: int):
            """Re-open the uid's stream on its (possibly migrating) owner.
            Retried until ``reattach_timeout_s``: a dying replica can look
            alive for a few ms after SIGKILL (poll() races the reaper), so
            the first attempt may land on the corpse and get a connection
            reset, and a freshly imported uid may not be visible for one
            beat.  Returns ``(resp, conn)`` or ``(None, None)``."""
            deadline = time.monotonic() + reattach_timeout_s
            attempt = 0
            while time.monotonic() < deadline:
                nxt = self._await_new_owner(uid, deadline)
                if nxt is None:
                    return None, None
                _reattaches.inc()
                conn = HTTPConnection("127.0.0.1", nxt.port,
                                      timeout=reattach_timeout_s)
                try:
                    conn.request("GET", f"/requests/{uid}/stream"
                                        f"?from_token={sent}")
                    resp = conn.getresponse()
                    if resp.status != 200:
                        resp.read()
                        raise OSError(f"reattach got {resp.status}")
                    return resp, conn
                except (OSError, TimeoutError, HTTPException) as exc:
                    conn.close()
                    logger.warning(f"ds_router: reattach for uid={uid} to "
                                   f"gen{nxt.generation} failed "
                                   f"(attempt {attempt}): {exc!r}")
                    attempt += 1
                    time.sleep(backoff_delay(attempt, 0.05, 1.0,
                                             jitter="full", rng=fleet.rng))
            return None, None

        def _await_new_owner(self, uid: int,
                             deadline: float) -> Optional[_Replica]:
            while time.monotonic() < deadline:
                if fleet.lost_retry_after(uid) is not None:
                    return None
                owner = fleet.owner_of(uid)
                if owner is not None and owner.routable and owner.alive():
                    return owner
                time.sleep(0.05)
            return None

        def _proxy_stream(self, r: _Replica, method: str, path: str,
                          body: Optional[bytes], uid: Optional[int]) -> None:
            conn = HTTPConnection("127.0.0.1", r.port,
                                  timeout=reattach_timeout_s)
            try:
                conn.request(method, path, body=body)
                resp = conn.getresponse()
            except (OSError, TimeoutError):
                conn.close()
                self._no_replica()
                return
            if resp.getheader("Transfer-Encoding", "").lower() == "chunked":
                # count tokens the CLIENT already holds (from_token=N in
                # the proxied path) so a mid-proxy reattach resumes at the
                # true client high-water mark, not at zero
                sent = 0
                if "from_token=" in path:
                    try:
                        sent = int(path.rsplit("from_token=", 1)[1]
                                   .split("&")[0])
                    except ValueError:
                        sent = 0
                self._relay_stream(resp, conn, uid, already_sent=sent)
                return
            payload = resp.read()
            self.send_response(resp.status)
            self.send_header("Content-Type",
                             resp.getheader("Content-Type",
                                            "application/json"))
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            conn.close()

    server = ThreadingHTTPServer((host, port), Handler)
    server.daemon_threads = True
    return server


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="Replica fleet router: health-gated balancing, WAL "
                    "live migration, queue-depth autoscaling")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=None,
                    help="pool ceiling (default: available world size via "
                         "the elasticity probe)")
    ap.add_argument("--journal-root", default=None)
    ap.add_argument("--probe-interval", type=float, default=1.0)
    ap.add_argument("--probe-timeout", type=float, default=2.0)
    ap.add_argument("--quarantine-after", type=int, default=3)
    ap.add_argument("--migrate-stall", type=float, default=30.0)
    ap.add_argument("--no-autoscale", action="store_true")
    ap.add_argument("--queue-high", type=float, default=8.0)
    ap.add_argument("--queue-low", type=float, default=1.0)
    ap.add_argument("--occupancy-high", type=float, default=0.95)
    ap.add_argument("--hysteresis", type=int, default=3)
    ap.add_argument("--cooldown", type=float, default=10.0)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="replica command after --, with {port} placeholder"
                         " (e.g. -- ds_serve --port {port})")
    args = ap.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no replica command given (after --)")
    if not any("{port}" in a for a in cmd):
        ap.error("replica command needs a {port} placeholder")
    fleet = ReplicaFleet(
        cmd, replicas=args.replicas, min_replicas=args.min_replicas,
        max_replicas=args.max_replicas, journal_root=args.journal_root,
        probe_interval=args.probe_interval, probe_timeout=args.probe_timeout,
        quarantine_after=args.quarantine_after,
        migrate_stall_s=args.migrate_stall,
        autoscale=not args.no_autoscale, queue_high=args.queue_high,
        queue_low=args.queue_low, occupancy_high=args.occupancy_high,
        hysteresis=args.hysteresis, cooldown_s=args.cooldown).start()
    server = create_router_server(fleet, host=args.host, port=args.port)
    logger.info(f"ds_router: fleet of {args.replicas} "
                f"({shlex.join(cmd)}) on http://{args.host}:{args.port}")

    # SIGTERM must not strand the replicas: python's default handler
    # skips the finally below, leaving N orphaned daemons holding ports
    # and journal dirs.  Route it through KeyboardInterrupt so shutdown
    # tears the whole fleet down.
    import signal as _signal

    def _on_term(signum, frame):
        raise KeyboardInterrupt

    _signal.signal(_signal.SIGTERM, _on_term)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        fleet.stop()
    return 0


if __name__ == "__main__":
    main()
