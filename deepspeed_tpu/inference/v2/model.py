"""Ragged (paged-KV) model implementation for the v2 engine.

Reference: ``deepspeed/inference/v2/model_implementations/
inference_transformer_base.py`` + the ragged kernels under
``inference/v2/kernels/ragged_ops/`` (blocked_flash, linear_blocked_kv_rotary,
logits_gather). TPU design:

- The whole forward is ONE jitted function ``(params, cache, batch) ->
  (logits, cache)`` with the cache donated — the paged-KV write is a single
  scatter of per-token flat slots, history read is a gather of the dense
  block table; both static-shaped (bucketed), MXU-friendly einsums do the
  attention. This replaces the reference's per-op CUDA kernel chain
  (qkv+rotary → blocked flash → moe/mlp → logits_gather).
- Logits are computed only for each sequence's final token
  (reference logits_gather: "saves cost on unembedding").
- Consumes the same param tree as ``models/llama.py`` (the training model) so
  a trained checkpoint serves directly.
"""

import functools
import re
from functools import partial
from typing import Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp

try:
    from jax import shard_map as _shard_map_new

    def _smap(f, mesh, in_specs, out_specs, manual):
        return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, axis_names=frozenset(manual),
                              check_vma=False)
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _smap(f, mesh, in_specs, out_specs, manual):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

from .config_v2 import KVCacheConfig
from ...models.llama import LlamaConfig, precompute_rope
from ...observability import get_registry
from ...ops.normalization import rms_norm
from ...ops.paged_attention import paged_attention
from ...ops.grouped_matmul import moe_grouped_mlp
from .ragged.ragged_wrapper import RaggedBatch
from .ragged.sequence_descriptor import BaseSequenceDescriptor
from ...ops.registry import on_tpu

_obs = get_registry()
_tp_wire_moved = _obs.counter(
    "ds_tp_wire_bytes_moved_total",
    "Receive-side interconnect bytes moved by the per-layer TP output "
    "collectives (reduce-scatter + all-gather two-step, or the "
    "plain-precision psum equivalent when the wire is fp)")
_tp_wire_saved = _obs.counter(
    "ds_tp_wire_bytes_saved_total",
    "Interconnect bytes saved by the blockwise-int8 TP wire vs moving the "
    "same activations at their compute dtype")

_SERVE_COMPILE_WATCH = None


def _serving_compile_watch():
    """Process-wide :class:`~...observability.xla.CompileWatch` for the
    serving compile cache: every bucketed forward / fused-decode /
    fused-spec program shares one watch so ``ds_compiles_total{key}`` /
    ``ds_compile_cache_hits_total{key}`` count across engines."""
    global _SERVE_COMPILE_WATCH
    if _SERVE_COMPILE_WATCH is None:
        from ...observability.xla import CompileWatch
        _SERVE_COMPILE_WATCH = CompileWatch(registry=_obs)
    return _SERVE_COMPILE_WATCH


def _compile_key_str(key) -> str:
    """Flatten a ``_fwd_cache`` key tuple into a Prometheus-safe label."""
    return re.sub(r"[^0-9A-Za-z_.,:=\[\]()+-]", "", "serve:" + repr(key))


def _kernel(d):
    """Weight accessor: dequantizes WoQ kernels in-graph (XLA fuses the
    dequant into the consuming matmul; HBM holds int8)."""
    k = d["kernel"]
    return k.dequantized() if hasattr(k, "dequantized") else k


def check_woq_tp_support(config: LlamaConfig, quantize, tp_size: int,
                         group_size: int = 512) -> dict:
    """Capability check for weight-quantization × tensor-parallel combos.

    Replaces the former blanket mutual exclusion: packed kernels + their
    per-block scales now shard shard-major along the AutoTP dims, so only
    genuinely unsupported combos are refused — packing granularities the
    quantizer cannot honor, or a combo where NO kernel is shardable (which
    would silently serve a fully-replicated "TP" engine, the failure mode
    the old ValueError guarded against). Kernels that are individually
    non-divisible simply replicate, matching the fp heuristics.

    Returns ``{kernel class: shardable}`` (empty when the combo is trivially
    fine, i.e. no quantization or tp_size == 1); raises ``ValueError`` with
    an actionable message naming the combo otherwise.
    """
    if quantize is None or tp_size <= 1:
        return {}
    combo = f"quantize={quantize!r} x tp={tp_size}"
    if quantize == "int4" and group_size % 2:
        raise ValueError(
            f"unsupported combo {combo}: int4 nibble-packing needs an even "
            f"quantization group_size, got {group_size}")
    if quantize == "fp6" and group_size % 4:
        raise ValueError(
            f"unsupported combo {combo}: fp6 e3m2 packs 4 codes per 3 bytes "
            f"and needs group_size % 4 == 0, got {group_size}")
    hd, nq, nkv = (config.head_dim_, config.num_attention_heads,
                   config.num_key_value_heads)
    shardable = {
        "q_proj/o_proj": (nq * hd) % tp_size == 0,
        "k_proj/v_proj": (nkv * hd) % tp_size == 0,
        "mlp": (config.num_local_experts == 0
                and config.intermediate_size % tp_size == 0),
    }
    if not any(shardable.values()):
        raise ValueError(
            f"unsupported combo {combo}: no quantized kernel is shardable "
            f"(attn q/o dim {nq * hd}, k/v dim {nkv * hd}, mlp intermediate "
            f"{config.intermediate_size}"
            + (" [MoE experts replicate under TP]"
               if config.num_local_experts else "")
            + f" — none divisible by tp={tp_size}), so every chip would hold "
            f"the full quantized model: a silently-replicated 'TP' engine. "
            f"Pick a tp_size dividing the head or MLP dims, or serve "
            f"unquantized.")
    return shardable


def _tp_wire_matmul(x, w, mesh, block: int):
    """Row-parallel output projection with an EXPLICIT quantized-wire
    reduction: local partial matmul → fp32 → blockwise-int8
    reduce-scatter → blockwise-int8 all-gather (comm/bucketing.py wire
    kernels), replacing the plain-precision psum GSPMD would insert. The
    all-gather dequant is deterministic, so every worker reconstructs the
    identical full output — activations stay replicated downstream exactly
    like the implicit path. Quantization residual is dropped (serving has
    no cross-step error-feedback channel).

    ``x`` [T, K] activations (K = the sharded contraction dim), ``w``
    [K, M] row-sharded kernel. Caller guarantees ``K % tp == 0``.
    """
    from jax.sharding import PartitionSpec as P
    from ...comm.bucketing import all_gather_bucket, reduce_scatter_bucket
    T, K = x.shape
    M = w.shape[-1]
    n = T * M
    tp = mesh.shape["model"]
    pad = (-n) % (tp * block)

    def _local(x_l, w_l):
        part = (x_l @ w_l).astype(jnp.float32).reshape(-1)
        if pad:
            part = jnp.concatenate([part, jnp.zeros((pad, ), jnp.float32)])
        shard, _ = reduce_scatter_bucket(part, ("model", ), tier="int8",
                                         block_size=block)
        full = all_gather_bucket(shard, ("model", ), tier="int8",
                                 block_size=block)
        return full[:n].reshape(T, M)

    out = _smap(_local, mesh, (P(None, "model"), P("model", None)),
                P(None, None), {"model"})(x, w)
    return out.astype(x.dtype)


def _rope_tok(x, cos, sin, positions, rotary_dim=None, interleaved=False):
    """Token-major rope: x [T, H, D], positions [T]; partial rotary (Phi)
    rotates only the leading rotary_dim dims; ``interleaved`` = GPT-J
    adjacent-pair layout."""
    if rotary_dim is not None and rotary_dim < x.shape[-1]:
        xr, xp = x[..., :rotary_dim], x[..., rotary_dim:]
        return jnp.concatenate([_rope_tok(xr, cos, sin, positions,
                                          interleaved=interleaved), xp],
                               -1).astype(x.dtype)
    c = cos[positions][:, None, :]
    s = sin[positions][:, None, :]
    if interleaved:
        x1, x2 = x[..., ::2], x[..., 1::2]
        return jnp.stack([x1 * c - x2 * s, x2 * c + x1 * s],
                         axis=-1).reshape(x.shape).astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1).astype(x.dtype)


def _norm_tok(x, p, cfg):
    """rmsnorm or layernorm variant per the config (token-major):
    "layernorm" scale+bias, "layernorm_nobias" (Cohere) scale only,
    "layernorm_np" (OLMo) non-parametric."""
    if cfg.norm_type.startswith("layernorm"):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        out = (xf - mu) * jax.lax.rsqrt(var + cfg.rms_norm_eps)
        if cfg.norm_type == "layernorm":
            out = out * p["scale"] + p["bias"]
        elif cfg.norm_type == "layernorm_nobias":
            out = out * p["scale"]
        return out.astype(x.dtype)
    w = p["weight"]
    if getattr(cfg, "norm_plus_one", False):
        # Gemma stores (weight - 1); the +1 must happen in fp32 — in bf16
        # the ~1e-3 learned deltas round away against 1.0 (HF GemmaRMSNorm
        # also computes (1 + weight.float()) in fp32)
        w = 1.0 + w.astype(jnp.float32)
    return rms_norm(x, w, cfg.rms_norm_eps)


def _mlp_tok(x, lp, cfg, row_out=None, lora_add=None, layer=0):
    """Dense MLP variants (token-major): swiglu | gelu_fc | relu_fc.
    ``row_out(y, kernel, cls)`` routes the row-parallel down-projection —
    the TP wire hook; None = the plain matmul. ``lora_add(y, name, inp,
    layer)`` is the multi-LoRA delta hook on gate/up/down projections;
    None = base weights only."""
    mm = row_out or (lambda y, k, cls: y @ k)
    la = lora_add or (lambda y, name, inp, layer: y)
    mlp = lp["mlp"]
    if cfg.mlp_type in ("swiglu", "geglu_tanh"):
        pre = la(x @ _kernel(mlp["gate_proj"]), "gate_proj", x, layer)
        gate = (jax.nn.silu(pre) if cfg.mlp_type == "swiglu"
                else jax.nn.gelu(pre, approximate=True))
        inner = gate * la(x @ _kernel(mlp["up_proj"]), "up_proj", x, layer)
        return la(mm(inner, _kernel(mlp["down_proj"]), "mlp_out"),
                  "down_proj", inner, layer)
    act = {"gelu_fc": lambda y: jax.nn.gelu(y, approximate=False),
           "gelu_tanh_fc": lambda y: jax.nn.gelu(y, approximate=True),
           "relu_fc": jax.nn.relu}[cfg.mlp_type]
    h = x @ _kernel(mlp["fc1"])
    if "bias" in mlp["fc1"]:
        h = h + mlp["fc1"]["bias"]
    out = mm(act(h), _kernel(mlp["fc2"]), "mlp_out")
    if "bias" in mlp["fc2"]:
        out = out + mlp["fc2"]["bias"]
    return out


class RaggedLlamaModel:
    """Paged-KV decode/prefill model over a Llama param tree."""

    def __init__(self, config: LlamaConfig, params, dtype=jnp.bfloat16, kv_block_size: int = 64,
                 attn_backend: str = "auto", quantize=None, tp_size: int = 1,
                 kv_cache_dtype: Optional[str] = None,
                 tp_wire_dtype: Optional[str] = None,
                 tp_wire_overrides: Optional[dict] = None,
                 tp_wire_block: int = 256,
                 devices=None):
        self.config = config
        # explicit device subset (disaggregated serving: each group's
        # engine pins params + KV to its own devices). None = process
        # default placement, byte-identical to the pre-disagg behavior.
        self.devices = tuple(devices) if devices is not None else None
        self.dtype = dtype
        self.kv_block_size = kv_block_size
        if quantize not in (None, "int8", "fp6", "int4"):
            raise ValueError("quantize must be None, 'int8', 'fp6' or 'int4', "
                             f"got {quantize!r}")
        self._quantize = quantize
        if kv_cache_dtype not in (None, "int8", "bfloat16", "float32"):
            raise ValueError("kv_cache_dtype must be None/int8/bfloat16/"
                             f"float32, got {kv_cache_dtype!r}")
        # int8: KV pages stored 1 byte/element + per-slot-vector fp32 scales
        # (vLLM-class KV quantization — beyond the reference's FastGen);
        # dequant happens in-kernel on the paged path
        self._kv_cache_dtype = kv_cache_dtype
        self.tp_size = int(tp_size or 1)
        self._kv_pad = 0  # KV-head padding for nondivisible GQA under TP
        if quantize is not None:
            from ...linear.config import QuantizationConfig as _QC
            check_woq_tp_support(config, quantize, self.tp_size,
                                 _QC().group_size)
        # TP collective wire: explicit tp_wire_dtype > DS_TPU_TP_WIRE env >
        # default "fp" (the bit-identical GSPMD path). Resolved per layer
        # class; an all-fp map leaves the traced program literally untouched.
        from ...parallel.tp import resolve_tp_wire
        self._tp_wire, self._tp_wire_source = resolve_tp_wire(
            tp_wire_dtype, tp_wire_overrides)
        self._wire_block = int(tp_wire_block or 256)
        self._wire_static = (tuple(sorted(self._tp_wire.items()))
                             if self.tp_size > 1 and any(
                                 v == "int8" for v in self._tp_wire.values())
                             else None)
        # "paged" = Pallas blocked-flash decode kernel (TPU; interpret-mode on
        # CPU), "dense" = XLA gather of the full history window, "auto" =
        # paged on TPU, dense elsewhere (interpret mode is a numerics tool,
        # not a serving path)
        if attn_backend == "auto":
            attn_backend = "paged" if on_tpu() else "dense"
        assert attn_backend in ("paged", "dense"), attn_backend
        self._mesh_ctx = None
        self._cache_sharding = None
        if self.tp_size > 1:
            # TP serving (reference FastGen serves TP-sharded): weights are
            # column/row-sharded over the mesh model axis via the AutoTP
            # heuristics; GSPMD propagates head-sharded attention and inserts
            # the per-layer psum on the row-parallel projections
            from ...comm.mesh import (MeshContext, get_mesh_context,
                                      mesh_is_initialized, set_mesh_context)
            if self.devices is not None:
                # disaggregated group: a PRIVATE mesh over exactly these
                # devices — never registered globally, so the prefill and
                # decode groups' TP engines coexist in one process
                if len(self.devices) % self.tp_size != 0:
                    raise ValueError(
                        f"tp_size={self.tp_size} does not divide the "
                        f"{len(self.devices)}-device group")
                ctx = MeshContext.create(
                    axis_sizes={"model": self.tp_size, "data": -1},
                    devices=list(self.devices))
            elif mesh_is_initialized():
                ctx = get_mesh_context()
                if ctx.axis_size("model") != self.tp_size:
                    raise ValueError(
                        f"tp_size={self.tp_size} but the initialized mesh has "
                        f"model={ctx.axis_size('model')} — if that mesh "
                        f"belongs to a discarded engine, call "
                        f"deepspeed_tpu.comm.reset_mesh_context() first")
            else:
                ctx = MeshContext.create(
                    axis_sizes={"model": self.tp_size, "data": -1})
                set_mesh_context(ctx)
            self._mesh_ctx = ctx
            if attn_backend == "paged":
                # a raw pallas_call can't auto-partition under GSPMD, but
                # attention is embarrassingly parallel over heads: the paged
                # branch runs the kernel per head-block inside a
                # partial-manual shard_map (same design as ulysses_flash).
                # KV heads not divisible by tp pad up to the next multiple
                # (reference sharding/attn.py handles uneven head splits;
                # here padded heads carry zero K/V/q and their outputs are
                # sliced off after the kernel). ALiBi stays on the kernel:
                # global-head slopes are computed once and each shard gets
                # its slice through the shard_map, so head identity
                # survives the split.
                rem = config.num_key_value_heads % self.tp_size
                if rem:
                    self._kv_pad = self.tp_size - rem
                    from ...utils.logging import logger
                    logger.info(
                        f"TP serving: kv_heads={config.num_key_value_heads} "
                        f"pads to {config.num_key_value_heads + self._kv_pad} "
                        f"for tp={self.tp_size} (paged kernel keeps running; "
                        f"padded heads are dead weight, not a dense fallback)")
        self.attn_backend = attn_backend
        if self._mesh_ctx is not None:
            # place each leaf DIRECTLY into its TP sharding — a plain
            # jnp.asarray would commit the full tree to one device first,
            # and a model that needs TP to fit per-chip HBM would OOM right
            # there. Host leaves cast on host (ml_dtypes bf16); device
            # leaves reshard then cast per-shard.
            from ...parallel.tp import tp_shardings
            shardings = tp_shardings(params, self._mesh_ctx)

            def _place(x, s):
                if isinstance(x, jax.Array):
                    return jax.device_put(x, s).astype(dtype)
                return jax.device_put(np.asarray(x).astype(dtype), s)

            self.params = jax.tree_util.tree_map(_place, params, shardings)
            # KV cache [2L, slot, KV*D] shards over the folded head dim —
            # each chip holds 1/tp of the cache, the memory point of TP
            # serving (heads are contiguous D-wide strips, so the model-axis
            # split lands on head boundaries). Paged backend: nondivisible
            # KV pads to a tp multiple (above), so the head dim always
            # shards. Dense backend with kv_heads % tp != 0 replicates
            # (correct, larger).
            from jax.sharding import NamedSharding, PartitionSpec as P
            n_kv = config.num_key_value_heads + self._kv_pad
            spec = (P(None, None, "model")
                    if n_kv % self.tp_size == 0 else P())
            self._cache_sharding = NamedSharding(self._mesh_ctx.mesh, spec)
        elif self.devices is not None:
            # single-device group (disagg without TP): COMMIT params to the
            # group's lead device so every jitted forward — and the KV
            # cache it donates — executes there instead of on the process
            # default device
            from jax.sharding import SingleDeviceSharding
            dev = self.devices[0]

            def _place1(x):
                if isinstance(x, jax.Array):
                    return jax.device_put(x, dev).astype(dtype)
                return jax.device_put(np.asarray(x).astype(dtype), dev)

            self.params = jax.tree_util.tree_map(_place1, params)
            self._cache_sharding = SingleDeviceSharding(dev)
        else:
            self.params = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, dtype=dtype), params)
        if quantize is not None:
            # WoQ (reference inference/v2 mixed_gemm + linear/quantization):
            # per-layer matmul weights stored packed (int8 / fp6-e3m2 /
            # int4) + scales, dequantized in-graph. Router gates / norms /
            # embeddings / lm_head stay fp. Under TP the packed values AND
            # per-block scales are laid out SHARD-MAJOR along the same
            # model-axis dim the AutoTP heuristics pick for the fp kernel
            # (parallel/tp.woq_shard_dim), each shard quantized
            # independently so no block crosses a shard boundary — a chip
            # holds 1/tp of the quantized bytes and dequantizes its own
            # segment locally in-graph. Kernels the heuristics would not
            # shard (MoE experts, non-divisible dims) stay flat+replicated.
            from ...linear.config import QuantizationConfig
            from ...linear.quantization import QuantizedParameter
            qcfg = QuantizationConfig(
                q_bits={"int8": 8, "fp6": 6, "int4": 4}[quantize])
            tp = self.tp_size
            if tp > 1:
                from jax.sharding import NamedSharding, PartitionSpec as P
                from ...parallel.tp import woq_shard_dim
                sh_shard = NamedSharding(self._mesh_ctx.mesh, P("model"))
                sh_repl = NamedSharding(self._mesh_ctx.mesh, P())

            def _quantize_one(w, path):
                sd = woq_shard_dim(path, w.shape, tp) if tp > 1 else None
                qp = QuantizedParameter.quantize(
                    w, qcfg, shard_dim=sd,
                    shards=(tp if sd is not None else 1))
                if tp > 1:
                    sh = sh_shard if sd is not None else sh_repl
                    qp = QuantizedParameter(
                        jax.device_put(qp.values, sh),
                        jax.device_put(qp.scales, sh),
                        qp.shape, qp.block_size, qp.dtype, qp.q_bits,
                        qp.shard_dim, qp.shards)
                return qp

            model_p = self.params["model"]
            for lname, lp in model_p.items():
                if not lname.startswith("layers_"):
                    continue
                def _maybe_q(node, prefix):
                    for key, sub in list(node.items()):
                        if key in ("gate", "shared_expert_gate"):
                            continue
                        if isinstance(sub, dict):
                            if "kernel" in sub and getattr(sub["kernel"], "ndim", 0) >= 2:
                                sub["kernel"] = _quantize_one(
                                    sub["kernel"], f"{prefix}/{key}/kernel")
                            else:
                                _maybe_q(sub, f"{prefix}/{key}")
                        elif key in ("w1", "w2", "w3") and getattr(sub, "ndim", 0) >= 2:
                            node[key] = _quantize_one(sub, f"{prefix}/{key}")
                _maybe_q(lp, lname)
        # unembed in fp32 (reference keeps logits fp32; lm_head lives under
        # "model" in the training tree)
        if "lm_head" in params.get("model", {}):
            if self._mesh_ctx is not None:
                # mesh-replicated placement, same shard-first discipline as
                # _place: jnp.asarray would commit to (or keep) one device
                # and clash with the tp-mesh params inside the jitted forward
                from jax.sharding import NamedSharding, PartitionSpec as P
                repl = NamedSharding(self._mesh_ctx.mesh, P())
                fp32_put = lambda x: jax.device_put(
                    np.asarray(x, np.float32) if not isinstance(x, jax.Array)
                    else x, repl).astype(jnp.float32)
            elif self.devices is not None:
                dev0 = self.devices[0]
                fp32_put = lambda x: jax.device_put(
                    np.asarray(x, np.float32) if not isinstance(x, jax.Array)
                    else x, dev0).astype(jnp.float32)
            else:
                fp32_put = lambda x: jnp.asarray(x, jnp.float32)
            self.params["model"]["lm_head"] = jax.tree_util.tree_map(
                fp32_put, params["model"]["lm_head"])
        self._state_manager = None
        self._fwd_cache = {}  # bucket key -> compiled fn
        self._last_dispatch_fn = None  # WatchedJit behind the latest dispatch
        # Multi-LoRA: when an AdapterRegistry is attached, its stacked
        # factor bank rides every dispatch as a TRACED operand (shapes
        # fixed at registry construction), so hot adapter loads never
        # change a compile key
        self._adapters = None

    def set_adapter_registry(self, registry) -> None:
        """Attach the multi-LoRA adapter registry. Must happen before the
        first dispatch: the bank operand is part of every traced program's
        call signature, and attaching later would recompile the world."""
        if self._fwd_cache:
            raise RuntimeError("set_adapter_registry must precede the first "
                               "dispatch (the compiled programs' signatures "
                               "are fixed at trace time)")
        self._adapters = registry

    def _adapter_args(self, n_rows: int, adapter_slots):
        """(bank, per-seq slots [n_rows]) operand pair, or (None, None)
        when no registry is attached. ``adapter_slots=None`` with a
        registry means an all-identity wave (slot 0 everywhere)."""
        if self._adapters is None:
            return None, None
        if adapter_slots is None:
            slots = jnp.zeros(n_rows, jnp.int32)
        else:
            slots = jnp.asarray(adapter_slots, jnp.int32)
        return self._adapters.bank, slots

    # ---- state-manager plumbing (reference inference_model_base) ----

    def set_state_manager(self, state_manager) -> None:
        self._state_manager = state_manager

    def kv_cache_config(self) -> KVCacheConfig:
        cfg = self.config
        return KVCacheConfig(
            block_size=self.kv_block_size,
            cache_shape=(cfg.num_hidden_layers,
                         cfg.num_key_value_heads + self._kv_pad, cfg.head_dim_),
            cache_dtype=(self._kv_cache_dtype
                         or ("bfloat16" if self.dtype == jnp.bfloat16
                             else "float32")),
            cache_sharding=self._cache_sharding)

    # ---- scheduling arithmetic (reference get_kv_requirements) ----

    def get_kv_requirements(self, seq_desc: BaseSequenceDescriptor, max_new_tokens: int,
                            max_new_blocks: int) -> Tuple[int, int]:
        """How many of `max_new_tokens` fit given `max_new_blocks` free blocks;
        returns (schedulable_tokens, blocks_needed)."""
        bs = self.kv_block_size
        total = seq_desc.seen_tokens + max_new_tokens
        req_blocks = (total + bs - 1) // bs - seq_desc.cur_allocated_blocks
        if req_blocks <= max_new_blocks:
            return max_new_tokens, max(0, req_blocks)
        capacity = (seq_desc.cur_allocated_blocks + max_new_blocks) * bs - seq_desc.seen_tokens
        return max(0, capacity), max_new_blocks

    def get_remaining_block_capacity(self, seq_desc: BaseSequenceDescriptor) -> int:
        return seq_desc.cur_allocated_blocks * self.kv_block_size - seq_desc.seen_tokens

    def maybe_allocate_kv(self, seq_desc, n_new_tokens: int) -> None:
        _, req = self.get_kv_requirements(seq_desc, n_new_tokens,
                                          self._state_manager.free_blocks)
        if req > 0:
            seq_desc.extend_kv_cache(self._state_manager.allocate_blocks(req))

    def maybe_free_kv(self, seq_desc) -> None:
        """Mid-sequence trailing-window block release (reference
        ``inference_model_base.py:234`` — the sliding-window example in its
        docstring). Global attention retains every block until flush; when
        ALL layers attend through a local window, tokens at positions
        ``<= seen - W`` can never be attended again, so whole leading blocks
        return to the allocator while the sequence keeps decoding."""
        W = self._uniform_window
        if W is None:
            return
        # the next query position is seen_tokens; the window mask keeps
        # key_pos > q_pos - W, so the first position still reachable is
        # seen - W + 1 — blocks wholly below it are dead
        first_needed = seq_desc.seen_tokens - W + 1
        if first_needed <= 0:
            return
        freed = seq_desc.free_prefix_blocks(first_needed // self.kv_block_size)
        if freed:
            self._state_manager.release_blocks(freed)

    @functools.cached_property
    def _uniform_window(self):
        """max window when EVERY layer attends locally, else None (any
        global layer pins the whole history). Pure function of the config —
        hoisted off the per-token decode path."""
        cfg = self.config
        if cfg.sliding_window is None:
            return None
        from ...models.llama import _layer_window
        windows = [_layer_window(cfg, l) for l in range(cfg.num_hidden_layers)]
        return None if any(w is None for w in windows) else max(windows)

    def prepare_batch(self, batch) -> None:
        pass

    # ---- TP wire accounting (host-side static arithmetic) ----

    def tp_wire_cost(self, n_tokens: int) -> dict:
        """Receive-side interconnect bytes for ONE forward feeding
        ``n_tokens`` tokens through the per-layer TP output collectives.
        Pure host arithmetic mirroring the traced program (the in-graph
        collective can't count itself): per wired row-parallel matmul of
        ``n = n_tokens * hidden`` output elements over a tp-worker ring,
        both collectives of the two-step move ``2*(tp-1)/tp`` of the wire
        array remotely — int8 wire = codes (1 B/elem on the padded length)
        + fp32 scale and zero per ``wire_block``; the fp equivalent moves
        the partial sums at the activation dtype. Returns
        ``{"moved", "fp_equiv", "saved"}`` in bytes.
        """
        if self.tp_size <= 1:
            return {"moved": 0, "fp_equiv": 0, "saved": 0}
        cfg, tp, block = self.config, self.tp_size, self._wire_block
        itemsize = jnp.dtype(self.dtype).itemsize
        factor = 2.0 * (tp - 1) / tp
        classes = []
        if (cfg.num_attention_heads * cfg.head_dim_) % tp == 0:
            classes.append("attn_out")
        if cfg.num_local_experts == 0 and cfg.intermediate_size % tp == 0:
            classes.append("mlp_out")
        moved = fp_equiv = 0.0
        for cls in classes:
            n = n_tokens * cfg.hidden_size
            fp_n = factor * n * itemsize
            if self._tp_wire.get(cls) == "int8":
                n_tot = n + ((-n) % (tp * block))
                m = factor * (n_tot + 8 * (n_tot // block))
            else:
                m = fp_n
            moved += m * cfg.num_hidden_layers
            fp_equiv += fp_n * cfg.num_hidden_layers
        return {"moved": int(moved), "fp_equiv": int(fp_equiv),
                "saved": int(max(0.0, fp_equiv - moved))}

    def _bump_wire_counters(self, n_tokens: int) -> None:
        if self.tp_size <= 1:
            return
        cost = self.tp_wire_cost(n_tokens)
        if cost["moved"]:
            _tp_wire_moved.inc(cost["moved"])
        if cost["saved"]:
            _tp_wire_saved.inc(cost["saved"])
        from ...comm.comms_logging import get_comms_logger
        cl = get_comms_logger()
        if cl.enabled and cost["moved"]:
            tier = ("int8" if any(v == "int8"
                                  for v in self._tp_wire.values()) else "fp")
            cl.append("all_reduce", f"tp_wire[{tier}]", 0.0, cost["moved"],
                      n_participants=self.tp_size)

    # ---- forward ----

    def forward(self, batch: RaggedBatch, window_logits: bool = False,
                adapter_slots=None) -> jax.Array:
        """``window_logits``: return [S, N, vocab] logits for every fed
        token (the speculative verifier's one-pass need) instead of the
        final-token [S, vocab] gather. ``adapter_slots``: per-SEQUENCE
        adapter slot ids [S] (multi-LoRA); None = identity everywhere."""
        kv = self._state_manager.kv_cache
        key = (batch.bucket_key, window_logits)
        fn = self._fwd_cache.get(key)
        if fn is None:
            # under TP the cache's head sharding is pinned on the OUTPUT too:
            # the donated buffer must come back with the same layout or the
            # next step pays a reshard and the donation is wasted (int8
            # caches are a (data, scales) pytree — mirror its real layout)
            kw = ({"out_shardings": (None, jax.tree_util.tree_map(
                       lambda a: a.sharding, kv.cache))}
                  if self._mesh_ctx is not None else {})
            fn = jax.jit(partial(_ragged_forward, config=self.config,
                                 block_size=self.kv_block_size,
                                 attn_backend=self.attn_backend,
                                 tp_size=self.tp_size,
                                 kv_pad=self._kv_pad,
                                 tp_wire=self._wire_static,
                                 wire_block=self._wire_block,
                                 window_logits=window_logits,
                                 mesh=(self._mesh_ctx.mesh
                                       if self._mesh_ctx is not None else None)),
                         donate_argnums=(1, ), **kw)
            fn = _serving_compile_watch().wrap(fn, _compile_key_str(key))
            self._fwd_cache[key] = fn
        self._last_dispatch_fn = fn
        bank, slots = self._adapter_args(batch.q_tok_idx.shape[0],
                                         adapter_slots)
        if bank is not None:
            logits, new_cache = fn(self.params, kv.cache, batch, bank, slots)
        else:
            logits, new_cache = fn(self.params, kv.cache, batch)
        kv.update(new_cache)
        self._bump_wire_counters(batch.tokens.shape[0])
        return logits

    def cow_copy_block(self, src_block: int, dst_block: int) -> None:
        """Copy one KV block's slots ``src_block`` -> ``dst_block`` inside
        the paged pool: the prefix cache's copy-on-write fork. One jitted
        dynamic gather/scatter along the flat slot axis (the PR-15
        handoff-landing idiom), cache donated so the pool is updated in
        place; block indices are traced operands so every fork reuses the
        same compiled program. Copying the WHOLE block is safe even when
        only the first ``p`` slots are shared: causal attention means those
        slots are bit-identical to what the forking sequence would compute,
        and the stale tail slots are overwritten by the fork's own prefill
        before ``seen_tokens`` ever lets a read touch them."""
        kv = self._state_manager.kv_cache
        fn = self._fwd_cache.get("cow_copy")
        if fn is None:
            def _cow(cache, src, dst, *, block_size):
                def _one(arr):
                    blk = jax.lax.dynamic_slice_in_dim(
                        arr, src * block_size, block_size, axis=1)
                    return jax.lax.dynamic_update_slice_in_dim(
                        arr, blk, dst * block_size, axis=1)
                return jax.tree_util.tree_map(_one, cache)

            kw = ({"out_shardings": jax.tree_util.tree_map(
                       lambda a: a.sharding, kv.cache)}
                  if self._mesh_ctx is not None else {})
            fn = jax.jit(partial(_cow, block_size=self.kv_block_size),
                         donate_argnums=(0, ), **kw)
            fn = _serving_compile_watch().wrap(fn, "cow_copy_block")
            self._fwd_cache["cow_copy"] = fn
        kv.update(fn(kv.cache, jnp.int32(src_block), jnp.int32(dst_block)))

    def fused_decode(self, tokens, seq_lens, live, block_table, n_steps: int,
                     sampling: Optional[dict] = None, fetch: bool = True,
                     adapter_slots=None):
        """``n_steps`` decode steps in ONE XLA program (lax.scan over the
        single-token ragged forward). The TPU-native answer to the
        reference v1 engine's CUDA-graph decode capture
        (``inference/engine.py:527 _create_cuda_graph``): where CUDA graphs
        amortize kernel-launch overhead by replaying a recorded decode step,
        this amortizes the per-dispatch host/relay round-trip by scanning K
        steps inside the compiled program — sampling, KV append and
        position advance all stay on device.

        Host contract: every live row's block table already covers
        ``seq_lens + n_steps`` tokens (the engine pre-allocates); ``live`` is
        0/1 per row (bucket padding rows are 0 — their KV writes drop to the
        OOB slot and their position never advances, exactly like padding in
        the per-step path).

        ``sampling=None`` keeps the original greedy program (argmax
        in-trace, byte-identical compile key) and returns int32
        [n_steps, S] generated tokens (rows of dead sequences repeat their
        input token). With ``sampling`` (a dict of per-row arrays —
        ``keys`` [S, 2] uint32, ``temps``/``top_ps``/``penalties`` [S] f32,
        ``top_ks``/``eos_ids``/``n_out``/``min_new`` [S] int32, optional
        ``seen_mask`` [S, V] bool, and static flags ``want_logprobs``/
        ``use_penalty``/``use_eos_mask``), each scan step runs logit
        controls → ops/sampling.sample_core → feed-back, and the call
        returns ``(toks [n_steps, S], logprobs [n_steps, S], new_keys
        [S, 2])`` in one host transfer.

        ``fetch=False`` returns the same tuple as LAZY device arrays: the
        program is dispatched (JAX dispatch is async) but the host does
        not block on the result — the continuous-fusion scheduler feeds
        prefill chunks while the wave runs, then fetches. The KV cache
        ref is already rebound to the program's (lazy) output, so any
        forward dispatched afterwards serializes behind the wave through
        the donated-cache data dependency."""
        kv = self._state_manager.kv_cache
        total_slots = kv.num_blocks * kv.block_size
        S, B = tokens.shape[0], block_table.shape[1]
        if sampling is None:
            key = ("fused", S, B, n_steps)
            statics = {}
        else:
            statics = {"want_logprobs": bool(sampling["want_logprobs"]),
                       "use_penalty": bool(sampling["use_penalty"]),
                       "use_eos_mask": bool(sampling["use_eos_mask"])}
            key = ("fused_sampled", S, B, n_steps,
                   tuple(sorted(statics.items())))
        fn = self._fwd_cache.get(key)
        if fn is None:
            if self._mesh_ctx is not None:
                cache_sh = jax.tree_util.tree_map(lambda a: a.sharding,
                                                  kv.cache)
                out_sh = ((None, cache_sh) if sampling is None
                          else (None, None, None, cache_sh))
                kw = {"out_shardings": out_sh}
            else:
                kw = {}
            fn = jax.jit(partial(_fused_decode_loop, config=self.config,
                                 block_size=self.kv_block_size,
                                 attn_backend=self.attn_backend,
                                 tp_size=self.tp_size,
                                 kv_pad=self._kv_pad,
                                 tp_wire=self._wire_static,
                                 wire_block=self._wire_block,
                                 total_slots=total_slots,
                                 n_steps=n_steps,
                                 sample=sampling is not None,
                                 **statics,
                                 mesh=(self._mesh_ctx.mesh
                                       if self._mesh_ctx is not None else None)),
                         donate_argnums=(1, ), **kw)
            fn = _serving_compile_watch().wrap(fn, _compile_key_str(key))
            self._fwd_cache[key] = fn
        self._last_dispatch_fn = fn
        args = (self.params, kv.cache, jnp.asarray(tokens),
                jnp.asarray(seq_lens), jnp.asarray(live),
                jnp.asarray(block_table))
        bank, slots = self._adapter_args(S, adapter_slots)
        akw = ({} if bank is None
               else {"adapter_bank": bank, "adapter_slots": slots})
        if sampling is None:
            out, new_cache = fn(*args, **akw)
            kv.update(new_cache)
            self._bump_wire_counters(S * n_steps)
            if not fetch:
                return out
            return np.asarray(out)
        sargs = {k: (jnp.asarray(v) if v is not None else None)
                 for k, v in sampling.items()
                 if k not in ("want_logprobs", "use_penalty", "use_eos_mask")}
        out, lps, new_keys, new_cache = fn(*args, **sargs, **akw)
        kv.update(new_cache)
        self._bump_wire_counters(S * n_steps)
        if not fetch:
            return out, lps, new_keys
        out, lps, new_keys = jax.device_get((out, lps, new_keys))
        return np.asarray(out), np.asarray(lps), np.asarray(new_keys)

    def fused_spec_decode(self, tokens, seq_lens, live, block_table, hist,
                          hist_len, ngrams, max_drafts, n_steps: int,
                          draft_width: int, max_ngram: int,
                          sampling: Optional[dict] = None,
                          fetch: bool = True, adapter_slots=None):
        """``n_steps`` speculative draft/verify windows in ONE XLA program
        — the speculative sibling of ``fused_decode``. Each scan iteration
        drafts up to ``draft_width`` tokens per row from a carried
        token-history ring buffer (``ops/sampling.ngram_draft_ring``),
        feeds ``1 + draft_width`` tokens through the multi-token ragged
        forward with ``window_logits=True``, verifies the drafts on device
        (argmax match for greedy rows, point-mass rejection sampling for
        sampled rows) and advances each row by its accepted length + 1.

        Rollback never leaves the device: KV slots are a pure function of
        position, so a rejected tail's writes are simply overwritten by
        the next window's feed (which always starts at the accepted
        position and spans at least as far) — the host-side
        ``seq.rollback()`` of the per-token path has no fused equivalent
        to pay for.

        Host contract: every live row's block table covers
        ``seq_lens + n_steps * (1 + draft_width)`` tokens (worst case all
        drafts accepted), and the history ring is laid out with the token
        for logical position p at ``hist[:, p % W]``.

        Returns one host fetch: ``(out [n_steps, S, 1+draft_width] int32,
        n_emit [n_steps, S] int32, dlen [n_steps, S] int32, new_keys)``
        where window w of row i emitted ``out[w, i, :n_emit[w, i]]``
        tokens after drafting ``dlen[w, i]`` (accepted = n_emit - 1), and
        ``new_keys`` is None for the greedy program. ``fetch=False``
        returns the same tuple as LAZY device arrays (see
        :meth:`fused_decode`) so the scheduler can overlap host work with
        the in-flight windows."""
        kv = self._state_manager.kv_cache
        total_slots = kv.num_blocks * kv.block_size
        S, B = tokens.shape[0], block_table.shape[1]
        W = hist.shape[1]
        key = ("fused_spec", S, B, W, n_steps, draft_width, max_ngram,
               sampling is not None)
        fn = self._fwd_cache.get(key)
        if fn is None:
            if self._mesh_ctx is not None:
                cache_sh = jax.tree_util.tree_map(lambda a: a.sharding,
                                                  kv.cache)
                out_sh = ((None, None, None, cache_sh) if sampling is None
                          else (None, None, None, None, cache_sh))
                kw = {"out_shardings": out_sh}
            else:
                kw = {}
            fn = jax.jit(partial(_fused_spec_decode_loop, config=self.config,
                                 block_size=self.kv_block_size,
                                 attn_backend=self.attn_backend,
                                 tp_size=self.tp_size,
                                 kv_pad=self._kv_pad,
                                 tp_wire=self._wire_static,
                                 wire_block=self._wire_block,
                                 total_slots=total_slots,
                                 n_steps=n_steps,
                                 d=draft_width,
                                 max_ngram=max_ngram,
                                 sample=sampling is not None,
                                 mesh=(self._mesh_ctx.mesh
                                       if self._mesh_ctx is not None else None)),
                         donate_argnums=(1, ), **kw)
            fn = _serving_compile_watch().wrap(fn, _compile_key_str(key))
            self._fwd_cache[key] = fn
        self._last_dispatch_fn = fn
        args = (self.params, kv.cache, jnp.asarray(tokens),
                jnp.asarray(seq_lens), jnp.asarray(live),
                jnp.asarray(block_table), jnp.asarray(hist),
                jnp.asarray(hist_len), jnp.asarray(ngrams),
                jnp.asarray(max_drafts))
        bank, slots = self._adapter_args(S, adapter_slots)
        akw = ({} if bank is None
               else {"adapter_bank": bank, "adapter_slots": slots})
        if sampling is None:
            out, n_emit, dlen, new_cache = fn(*args, **akw)
            kv.update(new_cache)
            self._bump_wire_counters(S * (1 + draft_width) * n_steps)
            if not fetch:
                return out, n_emit, dlen, None
            out, n_emit, dlen = jax.device_get((out, n_emit, dlen))
            return np.asarray(out), np.asarray(n_emit), np.asarray(dlen), None
        sargs = {k: jnp.asarray(v) for k, v in sampling.items()}
        out, n_emit, dlen, new_keys, new_cache = fn(*args, **sargs, **akw)
        kv.update(new_cache)
        self._bump_wire_counters(S * (1 + draft_width) * n_steps)
        if not fetch:
            return out, n_emit, dlen, new_keys
        out, n_emit, dlen, new_keys = jax.device_get(
            (out, n_emit, dlen, new_keys))
        return (np.asarray(out), np.asarray(n_emit), np.asarray(dlen),
                np.asarray(new_keys))

    def last_wave_flops(self) -> float:
        """XLA cost-analysis FLOPs of the most recently dispatched program
        (the wave just harvested) — the numerator of the serving wave-MFU
        gauge. 0.0 when nothing dispatched yet or the backend exposes no
        cost analysis (the gauge then simply stays unset)."""
        w = self._last_dispatch_fn
        if w is None or not hasattr(w, "program_flops"):
            return 0.0
        try:
            return float(w.program_flops() or 0.0)
        except Exception:  # pragma: no cover — telemetry must not break serving
            return 0.0


def _ragged_forward(params, cache, batch: RaggedBatch, adapter_bank=None,
                    adapter_slots=None, *, config: LlamaConfig,
                    block_size: int, attn_backend: str = "dense",
                    tp_size: int = 1, kv_pad: int = 0, mesh=None,
                    tp_wire=None, wire_block: int = 256,
                    window_logits: bool = False):
    """One ragged step: embed → L×(paged attn + mlp) → final-token logits.

    ``adapter_bank`` (multi-LoRA, traced): ``{"factors": {target: (A
    [n_slots, L, in, r], B [n_slots, L, r, out])}, "scale": [n_slots]}``
    plus ``adapter_slots`` [S] per-sequence slot ids. Each targeted
    projection gains ``y += B[slot] @ (A[slot] @ x) * scale`` via ONE pair
    of grouped GEMMs over the slot-sorted token wave — the sort is hoisted
    here and shared by every layer/target. Slot 0 holds zero factors, so
    identity rows add an exact 0.0 and base streams stay bit-identical."""
    cfg = config
    T = batch.tokens.shape[0]
    S, B = batch.block_table.shape
    L = B * block_size  # history window bucket
    hd, nq, nkv = cfg.head_dim_, cfg.num_attention_heads, cfg.num_key_value_heads
    g = nq // nkv

    # int8 KV: the cache arrives as a (data_i8, scales_f32) pytree — half
    # the KV HBM per token; pages dequantize at read (in-kernel on the
    # paged path)
    kv_quant = isinstance(cache, tuple)
    if kv_quant:
        cache_data, cache_scales = cache
    else:
        cache_data, cache_scales = cache, None

    p = params["model"]
    x = p["embed_tokens"]["embedding"][batch.tokens]  # [T, E]
    if cfg.embed_scale is not None:  # Gemma sqrt(hidden) normalizer
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    if cfg.embed_layernorm:  # BLOOM word_embeddings_layernorm
        x = _norm_tok(x, {"scale": p["embed_layernorm"]["scale"],
                          "bias": p["embed_layernorm"]["bias"]}, cfg)
    if cfg.pos_embedding == "learned":  # OPT (table offset by pos_offset)
        x = x + p["embed_positions"]["embedding"][batch.token_pos + cfg.pos_offset]
    cos, sin = precompute_rope(cfg.rotary_dim or hd, cfg.max_position_embeddings,
                               cfg.rope_theta)

    # per-seq query gather indices come host-precomputed as [S, N] where N
    # buckets the largest burst — N=1 for pure decode, so attention work is
    # S×N×history instead of S×T×history (the decode fast path)
    q_tok_idx = batch.q_tok_idx
    N = q_tok_idx.shape[1]
    seq_lens = batch.seq_seen + batch.seq_n_new  # valid key region per seq

    if attn_backend not in ("paged", "dense"):
        raise ValueError(f"unknown attn_backend {attn_backend!r}")
    if attn_backend == "dense":
        # XLA fallback: gather the full bucketed history window per layer
        j = jnp.arange(L, dtype=jnp.int32)
        slot_grid = batch.block_table[:, j // block_size] * block_size + j % block_size
        n_idx = jnp.arange(N, dtype=jnp.int32)
        q_valid = n_idx[None, :] < batch.seq_n_new[:, None]  # [S, N]
        q_abs = batch.seq_seen[:, None] + n_idx[None, :]
        key_pos = jnp.arange(L, dtype=jnp.int32)[None, None, :]
        attn_mask = (key_pos <= q_abs[:, :, None]) & \
            (key_pos < seq_lens[:, None, None]) & q_valid[:, :, None]  # [S, N, L]

    # token → (seq, rel) scatter-back indices
    rel = batch.token_pos - batch.seq_seen[batch.token_seq]  # [T]

    # TP wire routing for the row-parallel output projections: a class gated
    # to "int8" rides the explicit quantized two-step (lives inside whatever
    # scan calls this forward); "fp" (or no TP) keeps the plain matmul whose
    # psum GSPMD inserts — byte-identical to the pre-wire program. The
    # lm_head class is accounted but currently a no-op: the unembed is
    # replicated, so no TP reduce exists there to quantize.
    wire = dict(tp_wire) if tp_wire else {}

    def _row_out(y, kern, cls):
        if (wire.get(cls) == "int8" and tp_size > 1 and mesh is not None
                and y.shape[-1] % tp_size == 0):
            return _tp_wire_matmul(y, kern, mesh, wire_block)
        return y @ kern

    # multi-LoRA: hoist the slot sort ONCE per forward (it depends only on
    # the wave's slot assignment), then each targeted projection pays two
    # rank-r grouped GEMMs regardless of how many adapters are live
    lora = None
    if adapter_bank is not None:
        from ...ops.grouped_matmul import lora_grouped_delta, lora_sort_slots
        slots_tok = adapter_slots[batch.token_seq]  # [T] per-token slot
        n_slots = adapter_bank["scale"].shape[0]
        l_order, l_gsz = lora_sort_slots(slots_tok, n_slots)
        l_scale = adapter_bank["scale"][slots_tok][l_order]

        def lora(name, inp, layer):
            ab = adapter_bank["factors"].get(name)
            if ab is None:
                return None
            a, b = ab
            return lora_grouped_delta(inp, a[:, layer], b[:, layer],
                                      l_scale, l_order, l_gsz)

    def _lora_add(y, name, inp, layer):
        if lora is None:
            return y
        d = lora(name, inp, layer)
        return y if d is None else y + d.astype(y.dtype)

    for l in range(cfg.num_hidden_layers):
        lp = p[f"layers_{l}"]
        # post_norm (OLMo2): the raw stream feeds the sublayers, norms land
        # on the sublayer outputs below; None param: OLMo's np-norm
        h = x if cfg.post_norm else _norm_tok(x, lp.get("input_layernorm"), cfg)

        def proj(name, heads, norm=None):
            y = h @ _kernel(lp["self_attn"][name])
            y = _lora_add(y, name, h, l)
            if "bias" in lp["self_attn"][name]:  # qwen2/OPT/Phi biases
                y = y + lp["self_attn"][name]["bias"]
            if cfg.clip_qkv is not None:  # OLMo clamp — BEFORE qk-norm,
                y = jnp.clip(y, -cfg.clip_qkv, cfg.clip_qkv)  # as llama.py
            if norm is not None:  # OLMo2 qk-norm on the FLAT projection
                y = rms_norm(y, lp["self_attn"][norm]["weight"],
                             cfg.rms_norm_eps)
            return y.reshape(T, heads, hd)

        q = proj("q_proj", nq, "q_norm" if cfg.qk_norm else None)
        k = proj("k_proj", nkv, "k_norm" if cfg.qk_norm else None)
        v = proj("v_proj", nkv)
        if cfg.pos_embedding == "rope":
            q = _rope_tok(q, cos, sin, batch.token_pos, cfg.rotary_dim,
                          cfg.rope_interleaved)
            k = _rope_tok(k, cos, sin, batch.token_pos, cfg.rotary_dim,
                          cfg.rope_interleaved)

        # paged write: the cache is [2L, slot, KV*D] (k row 2l, v row 2l+1 —
        # see kv_cache.py: the slot-major fold makes this scatter IN-PLACE
        # on the donated buffer; the old head-major layout forced two
        # whole-cache transposed copies per forward). kv_pad > 0:
        # nondivisible-GQA TP — the cache rides padded KV heads (zeros) so
        # the head dim splits evenly over the model axis
        if kv_pad:
            k_w = jnp.pad(k, ((0, 0), (0, kv_pad), (0, 0)))  # [T, KV+p, D]
            v_w = jnp.pad(v, ((0, 0), (0, kv_pad), (0, 0)))
        else:
            k_w, v_w = k, v
        KVt = nkv + kv_pad
        if kv_quant:
            # int8 cache: per-slot-vector symmetric quant at write time —
            # one scale per (k|v, head, token) over head_dim; scales are
            # slot-major [2L, slots, KV] so this scatter is in-place too
            for row, w in ((2 * l, k_w), (2 * l + 1, v_w)):
                wf = w.astype(jnp.float32)
                sc = jnp.maximum(jnp.max(jnp.abs(wf), axis=-1) / 127.0, 1e-8)
                w_i8 = jnp.clip(jnp.round(wf / sc[..., None]),
                                -127, 127).astype(jnp.int8)
                cache_data = cache_data.at[row, batch.token_slot, :].set(
                    w_i8.reshape(T, KVt * hd), mode="drop")
                cache_scales = cache_scales.at[row, batch.token_slot, :].set(
                    sc, mode="drop")
        else:
            cache_data = cache_data.at[2 * l, batch.token_slot, :].set(
                k_w.reshape(T, KVt * hd).astype(cache_data.dtype), mode="drop")
            cache_data = cache_data.at[2 * l + 1, batch.token_slot, :].set(
                v_w.reshape(T, KVt * hd).astype(cache_data.dtype), mode="drop")

        # queries head-major [S, N, H, D] (H = KV*G kv-major = the natural
        # q head order); padded KV heads append G zero q heads at the END
        q_s = q[q_tok_idx]  # [S, N, nq, hd]
        if kv_pad:
            q_s = jnp.pad(q_s, ((0, 0), (0, 0), (0, kv_pad * g), (0, 0)))

        if attn_backend == "paged":
            # Pallas blocked-flash: stream the block-table pages, online
            # softmax — no history gather (ops/paged_attention.py); local
            # windows, ALiBi, and custom scale are handled in-kernel
            from ...models.llama import _layer_window
            kernel_kw = dict(page_size=block_size,
                             window=_layer_window(cfg, l),
                             attn_scale=cfg.attn_scale,
                             softcap=cfg.attn_logit_softcapping,
                             interpret=not on_tpu())
            has_alibi = cfg.pos_embedding == "alibi"
            if tp_size > 1:
                # TP: kernel per LOCAL head block inside a partial-manual
                # shard_map (heads are independent — no collectives); q and
                # the cache shard on their head dims, metadata replicated.
                # ``mesh`` is the model's OWN mesh, threaded in explicitly —
                # a global lookup at retrace time could bind a newer
                # engine's mesh and clash with this jit's pinned shardings.
                # ALiBi: slopes are a GLOBAL-head table sharded alongside
                # the heads, so each shard biases with its true head
                # identity (reference sharding/attn.py).
                from jax.sharding import PartitionSpec as P
                hspec = P(None, None, "model", None)  # q/o [S, N, H, D]
                cspec = P(None, None, "model")  # [2L, slot, KV*D] head fold
                rep = P()
                # optional extra operands ride the shard_map with their own
                # specs: int8 scales shard with the heads, slopes likewise
                extra, extra_specs = [], []
                if kv_quant:
                    extra.append(cache_scales)
                    extra_specs.append(P(None, None, "model"))
                if has_alibi:
                    from ...models.llama import alibi_slopes
                    slopes = jnp.asarray(alibi_slopes(nq)).reshape(nkv, g)
                    if kv_pad:
                        slopes = jnp.pad(slopes, ((0, kv_pad), (0, 0)))
                    extra.append(slopes)
                    extra_specs.append(P("model", None))

                def _paged_local(q_l, cache_l, bt, seen, lens, *rest):
                    rest = list(rest)
                    kw = dict(kernel_kw)
                    if kv_quant:
                        kw["cache_scales"] = rest.pop(0)
                    if has_alibi:
                        kw["slopes"] = rest.pop(0)
                    return paged_attention(q_l, cache_l, l, bt, seen,
                                           lens, **kw)

                ctx = _smap(
                    _paged_local, mesh,
                    tuple([hspec, cspec, rep, rep, rep] + extra_specs),
                    hspec, {"model"},
                )(q_s, cache_data, batch.block_table, batch.seq_seen,
                  seq_lens, *extra)
            else:
                ctx = paged_attention(q_s, cache_data, l, batch.block_table,
                                      batch.seq_seen, seq_lens,
                                      use_alibi=has_alibi,
                                      cache_scales=cache_scales,
                                      **kernel_kw)
            if kv_pad:
                ctx = ctx[:, :, :nq]  # drop the padded heads' outputs
            ctx = ctx.astype(x.dtype).reshape(S, N, nq * hd)
        else:
            # dense backend never pads KV heads (kv_pad is paged-only)
            k_h = cache_data[2 * l][slot_grid].reshape(S, L, nkv, hd)
            v_h = cache_data[2 * l + 1][slot_grid].reshape(S, L, nkv, hd)
            if kv_quant:  # int8: dequant the gathered window
                k_sc = cache_scales[2 * l][slot_grid]       # [S, L, KV]
                v_sc = cache_scales[2 * l + 1][slot_grid]
                k_h = k_h.astype(jnp.float32) * k_sc[..., None]
                v_h = v_h.astype(jnp.float32) * v_sc[..., None]
            k_h = k_h.astype(jnp.float32)  # [S, L, KV, D]
            v_h = v_h.astype(x.dtype)
            qf = q_s.reshape(S, N, nkv, g, hd).astype(jnp.float32)
            scale = (cfg.attn_scale if cfg.attn_scale is not None
                     else 1.0 / float(np.sqrt(hd)))
            scores = jnp.einsum("snkgd,slkd->snkgl", qf, k_h) * jnp.float32(scale)
            if cfg.attn_logit_softcapping is not None:  # Gemma-2, pre-mask
                from ...ops.attention import softcap_scores
                scores = softcap_scores(scores,
                                        jnp.float32(cfg.attn_logit_softcapping))
            from ...models.llama import _layer_window
            window = _layer_window(cfg, l)
            if window is not None:
                # Mistral/GPT-Neo local attention: keys older than the window
                keep = key_pos > q_abs[:, :, None] - window  # [S, N, L]
                scores = jnp.where(keep[:, :, None, None, :], scores, -1e30)
            if cfg.pos_embedding == "alibi":
                from ...models.llama import alibi_slopes
                slopes = jnp.asarray(alibi_slopes(nq)).reshape(nkv, g)
                # [S, N, KV, G, L]: slope_h * (key_pos - query_abs_pos)
                dist = (key_pos[:, :, None, None, :]
                        - q_abs[:, :, None, None, None]).astype(jnp.float32)
                scores = scores + slopes[None, None, :, :, None] * dist
            scores = jnp.where(attn_mask[:, :, None, None, :], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            ctx = jnp.einsum("snkgl,slkd->snkgd", probs, v_h).reshape(S, N, nq * hd)

        # back to token-major and project out
        ctx_tok = ctx[batch.token_seq, jnp.clip(rel, 0, N - 1)]  # [T, H*D]
        attn_out = _row_out(ctx_tok, _kernel(lp["self_attn"]["o_proj"]),
                            "attn_out")
        attn_out = _lora_add(attn_out, "o_proj", ctx_tok, l)
        if "bias" in lp["self_attn"]["o_proj"]:
            attn_out = attn_out + lp["self_attn"]["o_proj"]["bias"]

        def _ffn(h_in):
            """Dense MLP or Mixtral-style MoE block (matches models/llama.py)."""
            if cfg.num_local_experts == 0:
                return _mlp_tok(h_in, lp, cfg, _row_out, _lora_add, l)
            moe = lp["block_sparse_moe"]
            logits = h_in.astype(jnp.float32) @ moe["gate"]["kernel"].astype(jnp.float32)
            probs = jax.nn.softmax(logits, axis=-1)
            w, idx = jax.lax.top_k(probs, cfg.num_experts_per_tok)
            if cfg.moe_renormalize:  # Mixtral; Qwen2-MoE keeps raw mass
                w = w / jnp.sum(w, -1, keepdims=True)
            w = w.astype(x.dtype)
            # grouped GEMM: FLOPs ∝ top-k, not E (ops/grouped_matmul.py)
            def _w(name):
                t = moe[name]
                return t.dequantized() if hasattr(t, "dequantized") else t
            moe_out = moe_grouped_mlp(h_in, _w("w1"), _w("w3"), _w("w2"), idx, w)
            if cfg.shared_expert_intermediate_size:  # Qwen2-MoE shared expert
                se = moe["shared_expert"]
                shared = (jax.nn.silu(h_in @ _kernel(se["gate_proj"]))
                          * (h_in @ _kernel(se["up_proj"]))) @ _kernel(se["down_proj"])
                g = h_in.astype(jnp.float32) @ moe["shared_expert_gate"]["kernel"]
                moe_out = moe_out + jax.nn.sigmoid(g).astype(x.dtype) * shared
            return moe_out

        if cfg.sandwich_norm:  # Gemma-2: pre+post norms on both sublayers
            x = x + _norm_tok(attn_out, lp["post_attention_layernorm"], cfg)
            h2 = _norm_tok(x, lp["pre_feedforward_layernorm"], cfg)
            x = x + _norm_tok(_ffn(h2), lp["post_feedforward_layernorm"], cfg)
            continue
        if cfg.post_norm:  # OLMo2: x + norm(attn(x)), then x + norm(ffn(x))
            x = x + _norm_tok(attn_out, lp["post_attention_layernorm"], cfg)
            x = x + _norm_tok(_ffn(x), lp["post_feedforward_layernorm"], cfg)
            continue
        if cfg.parallel_residual:
            # Falcon/Phi: attention and MLP both read the SAME normed input;
            # GPT-NeoX (parallel_residual_norms=2): MLP norms x independently
            h_mlp = (_norm_tok(x, lp.get("post_attention_layernorm"), cfg)
                     if cfg.parallel_residual_norms == 2 else h)
            x = x + attn_out + _ffn(h_mlp)
            continue
        x = x + attn_out
        x = x + _ffn(_norm_tok(x, lp.get("post_attention_layernorm"), cfg))

    x = _norm_tok(x, p.get("norm"), cfg)
    if window_logits:
        # speculative verification: logits for EVERY fed token of each
        # sequence ([S, N, E] via the q_tok_idx bucket) instead of the
        # final-token gather — the verifier needs next-token distributions
        # at all draft positions in ONE pass
        final = x[q_tok_idx].astype(jnp.float32)     # [S, N, E]
    else:
        final = x[batch.last_token_idx].astype(jnp.float32)  # [S, E]
    if cfg.tie_word_embeddings:
        logits = final @ p["embed_tokens"]["embedding"].astype(jnp.float32).T
    else:
        logits = final @ p["lm_head"]["kernel"].astype(jnp.float32)
        if "bias" in p["lm_head"]:  # Phi
            logits = logits + p["lm_head"]["bias"].astype(jnp.float32)
    if cfg.logit_scale is not None:  # Cohere
        logits = logits * jnp.float32(cfg.logit_scale)
    if cfg.final_logit_softcapping is not None:  # Gemma-2
        cap = jnp.float32(cfg.final_logit_softcapping)
        logits = cap * jnp.tanh(logits / cap)
    return logits, ((cache_data, cache_scales) if kv_quant else cache_data)


def _fused_decode_loop(params, cache, tokens, seq_lens, live, block_table,
                       keys=None, temps=None, top_ks=None, top_ps=None,
                       penalties=None, eos_ids=None, n_out=None, min_new=None,
                       seen_mask=None, adapter_bank=None, adapter_slots=None,
                       *,
                       config, block_size, attn_backend, tp_size, kv_pad,
                       total_slots, n_steps, mesh, tp_wire=None,
                       wire_block=256, sample=False,
                       want_logprobs=False, use_penalty=False,
                       use_eos_mask=False):
    """K single-token ragged steps under one lax.scan: each iteration builds
    the pure-decode RaggedBatch **in-trace** (for one new token per sequence
    every field is a function of (block_table, seq_lens, tokens) — compare
    the host fast path in ``ragged_wrapper.py finalize``) and reuses
    ``_ragged_forward`` unchanged, so every model feature (GQA/ALiBi/windows/
    MoE/int8-KV/TP) composes by construction. Dead (padding) rows write to
    the OOB drop slot and never advance — identical to how ``finalize`` pads
    short batches.

    ``sample=False`` is the original greedy program (argmax in-program).
    ``sample=True`` runs the on-device sampler per step (ops/sampling):
    logit controls (repetition penalty over a carried [S, V] presence mask,
    eos masking while ``n_out + step < min_new``) then
    temperature/top-k/top-p Gumbel-max with one key split per row per step
    — the identical op chain the batched per-token dispatch runs, so token
    streams match the per-token path bit-for-bit under the same keys."""
    S, B = block_table.shape
    ar = jnp.arange(S, dtype=jnp.int32)
    live_i = live.astype(jnp.int32)
    if sample:
        from ...ops import sampling as dsamp
        if not use_penalty:
            seen_mask = jnp.zeros((S, 1), bool)  # dead carry, shape-stable

    def body(carry, step):
        cache, toks, lens, keys, seen = carry
        slot = block_table[ar, lens // block_size] * block_size + lens % block_size
        slot = jnp.where(live_i > 0, slot, total_slots)  # padding → scatter drop
        batch = RaggedBatch(
            tokens=toks, token_seq=ar, token_pos=lens, token_slot=slot,
            seq_start=ar, seq_n_new=live_i, seq_seen=lens,
            block_table=block_table, last_token_idx=ar,
            q_tok_idx=ar[:, None])
        logits, cache = _ragged_forward(
            params, cache, batch, adapter_bank, adapter_slots,
            config=config, block_size=block_size,
            attn_backend=attn_backend, tp_size=tp_size, kv_pad=kv_pad,
            mesh=mesh, tp_wire=tp_wire, wire_block=wire_block)
        if not sample:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            lps = jnp.zeros(S, jnp.float32)
        else:
            ctrl = dsamp.apply_logit_controls(
                logits,
                seen_mask=seen if use_penalty else None,
                penalties=penalties if use_penalty else None,
                eos_ids=eos_ids if use_eos_mask else None,
                block_eos=((n_out + step) < min_new) if use_eos_mask
                else None)
            nxt, lps, keys = dsamp.sample_core(
                ctrl, keys, temps, top_ks, top_ps,
                want_logprobs=want_logprobs)
        nxt = jnp.where(live_i > 0, nxt, toks)
        if sample and use_penalty:
            # the sampled token joins each row's history set before the
            # next step — exactly the host-side mask rebuild the per-token
            # path performs between dispatches
            seen = seen.at[ar, nxt].set(True)
        lens = lens + live_i
        return (cache, nxt, lens, keys, seen), (nxt, lps)

    if not sample:
        keys = jnp.zeros((S, 2), jnp.uint32)
    carry0 = (cache, tokens, seq_lens, keys, seen_mask if sample
              else jnp.zeros((S, 1), bool))
    (cache, _, _, keys, _), (out, lps) = jax.lax.scan(
        body, carry0, jnp.arange(n_steps, dtype=jnp.int32))
    if not sample:
        return out, cache
    return out, lps, keys, cache


def _fused_spec_decode_loop(params, cache, tokens, seq_lens, live, block_table,
                            hist, hist_len, ngrams, max_drafts,
                            keys=None, temps=None, top_ks=None, top_ps=None,
                            adapter_bank=None, adapter_slots=None, *,
                            config, block_size, attn_backend, tp_size, kv_pad,
                            total_slots, n_steps, d, max_ngram, mesh,
                            tp_wire=None, wire_block=256, sample=False):
    """K speculative windows under one lax.scan — the speculative sibling
    of ``_fused_decode_loop``. Each iteration: draft from the carried
    history ring, build the multi-token RaggedBatch **in-trace** (1+d
    tokens per row; token-major fields of length S*(1+d); per-position KV
    slots from the carried ``lens`` — writes past the accepted length are
    overwritten by the next window, which is the whole on-device rollback
    story), run ``_ragged_forward`` with ``window_logits=True``, verify on
    device, append the emitted tokens to the ring, and advance ``lens`` by
    the per-row emit count. Dead (padding) rows scatter to the OOB drop
    slot, emit nothing, and never advance.

    ``sample=False`` verifies by exact argmax match — byte-identical to
    the host ``accept_drafts`` — with no sort/filter/PRNG work in the
    trace. ``sample=True`` runs ``ops/sampling.spec_verify_window``
    (rejection sampling against the point-mass drafts, one key split per
    row per WINDOW), the same function the host fallback applies
    row-at-a-time, so streams agree bit-for-bit under the same keys."""
    from ...ops import sampling as dsamp
    S, B = block_table.shape
    Np1 = 1 + d
    ar = jnp.arange(S, dtype=jnp.int32)
    jw = jnp.arange(Np1, dtype=jnp.int32)
    live_i = live.astype(jnp.int32)

    def body(carry, _):
        cache, toks, lens, hist, hlen, keys = carry
        drafts, dlen = dsamp.ngram_draft_ring(
            hist, hlen, ngrams, max_drafts, max_ngram=max_ngram, d=d)
        dlen = jnp.where(live_i > 0, dlen, 0)
        feed = jnp.concatenate([toks[:, None], drafts], axis=1)   # [S, 1+d]
        pos = lens[:, None] + jw[None, :]
        slot = (block_table[ar[:, None], pos // block_size] * block_size
                + pos % block_size)
        slot = jnp.where(live_i[:, None] > 0, slot, total_slots)
        batch = RaggedBatch(
            tokens=feed.reshape(-1), token_seq=jnp.repeat(ar, Np1),
            token_pos=pos.reshape(-1), token_slot=slot.reshape(-1),
            seq_start=ar * Np1, seq_n_new=live_i * Np1, seq_seen=lens,
            block_table=block_table, last_token_idx=ar * Np1,
            q_tok_idx=(ar * Np1)[:, None] + jw[None, :])
        logits, cache = _ragged_forward(
            params, cache, batch, adapter_bank, adapter_slots,
            config=config, block_size=block_size,
            attn_backend=attn_backend, tp_size=tp_size, kv_pad=kv_pad,
            mesh=mesh, tp_wire=tp_wire, wire_block=wire_block,
            window_logits=True)                          # [S, 1+d, V]
        if sample:
            out, n_emit, keys = dsamp.spec_verify_window(
                logits, drafts, dlen, keys, temps, top_ks, top_ps, d=d)
        else:
            g_tok = jnp.argmax(logits.astype(jnp.float32),
                               axis=-1).astype(jnp.int32)         # [S, 1+d]
            acc = (drafts == g_tok[:, :d]) & (jw[None, :d] < dlen[:, None])
            m = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1),
                        axis=1).astype(jnp.int32)
            corr = g_tok[ar, m]
            drafts_pad = jnp.concatenate([drafts, drafts[:, -1:]], axis=1)
            out = jnp.where(jw[None, :] < m[:, None], drafts_pad,
                            corr[:, None])
            n_emit = m + 1
        n_emit = jnp.where(live_i > 0, n_emit, 0)
        last = out[ar, jnp.maximum(n_emit - 1, 0)]
        toks = jnp.where(live_i > 0, last, toks)
        hist, hlen = dsamp.ring_append(hist, hlen, out, n_emit)
        lens = lens + n_emit
        return (cache, toks, lens, hist, hlen, keys), (out, n_emit, dlen)

    if not sample:
        keys = jnp.zeros((S, 2), jnp.uint32)
    carry0 = (cache, tokens, seq_lens, hist, hist_len, keys)
    (cache, _, _, _, _, keys), (out, n_emit, dlen) = jax.lax.scan(
        body, carry0, jnp.arange(n_steps, dtype=jnp.int32))
    if not sample:
        return out, n_emit, dlen, cache
    return out, n_emit, dlen, keys, cache
