"""Inference config (reference ``deepspeed/inference/config.py``).

Same key surface (dtype, tensor_parallel/tp_size, max_out_tokens,
replace_with_kernel_inject, ...); kernel-injection flags are accepted for
API parity — on TPU "injection" is jit + Pallas kernels + sharding rules,
applied automatically.
"""

from typing import Any, Dict, Optional

from pydantic import Field

from ..config.config_utils import ConfigModel


class DeepSpeedTPConfig(ConfigModel):
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(ConfigModel):
    enabled: bool = True
    ep_size: int = 1
    moe_experts: Any = 1
    type: str = "standard"


class QuantizationConfig(ConfigModel):
    enabled: bool = False
    num_bits: int = 8
    group_size: int = 64


class DeepSpeedInferenceConfig(ConfigModel):
    kernel_inject: bool = Field(False, alias="replace_with_kernel_inject")
    dtype: str = "bfloat16"
    tensor_parallel: DeepSpeedTPConfig = Field({}, alias="tp")
    enable_cuda_graph: bool = False  # parity no-op: XLA always compiles
    zero: Dict[str, Any] = {}
    triangular_masking: bool = Field(True, alias="triangular_masking")
    moe: DeepSpeedMoEConfig = {}
    quant: QuantizationConfig = {}
    max_out_tokens: int = Field(1024, alias="max_out_tokens")
    min_out_tokens: int = Field(1, alias="min_out_tokens")
    max_tokens: int = 1024
    checkpoint: Optional[Any] = None
    replace_method: str = "auto"
    injection_policy: Optional[Dict] = None
    return_tuple: bool = True
    set_empty_params: bool = False
