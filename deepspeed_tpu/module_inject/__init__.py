from .replace_policy import (HFCheckpointPolicy, LlamaPolicy, MistralPolicy, Qwen2Policy,
                             Gemma2Policy, OPTPolicy, PhiPolicy, FalconPolicy,
                             policy_for, SUPPORTED_ARCHS)
from .replace_module import (convert_hf_checkpoint, convert_hf_safetensors,
                             export_hf_checkpoint, merge_peft_adapter,
                             replace_transformer_layer)
