from .replace_policy import (HFCheckpointPolicy, LlamaPolicy, MistralPolicy, Qwen2Policy,
                             Gemma2Policy, policy_for, SUPPORTED_ARCHS)
from .replace_module import convert_hf_checkpoint, export_hf_checkpoint, replace_transformer_layer
