"""Per-architecture injection policies.

Reference: ``deepspeed/module_inject/replace_policy.py`` +
``containers/*`` (~20 archs): each policy knows an architecture's module
layout — which weights feed attention/MLP, which are column- vs row-parallel
— and maps HF modules onto the fused inference containers.

TPU equivalent: the "container" is the native flax Llama-family model
(``models/llama.py``) plus its paged-KV serving twin
(``inference/v2/model.py``); a policy here is (a) the HF→flax parameter name
map with layout fixups (torch Linear stores [out,in]; flax kernels are
[in,out]) and (b) the TP partition hints AutoTP consumes
(``parallel/tp.py``).
"""

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.llama import LlamaConfig


class HFCheckpointPolicy:
    """Base policy: llama-family weight map (LLaMA 2/3, Mistral, Qwen2 share
    the module graph; reference containers/llama.py, mistral, qwen2)."""

    arch: str = "llama"
    supports_bias: bool = False

    # AutoTP hints (reference policy.py container attrs)
    col_parallel = ["q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"]
    row_parallel = ["o_proj", "down_proj"]

    def config_from_hf(self, hf_config: Dict) -> LlamaConfig:
        """Map an HF config dict to LlamaConfig."""
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            num_key_value_heads=hf_config.get("num_key_value_heads",
                                              hf_config["num_attention_heads"]),
            max_position_embeddings=hf_config.get("max_position_embeddings", 8192),
            rms_norm_eps=hf_config.get("rms_norm_eps", 1e-5),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            tie_word_embeddings=hf_config.get("tie_word_embeddings", False),
        )

    def weight_map(self, layer: int, attention_bias: bool = False
                   ) -> Dict[str, Tuple[str, bool]]:
        """HF name -> (flax path under params['model'], transpose?)."""
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        out = {}
        if attention_bias:  # qwen2-style qkv biases (1-D: no transpose)
            for proj in ("q_proj", "k_proj", "v_proj"):
                out[p + f"self_attn.{proj}.bias"] = (f + f"self_attn/{proj}/bias", False)
        out.update({
            p + "self_attn.q_proj.weight": (f + "self_attn/q_proj/kernel", True),
            p + "self_attn.k_proj.weight": (f + "self_attn/k_proj/kernel", True),
            p + "self_attn.v_proj.weight": (f + "self_attn/v_proj/kernel", True),
            p + "self_attn.o_proj.weight": (f + "self_attn/o_proj/kernel", True),
            p + "mlp.gate_proj.weight": (f + "mlp/gate_proj/kernel", True),
            p + "mlp.up_proj.weight": (f + "mlp/up_proj/kernel", True),
            p + "mlp.down_proj.weight": (f + "mlp/down_proj/kernel", True),
            p + "input_layernorm.weight": (f + "input_layernorm/weight", False),
            p + "post_attention_layernorm.weight": (f + "post_attention_layernorm/weight",
                                                    False),
        })
        return out

    def global_map(self, tie_embeddings: bool) -> Dict[str, Tuple[str, bool]]:
        out = {
            "model.embed_tokens.weight": ("embed_tokens/embedding", False),
            "model.norm.weight": ("norm/weight", False),
        }
        if not tie_embeddings:
            out["lm_head.weight"] = ("lm_head/kernel", True)
        return out


class LlamaPolicy(HFCheckpointPolicy):
    arch = "llama"


class MistralPolicy(HFCheckpointPolicy):
    """Mistral: llama graph w/ sliding-window attention (reference
    containers/mistral)."""
    arch = "mistral"

    def config_from_hf(self, hf_config):
        cfg = super().config_from_hf(hf_config)
        import dataclasses
        return dataclasses.replace(cfg, sliding_window=hf_config.get("sliding_window"))


class Qwen2Policy(HFCheckpointPolicy):
    """Qwen2 adds attention qkv biases (reference containers/qwen2)."""
    arch = "qwen2"
    supports_bias = True

    def config_from_hf(self, hf_config):
        cfg = super().config_from_hf(hf_config)
        import dataclasses
        return dataclasses.replace(cfg, attention_bias=True)


class OlmoPolicy(HFCheckpointPolicy):
    """OLMo (AllenAI): llama module graph with NON-PARAMETRIC layernorm —
    no norm weights exist in the checkpoint — plus an optional q/k/v clamp
    (HF ``modeling_olmo.py`` OlmoLayerNorm / config.clip_qkv)."""
    arch = "olmo"

    def config_from_hf(self, hf_config):
        import dataclasses
        cfg = super().config_from_hf(hf_config)
        return dataclasses.replace(cfg, norm_type="layernorm_np",
                                   rms_norm_eps=1e-5,  # OlmoLayerNorm hardcodes
                                   clip_qkv=hf_config.get("clip_qkv"))

    def weight_map(self, layer: int, attention_bias: bool = False):
        out = super().weight_map(layer, attention_bias)
        return {k: v for k, v in out.items() if "layernorm" not in k}

    def global_map(self, tie_embeddings: bool):
        out = super().global_map(tie_embeddings)
        out.pop("model.norm.weight")  # non-parametric final norm
        return out


class Olmo2Policy(HFCheckpointPolicy):
    """OLMo2: parametric RMSNorm moved to the SUBLAYER OUTPUTS (post-norm:
    x + norm(attn(x)), x + norm(mlp(x))) plus RMSNorm on the flat q/k
    projections (HF ``modeling_olmo2.py`` Olmo2DecoderLayer/Olmo2Attention)."""
    arch = "olmo2"

    def config_from_hf(self, hf_config):
        import dataclasses
        cfg = super().config_from_hf(hf_config)
        return dataclasses.replace(cfg, qk_norm=True, post_norm=True)

    def weight_map(self, layer: int, attention_bias: bool = False):
        out = super().weight_map(layer, attention_bias)
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        out.pop(p + "input_layernorm.weight")  # no pre-norms in OLMo2
        out[p + "post_feedforward_layernorm.weight"] = \
            (f + "post_feedforward_layernorm/weight", False)
        out[p + "self_attn.q_norm.weight"] = (f + "self_attn/q_norm/weight", False)
        out[p + "self_attn.k_norm.weight"] = (f + "self_attn/k_norm/weight", False)
        return out


class CoherePolicy(HFCheckpointPolicy):
    """Cohere Command-R: weight-only layernorm, PARALLEL attn+mlp residual
    off ONE shared input norm, GPT-J-style interleaved rotary
    (repeat_interleave cos/sin), tied embeddings with ``logit_scale`` on the
    unembed (HF ``modeling_cohere.py`` — 'main diff from Llama')."""
    arch = "cohere"

    def config_from_hf(self, hf_config):
        import dataclasses
        if hf_config.get("use_qk_norm"):
            raise ValueError("cohere: use_qk_norm=True is not supported")
        cfg = super().config_from_hf(hf_config)
        return dataclasses.replace(
            cfg, norm_type="layernorm_nobias",
            rms_norm_eps=hf_config.get("layer_norm_eps", 1e-5),
            rope_interleaved=True,
            parallel_residual=True, parallel_residual_norms=1,
            tie_word_embeddings=hf_config.get("tie_word_embeddings", True),
            # HF CohereConfig default (NOT 1.0)
            logit_scale=hf_config.get("logit_scale", 0.0625))

    def weight_map(self, layer: int, attention_bias: bool = False):
        out = super().weight_map(layer, attention_bias)
        # one shared norm per layer; flax LayerNorm stores its weight as
        # "scale"
        out = {k: v for k, v in out.items()
               if "post_attention_layernorm" not in k}
        out[f"model.layers.{layer}.input_layernorm.weight"] = \
            (f"layers_{layer}/input_layernorm/scale", False)
        return out

    def global_map(self, tie_embeddings: bool):
        out = super().global_map(tie_embeddings)
        out["model.norm.weight"] = ("norm/scale", False)
        return out


class MixtralPolicy(HFCheckpointPolicy):
    """Mixtral: llama attention + sparse-MoE MLP (reference
    inference/v2/model_implementations/mixtral). Per-expert HF tensors are
    stacked into [E, ...] arrays — the layout the grouped einsum consumes."""
    arch = "mixtral"

    def config_from_hf(self, hf_config):
        cfg = super().config_from_hf(hf_config)
        import dataclasses
        return dataclasses.replace(
            cfg, num_local_experts=hf_config.get("num_local_experts", 8),
            num_experts_per_tok=hf_config.get("num_experts_per_tok", 2))

    def weight_map(self, layer: int, attention_bias: bool = False):
        out = super().weight_map(layer, attention_bias)
        # mixtral has no dense mlp — drop those entries
        return {k: v for k, v in out.items() if ".mlp." not in k}

    def moe_map(self, layer: int, num_experts: int):
        """HF names → (flax path, stacking) for the MoE block."""
        p = f"model.layers.{layer}.block_sparse_moe."
        f = f"layers_{layer}/block_sparse_moe/"
        gate = {p + "gate.weight": (f + "gate/kernel", True)}
        experts = {}
        for which, tr in (("w1", True), ("w2", True), ("w3", True)):
            experts[f + which] = [p + f"experts.{e}.{which}.weight" for e in range(num_experts)]
        return gate, experts


class Qwen2MoePolicy(MixtralPolicy):
    """Qwen2-MoE (reference ``inference/v2/model_implementations/qwen_v2_moe``):
    qwen2 attention (qkv biases) + sparse MoE with NON-renormalized top-k
    and a sigmoid-gated shared expert."""
    arch = "qwen2_moe"
    supports_bias = True

    def config_from_hf(self, hf_config):
        if hf_config.get("mlp_only_layers") or hf_config.get("decoder_sparse_step", 1) != 1:
            raise ValueError("qwen2-moe variants mixing dense-MLP layers "
                             "(mlp_only_layers/decoder_sparse_step) are not supported")
        import dataclasses
        cfg = HFCheckpointPolicy.config_from_hf(self, hf_config)
        return dataclasses.replace(
            cfg,
            attention_bias=True,
            intermediate_size=hf_config["moe_intermediate_size"],
            num_local_experts=hf_config.get("num_experts", 60),
            num_experts_per_tok=hf_config.get("num_experts_per_tok", 4),
            moe_renormalize=bool(hf_config.get("norm_topk_prob", False)),
            shared_expert_intermediate_size=hf_config.get(
                "shared_expert_intermediate_size"))

    def moe_map(self, layer: int, num_experts: int):
        p = f"model.layers.{layer}.mlp."
        f = f"layers_{layer}/block_sparse_moe/"
        gate = {
            p + "gate.weight": (f + "gate/kernel", True),
            p + "shared_expert_gate.weight": (f + "shared_expert_gate/kernel", True),
        }
        for proj in ("gate_proj", "up_proj", "down_proj"):
            gate[p + f"shared_expert.{proj}.weight"] = (
                f + f"shared_expert/{proj}/kernel", True)
        experts = {}
        for hf_name, fx in (("gate_proj", "w1"), ("up_proj", "w3"),
                            ("down_proj", "w2")):
            experts[f + fx] = [p + f"experts.{e}.{hf_name}.weight"
                               for e in range(num_experts)]
        return gate, experts


class GemmaPolicy(HFCheckpointPolicy):
    """Gemma (v1): llama graph with (1+weight) RMSNorm, sqrt(hidden) embed
    normalizer (rounded through the compute dtype, as HF does), tanh-gelu
    gated MLP, explicit head_dim, tied embeddings."""
    arch = "gemma"

    def config_from_hf(self, hf_config):
        import dataclasses
        cfg = super().config_from_hf(hf_config)
        return dataclasses.replace(
            cfg, tie_word_embeddings=True, norm_plus_one=True,
            head_dim=hf_config.get(
                "head_dim",
                hf_config["hidden_size"] // hf_config["num_attention_heads"]),
            embed_scale=float(hf_config["hidden_size"]) ** 0.5,
            mlp_type="geglu_tanh")


class Gemma2Policy(GemmaPolicy):
    """Gemma-2 adds sandwich norms (pre+post around both sublayers),
    attention/final logit softcapping, query_pre_attn_scalar-derived scale,
    and a sliding window on every EVEN layer (HF: ``not bool(layer_idx %
    2)``)."""
    arch = "gemma2"

    def config_from_hf(self, hf_config):
        import dataclasses
        cfg = super().config_from_hf(hf_config)
        return dataclasses.replace(
            cfg, sandwich_norm=True,
            attn_scale=float(hf_config.get("query_pre_attn_scalar", 256)) ** -0.5,
            attn_logit_softcapping=hf_config.get("attn_logit_softcapping", 50.0),
            final_logit_softcapping=hf_config.get("final_logit_softcapping", 30.0),
            sliding_window=hf_config.get("sliding_window"),
            sliding_window_layers=tuple(
                range(0, hf_config["num_hidden_layers"], 2)))

    def weight_map(self, layer: int, attention_bias: bool = False):
        out = super().weight_map(layer, attention_bias)
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        out[p + "pre_feedforward_layernorm.weight"] = \
            (f + "pre_feedforward_layernorm/weight", False)
        out[p + "post_feedforward_layernorm.weight"] = \
            (f + "post_feedforward_layernorm/weight", False)
        return out


class OPTPolicy(HFCheckpointPolicy):
    """OPT (reference ``module_inject/containers/opt.py`` +
    ``inference/v2/model_implementations/opt``): learned positions (table
    offset by 2 in HF), pre-LayerNorm, ReLU fc MLP, biases everywhere,
    tied lm_head. ``word_embed_proj_dim != hidden_size`` variants (350m's
    project_in/out) are out of scope."""
    arch = "opt"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        if hf_config.get("word_embed_proj_dim",
                         hf_config["hidden_size"]) != hf_config["hidden_size"]:
            raise ValueError("OPT variants with word_embed_proj_dim != hidden_size "
                             "(project_in/out) are not supported")
        if not hf_config.get("do_layer_norm_before", True):
            raise ValueError("OPT do_layer_norm_before=False (post-LN, the 350m "
                             "ordering) is not supported — the decoder here is "
                             "pre-LN only")
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["ffn_dim"],
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            num_key_value_heads=hf_config["num_attention_heads"],
            max_position_embeddings=hf_config.get("max_position_embeddings", 2048),
            rms_norm_eps=1e-5,
            tie_word_embeddings=hf_config.get("tie_word_embeddings", True),
            attention_bias=hf_config.get("enable_bias", True),
            attention_out_bias=hf_config.get("enable_bias", True),
            norm_type="layernorm",
            pos_embedding="learned",
            pos_offset=2,
            mlp_type="relu_fc",
            mlp_bias=hf_config.get("enable_bias", True),
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"model.decoder.layers.{layer}."
        f = f"layers_{layer}/"
        out = {}
        for hf, fx in (("q_proj", "q_proj"), ("k_proj", "k_proj"),
                       ("v_proj", "v_proj"), ("out_proj", "o_proj")):
            out[p + f"self_attn.{hf}.weight"] = (f + f"self_attn/{fx}/kernel", True)
            if attention_bias:  # enable_bias=False checkpoints have none
                out[p + f"self_attn.{hf}.bias"] = (f + f"self_attn/{fx}/bias", False)
        if attention_bias:
            out.update({
                p + "fc1.bias": (f + "mlp/fc1/bias", False),
                p + "fc2.bias": (f + "mlp/fc2/bias", False),
            })
        out.update({
            p + "self_attn_layer_norm.weight": (f + "input_layernorm/scale", False),
            p + "self_attn_layer_norm.bias": (f + "input_layernorm/bias", False),
            p + "final_layer_norm.weight": (f + "post_attention_layernorm/scale", False),
            p + "final_layer_norm.bias": (f + "post_attention_layernorm/bias", False),
            p + "fc1.weight": (f + "mlp/fc1/kernel", True),
            p + "fc2.weight": (f + "mlp/fc2/kernel", True),
        })
        return out

    def global_map(self, tie_embeddings: bool):
        return {
            "model.decoder.embed_tokens.weight": ("embed_tokens/embedding", False),
            "model.decoder.embed_positions.weight": ("embed_positions/embedding", False),
            "model.decoder.final_layer_norm.weight": ("norm/scale", False),
            "model.decoder.final_layer_norm.bias": ("norm/bias", False),
        }


class PhiPolicy(HFCheckpointPolicy):
    """Phi-1/2 (reference ``inference/v2/model_implementations/phi``):
    parallel attention+MLP over ONE shared LayerNorm, partial rotary, GELU fc
    MLP, biases everywhere including the lm_head."""
    arch = "phi"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        if hf_config.get("qk_layernorm"):
            raise ValueError("phi qk_layernorm=True checkpoints are not supported "
                             "(q/k layernorm weights would be dropped)")
        hd = hf_config["hidden_size"] // hf_config["num_attention_heads"]
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            num_key_value_heads=hf_config.get("num_key_value_heads")
            or hf_config["num_attention_heads"],
            max_position_embeddings=hf_config.get("max_position_embeddings", 2048),
            rms_norm_eps=hf_config.get("layer_norm_eps", 1e-5),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            rotary_dim=int(hf_config.get("partial_rotary_factor", 0.5) * hd),
            attention_bias=True,
            attention_out_bias=True,
            norm_type="layernorm",
            mlp_type="gelu_tanh_fc",  # HF phi hidden_act "gelu_new"
            mlp_bias=True,
            parallel_residual=True,
            lm_head_bias=True,
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        out = {}
        for hf, fx in (("q_proj", "q_proj"), ("k_proj", "k_proj"),
                       ("v_proj", "v_proj"), ("dense", "o_proj")):
            out[p + f"self_attn.{hf}.weight"] = (f + f"self_attn/{fx}/kernel", True)
            out[p + f"self_attn.{hf}.bias"] = (f + f"self_attn/{fx}/bias", False)
        out.update({
            p + "input_layernorm.weight": (f + "input_layernorm/scale", False),
            p + "input_layernorm.bias": (f + "input_layernorm/bias", False),
            p + "mlp.fc1.weight": (f + "mlp/fc1/kernel", True),
            p + "mlp.fc1.bias": (f + "mlp/fc1/bias", False),
            p + "mlp.fc2.weight": (f + "mlp/fc2/kernel", True),
            p + "mlp.fc2.bias": (f + "mlp/fc2/bias", False),
        })
        return out

    def global_map(self, tie_embeddings: bool):
        return {
            "model.embed_tokens.weight": ("embed_tokens/embedding", False),
            "model.final_layernorm.weight": ("norm/scale", False),
            "model.final_layernorm.bias": ("norm/bias", False),
            "lm_head.weight": ("lm_head/kernel", True),
            "lm_head.bias": ("lm_head/bias", False),
        }


class FalconPolicy(HFCheckpointPolicy):
    """Falcon-7B family (reference ``module_inject/containers/`` falcon +
    ``inference/v2/model_implementations/falcon``): multi-query attention
    (1 KV head) with a FUSED query_key_value tensor, parallel attention+MLP
    over one LayerNorm, GELU fc MLP. The new_decoder_architecture (40B
    grouped ln_attn/ln_mlp) variant is out of scope."""
    arch = "falcon"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        if hf_config.get("new_decoder_architecture"):
            raise ValueError("falcon new_decoder_architecture (40B/180B ln_attn/"
                             "ln_mlp) is not supported; 7B-family only")
        if hf_config.get("alibi"):
            raise ValueError("falcon-rw alibi positions are not supported "
                             "(this model family uses rotary)")
        if not hf_config.get("multi_query", True):
            raise ValueError("falcon multi_query=False uses a per-head "
                             "interleaved fused qkv layout; not supported")
        if hf_config.get("bias"):
            raise ValueError("falcon bias=True checkpoints are not supported "
                             "(bias tensors have no conversion entries)")
        if not hf_config.get("parallel_attn", True):
            raise ValueError("falcon parallel_attn=False (sequential residual "
                             "with post-attention ln) is not supported")
        h = hf_config["hidden_size"]
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("ffn_hidden_size", 4 * h),
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            num_key_value_heads=1 if hf_config.get("multi_query", True)
            else hf_config["num_attention_heads"],
            max_position_embeddings=hf_config.get("max_position_embeddings", 2048),
            rms_norm_eps=hf_config.get("layer_norm_epsilon", 1e-5),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            tie_word_embeddings=hf_config.get("tie_word_embeddings", True),
            attention_bias=hf_config.get("bias", False),
            attention_out_bias=hf_config.get("bias", False),
            norm_type="layernorm",
            mlp_type="gelu_fc",
            mlp_bias=hf_config.get("bias", False),
            parallel_residual=hf_config.get("parallel_attn", True),
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"transformer.h.{layer}."
        f = f"layers_{layer}/"
        return {
            p + "self_attention.dense.weight": (f + "self_attn/o_proj/kernel", True),
            p + "input_layernorm.weight": (f + "input_layernorm/scale", False),
            p + "input_layernorm.bias": (f + "input_layernorm/bias", False),
            p + "mlp.dense_h_to_4h.weight": (f + "mlp/fc1/kernel", True),
            p + "mlp.dense_4h_to_h.weight": (f + "mlp/fc2/kernel", True),
        }

    def special_hf_names(self, layer: int):
        """HF tensors convert_special consumes (streaming conversion buffers
        exactly these, nothing else)."""
        return [f"transformer.h.{layer}.self_attention.query_key_value.weight"]

    def convert_special(self, layer: int, cfg: LlamaConfig, get_tensor, put):
        """Split the fused MQA query_key_value tensor: rows are
        [nq*hd | hd (k) | hd (v)]."""
        hf = f"transformer.h.{layer}.self_attention.query_key_value.weight"
        w = get_tensor(hf)  # [(nq + 2*nkv) * hd, h]
        hd = cfg.head_dim_
        nq, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
        f = f"layers_{layer}/self_attn/"
        put(f + "q_proj/kernel", w[:nq * hd].T)
        put(f + "k_proj/kernel", w[nq * hd:(nq + nkv) * hd].T)
        put(f + "v_proj/kernel", w[(nq + nkv) * hd:].T)

    def export_special(self, layer: int, cfg: LlamaConfig, flat):
        f = f"layers_{layer}/self_attn/"
        qkv = np.concatenate([flat[f + "q_proj/kernel"].T,
                              flat[f + "k_proj/kernel"].T,
                              flat[f + "v_proj/kernel"].T], axis=0)
        return {f"transformer.h.{layer}.self_attention.query_key_value.weight": qkv}

    def global_map(self, tie_embeddings: bool):
        out = {
            "transformer.word_embeddings.weight": ("embed_tokens/embedding", False),
            "transformer.ln_f.weight": ("norm/scale", False),
            "transformer.ln_f.bias": ("norm/bias", False),
        }
        if not tie_embeddings:
            out["lm_head.weight"] = ("lm_head/kernel", True)
        return out


class GPT2Policy(HFCheckpointPolicy):
    """GPT-2 (reference ``module_inject/containers/gpt2.py``): learned
    positions (no offset), pre-LN LayerNorm, gelu_new fc MLP, biases
    everywhere, fused Conv1D ``c_attn`` qkv. HF Conv1D stores weights
    ``[in, out]`` — already the flax kernel layout, so nothing transposes."""
    arch = "gpt2"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        h = hf_config["n_embd"]
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("n_inner") or 4 * h,
            num_hidden_layers=hf_config["n_layer"],
            num_attention_heads=hf_config["n_head"],
            num_key_value_heads=hf_config["n_head"],
            max_position_embeddings=hf_config.get("n_positions", 1024),
            rms_norm_eps=hf_config.get("layer_norm_epsilon", 1e-5),
            tie_word_embeddings=True,
            attention_bias=True,
            attention_out_bias=True,
            norm_type="layernorm",
            pos_embedding="learned",
            mlp_type="gelu_tanh_fc",  # HF activation_function "gelu_new"
            mlp_bias=True,
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"transformer.h.{layer}."
        f = f"layers_{layer}/"
        return {
            p + "ln_1.weight": (f + "input_layernorm/scale", False),
            p + "ln_1.bias": (f + "input_layernorm/bias", False),
            p + "ln_2.weight": (f + "post_attention_layernorm/scale", False),
            p + "ln_2.bias": (f + "post_attention_layernorm/bias", False),
            p + "attn.c_proj.weight": (f + "self_attn/o_proj/kernel", False),
            p + "attn.c_proj.bias": (f + "self_attn/o_proj/bias", False),
            p + "mlp.c_fc.weight": (f + "mlp/fc1/kernel", False),
            p + "mlp.c_fc.bias": (f + "mlp/fc1/bias", False),
            p + "mlp.c_proj.weight": (f + "mlp/fc2/kernel", False),
            p + "mlp.c_proj.bias": (f + "mlp/fc2/bias", False),
        }

    def special_hf_names(self, layer: int):
        p = f"transformer.h.{layer}.attn.c_attn."
        return [p + "weight", p + "bias"]

    def convert_special(self, layer: int, cfg: LlamaConfig, get_tensor, put):
        """Split fused c_attn: Conv1D weight [h, 3h] columns are [q | k | v]."""
        p = f"transformer.h.{layer}.attn.c_attn."
        w = get_tensor(p + "weight")  # [h, 3h], already [in, out]
        b = get_tensor(p + "bias")    # [3h]
        h = cfg.hidden_size
        f = f"layers_{layer}/self_attn/"
        for i, proj in enumerate(("q_proj", "k_proj", "v_proj")):
            put(f + f"{proj}/kernel", w[:, i * h:(i + 1) * h])
            put(f + f"{proj}/bias", b[i * h:(i + 1) * h])

    def export_special(self, layer: int, cfg: LlamaConfig, flat):
        f = f"layers_{layer}/self_attn/"
        p = f"transformer.h.{layer}.attn.c_attn."
        return {
            p + "weight": np.concatenate(
                [flat[f + f"{x}/kernel"] for x in ("q_proj", "k_proj", "v_proj")], axis=1),
            p + "bias": np.concatenate(
                [flat[f + f"{x}/bias"] for x in ("q_proj", "k_proj", "v_proj")]),
        }

    def global_map(self, tie_embeddings: bool):
        return {
            "transformer.wte.weight": ("embed_tokens/embedding", False),
            "transformer.wpe.weight": ("embed_positions/embedding", False),
            "transformer.ln_f.weight": ("norm/scale", False),
            "transformer.ln_f.bias": ("norm/bias", False),
        }


class GPTNeoXPolicy(HFCheckpointPolicy):
    """GPT-NeoX / Pythia (reference ``module_inject/containers/gptneox.py``):
    partial rotary (rotary_pct), two-norm parallel residual
    (x + attn(ln1 x) + mlp(ln2 x)), per-head-interleaved fused
    query_key_value, biases everywhere, untied embed_out."""
    arch = "gptneox"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        h = hf_config["hidden_size"]
        nq = hf_config["num_attention_heads"]
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("intermediate_size", 4 * h),
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=nq,
            num_key_value_heads=nq,
            max_position_embeddings=hf_config.get("max_position_embeddings", 2048),
            rms_norm_eps=hf_config.get("layer_norm_eps", 1e-5),
            rope_theta=hf_config.get("rotary_emb_base", 10000.0),
            rotary_dim=int(hf_config.get("rotary_pct", 0.25) * (h // nq)),
            tie_word_embeddings=False,
            attention_bias=True,
            attention_out_bias=True,
            norm_type="layernorm",
            mlp_type="gelu_fc",  # HF hidden_act "gelu" (erf)
            mlp_bias=True,
            parallel_residual=hf_config.get("use_parallel_residual", True),
            parallel_residual_norms=2,
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"gpt_neox.layers.{layer}."
        f = f"layers_{layer}/"
        return {
            p + "input_layernorm.weight": (f + "input_layernorm/scale", False),
            p + "input_layernorm.bias": (f + "input_layernorm/bias", False),
            p + "post_attention_layernorm.weight": (f + "post_attention_layernorm/scale",
                                                    False),
            p + "post_attention_layernorm.bias": (f + "post_attention_layernorm/bias",
                                                  False),
            p + "attention.dense.weight": (f + "self_attn/o_proj/kernel", True),
            p + "attention.dense.bias": (f + "self_attn/o_proj/bias", False),
            p + "mlp.dense_h_to_4h.weight": (f + "mlp/fc1/kernel", True),
            p + "mlp.dense_h_to_4h.bias": (f + "mlp/fc1/bias", False),
            p + "mlp.dense_4h_to_h.weight": (f + "mlp/fc2/kernel", True),
            p + "mlp.dense_4h_to_h.bias": (f + "mlp/fc2/bias", False),
        }

    def special_hf_names(self, layer: int):
        p = f"gpt_neox.layers.{layer}.attention.query_key_value."
        return [p + "weight", p + "bias"]

    def convert_special(self, layer: int, cfg: LlamaConfig, get_tensor, put):
        """Un-interleave fused qkv: rows are grouped PER HEAD as
        [q_i | k_i | v_i] (hd each), unlike falcon's [all q | k | v]."""
        p = f"gpt_neox.layers.{layer}.attention.query_key_value."
        hd = cfg.head_dim_
        nq = cfg.num_attention_heads
        w = get_tensor(p + "weight").reshape(nq, 3, hd, cfg.hidden_size)
        b = get_tensor(p + "bias").reshape(nq, 3, hd)
        f = f"layers_{layer}/self_attn/"
        for i, proj in enumerate(("q_proj", "k_proj", "v_proj")):
            put(f + f"{proj}/kernel", w[:, i].reshape(nq * hd, cfg.hidden_size).T)
            put(f + f"{proj}/bias", b[:, i].reshape(nq * hd))

    def export_special(self, layer: int, cfg: LlamaConfig, flat):
        hd = cfg.head_dim_
        nq = cfg.num_attention_heads
        f = f"layers_{layer}/self_attn/"
        w = np.stack([flat[f + f"{x}/kernel"].T.reshape(nq, hd, cfg.hidden_size)
                      for x in ("q_proj", "k_proj", "v_proj")], axis=1)
        b = np.stack([flat[f + f"{x}/bias"].reshape(nq, hd)
                      for x in ("q_proj", "k_proj", "v_proj")], axis=1)
        p = f"gpt_neox.layers.{layer}.attention.query_key_value."
        return {p + "weight": w.reshape(3 * nq * hd, cfg.hidden_size),
                p + "bias": b.reshape(3 * nq * hd)}

    def global_map(self, tie_embeddings: bool):
        return {
            "gpt_neox.embed_in.weight": ("embed_tokens/embedding", False),
            "gpt_neox.final_layer_norm.weight": ("norm/scale", False),
            "gpt_neox.final_layer_norm.bias": ("norm/bias", False),
            "embed_out.weight": ("lm_head/kernel", True),
        }


class InternLMPolicy(HFCheckpointPolicy):
    """InternLM-7B (reference ``module_inject/containers/internlm.py``):
    llama graph plus biases on all four attention projections."""
    arch = "internlm"

    def config_from_hf(self, hf_config):
        cfg = super().config_from_hf(hf_config)
        import dataclasses
        bias = hf_config.get("bias", True)
        return dataclasses.replace(cfg, attention_bias=bias, attention_out_bias=bias)

    def weight_map(self, layer: int, attention_bias: bool = False):
        out = super().weight_map(layer, attention_bias)
        if attention_bias:
            p = f"model.layers.{layer}."
            f = f"layers_{layer}/"
            out[p + "self_attn.o_proj.bias"] = (f + "self_attn/o_proj/bias", False)
        return out


class Phi3Policy(HFCheckpointPolicy):
    """Phi-3 (reference ``inference/v2/model_implementations/phi3``): llama
    graph (rmsnorm, swiglu, untied head) with FUSED qkv_proj and
    gate_up_proj tensors."""
    arch = "phi3"

    def config_from_hf(self, hf_config):
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            num_key_value_heads=hf_config.get("num_key_value_heads",
                                              hf_config["num_attention_heads"]),
            max_position_embeddings=hf_config.get("max_position_embeddings", 4096),
            rms_norm_eps=hf_config.get("rms_norm_eps", 1e-5),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            tie_word_embeddings=hf_config.get("tie_word_embeddings", False),
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        return {
            p + "self_attn.o_proj.weight": (f + "self_attn/o_proj/kernel", True),
            p + "mlp.down_proj.weight": (f + "mlp/down_proj/kernel", True),
            p + "input_layernorm.weight": (f + "input_layernorm/weight", False),
            p + "post_attention_layernorm.weight": (f + "post_attention_layernorm/weight",
                                                    False),
        }

    def special_hf_names(self, layer: int):
        p = f"model.layers.{layer}."
        return [p + "self_attn.qkv_proj.weight", p + "mlp.gate_up_proj.weight"]

    def convert_special(self, layer: int, cfg: LlamaConfig, get_tensor, put):
        """qkv_proj rows are [all q | all k | all v]; gate_up_proj rows are
        [gate | up]."""
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        hd = cfg.head_dim_
        nq, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
        w = get_tensor(p + "self_attn.qkv_proj.weight")
        put(f + "self_attn/q_proj/kernel", w[:nq * hd].T)
        put(f + "self_attn/k_proj/kernel", w[nq * hd:(nq + nkv) * hd].T)
        put(f + "self_attn/v_proj/kernel", w[(nq + nkv) * hd:].T)
        gu = get_tensor(p + "mlp.gate_up_proj.weight")
        put(f + "mlp/gate_proj/kernel", gu[:cfg.intermediate_size].T)
        put(f + "mlp/up_proj/kernel", gu[cfg.intermediate_size:].T)

    def export_special(self, layer: int, cfg: LlamaConfig, flat):
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        return {
            p + "self_attn.qkv_proj.weight": np.concatenate(
                [flat[f + f"self_attn/{x}/kernel"].T for x in ("q_proj", "k_proj", "v_proj")],
                axis=0),
            p + "mlp.gate_up_proj.weight": np.concatenate(
                [flat[f + "mlp/gate_proj/kernel"].T, flat[f + "mlp/up_proj/kernel"].T],
                axis=0),
        }


class BaichuanPolicy(HFCheckpointPolicy):
    """Baichuan-7B: llama graph with a fused W_pack qkv tensor (rows
    [q | k | v]). The 13B variant uses ALiBi positions — not supported."""
    arch = "baichuan"

    def config_from_hf(self, hf_config):
        if hf_config.get("position_embedding", "rope").lower() == "alibi" or \
                hf_config.get("hidden_size", 0) >= 5120:
            raise ValueError("baichuan-13B (ALiBi positions) is not supported; "
                             "7B (rope) only")
        return super().config_from_hf(hf_config)

    def weight_map(self, layer: int, attention_bias: bool = False):
        out = super().weight_map(layer, attention_bias)
        p = f"model.layers.{layer}."
        # qkv arrive fused as W_pack (convert_special)
        for proj in ("q_proj", "k_proj", "v_proj"):
            out.pop(p + f"self_attn.{proj}.weight", None)
        return out

    def special_hf_names(self, layer: int):
        return [f"model.layers.{layer}.self_attn.W_pack.weight"]

    def convert_special(self, layer: int, cfg: LlamaConfig, get_tensor, put):
        w = get_tensor(f"model.layers.{layer}.self_attn.W_pack.weight")
        h = cfg.hidden_size
        f = f"layers_{layer}/self_attn/"
        put(f + "q_proj/kernel", w[:h].T)
        put(f + "k_proj/kernel", w[h:2 * h].T)
        put(f + "v_proj/kernel", w[2 * h:].T)

    def export_special(self, layer: int, cfg: LlamaConfig, flat):
        f = f"layers_{layer}/self_attn/"
        return {f"model.layers.{layer}.self_attn.W_pack.weight": np.concatenate(
            [flat[f + f"{x}/kernel"].T for x in ("q_proj", "k_proj", "v_proj")], axis=0)}


class BloomPolicy(HFCheckpointPolicy):
    """BLOOM (reference ``module_inject/containers/bloom.py``): ALiBi
    positions, embedding LayerNorm, per-head-interleaved fused
    query_key_value (same layout as NeoX), gelu-tanh MLP, biases
    everywhere, tied embeddings."""
    arch = "bloom"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        if hf_config.get("apply_residual_connection_post_layernorm"):
            raise ValueError("bloom apply_residual_connection_post_layernorm=True "
                             "is not supported (pre-LN residual only)")
        h = hf_config.get("hidden_size") or hf_config["n_embed"]
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=4 * h,
            num_hidden_layers=hf_config["n_layer"],
            num_attention_heads=hf_config["n_head"],
            num_key_value_heads=hf_config["n_head"],
            max_position_embeddings=hf_config.get("seq_length", 2048),
            rms_norm_eps=hf_config.get("layer_norm_epsilon", 1e-5),
            tie_word_embeddings=True,
            attention_bias=True,
            attention_out_bias=True,
            norm_type="layernorm",
            pos_embedding="alibi",
            embed_layernorm=True,
            mlp_type="gelu_tanh_fc",  # BloomGelu = tanh approximation
            mlp_bias=True,
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"transformer.h.{layer}."
        f = f"layers_{layer}/"
        return {
            p + "input_layernorm.weight": (f + "input_layernorm/scale", False),
            p + "input_layernorm.bias": (f + "input_layernorm/bias", False),
            p + "post_attention_layernorm.weight": (f + "post_attention_layernorm/scale",
                                                    False),
            p + "post_attention_layernorm.bias": (f + "post_attention_layernorm/bias",
                                                  False),
            p + "self_attention.dense.weight": (f + "self_attn/o_proj/kernel", True),
            p + "self_attention.dense.bias": (f + "self_attn/o_proj/bias", False),
            p + "mlp.dense_h_to_4h.weight": (f + "mlp/fc1/kernel", True),
            p + "mlp.dense_h_to_4h.bias": (f + "mlp/fc1/bias", False),
            p + "mlp.dense_4h_to_h.weight": (f + "mlp/fc2/kernel", True),
            p + "mlp.dense_4h_to_h.bias": (f + "mlp/fc2/bias", False),
        }

    def special_hf_names(self, layer: int):
        p = f"transformer.h.{layer}.self_attention.query_key_value."
        return [p + "weight", p + "bias"]

    def convert_special(self, layer: int, cfg: LlamaConfig, get_tensor, put):
        """Fused qkv rows are grouped per head as [q_i | k_i | v_i]."""
        p = f"transformer.h.{layer}.self_attention.query_key_value."
        hd = cfg.head_dim_
        nq = cfg.num_attention_heads
        w = get_tensor(p + "weight").reshape(nq, 3, hd, cfg.hidden_size)
        b = get_tensor(p + "bias").reshape(nq, 3, hd)
        f = f"layers_{layer}/self_attn/"
        for i, proj in enumerate(("q_proj", "k_proj", "v_proj")):
            put(f + f"{proj}/kernel", w[:, i].reshape(nq * hd, cfg.hidden_size).T)
            put(f + f"{proj}/bias", b[:, i].reshape(nq * hd))

    def export_special(self, layer: int, cfg: LlamaConfig, flat):
        hd = cfg.head_dim_
        nq = cfg.num_attention_heads
        f = f"layers_{layer}/self_attn/"
        w = np.stack([flat[f + f"{x}/kernel"].T.reshape(nq, hd, cfg.hidden_size)
                      for x in ("q_proj", "k_proj", "v_proj")], axis=1)
        b = np.stack([flat[f + f"{x}/bias"].reshape(nq, hd)
                      for x in ("q_proj", "k_proj", "v_proj")], axis=1)
        p = f"transformer.h.{layer}.self_attention.query_key_value."
        return {p + "weight": w.reshape(3 * nq * hd, cfg.hidden_size),
                p + "bias": b.reshape(3 * nq * hd)}

    def global_map(self, tie_embeddings: bool):
        return {
            "transformer.word_embeddings.weight": ("embed_tokens/embedding", False),
            "transformer.word_embeddings_layernorm.weight": ("embed_layernorm/scale",
                                                             False),
            "transformer.word_embeddings_layernorm.bias": ("embed_layernorm/bias", False),
            "transformer.ln_f.weight": ("norm/scale", False),
            "transformer.ln_f.bias": ("norm/bias", False),
        }


class GPTJPolicy(HFCheckpointPolicy):
    """GPT-J (reference ``module_inject/containers/gptj.py``): interleaved
    (adjacent-pair) partial rotary, single-norm parallel residual, gelu_new
    fc MLP (biased), bias-free attention, untied lm_head WITH bias."""
    arch = "gptj"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        h = hf_config["n_embd"]
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("n_inner") or 4 * h,
            num_hidden_layers=hf_config["n_layer"],
            num_attention_heads=hf_config["n_head"],
            num_key_value_heads=hf_config["n_head"],
            max_position_embeddings=hf_config.get("n_positions", 2048),
            rms_norm_eps=hf_config.get("layer_norm_epsilon", 1e-5),
            rotary_dim=hf_config.get("rotary_dim", 64),
            rope_interleaved=True,
            tie_word_embeddings=False,
            norm_type="layernorm",
            mlp_type="gelu_tanh_fc",  # HF activation_function "gelu_new"
            mlp_bias=True,
            parallel_residual=True,
            lm_head_bias=True,
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"transformer.h.{layer}."
        f = f"layers_{layer}/"
        return {
            p + "ln_1.weight": (f + "input_layernorm/scale", False),
            p + "ln_1.bias": (f + "input_layernorm/bias", False),
            p + "attn.q_proj.weight": (f + "self_attn/q_proj/kernel", True),
            p + "attn.k_proj.weight": (f + "self_attn/k_proj/kernel", True),
            p + "attn.v_proj.weight": (f + "self_attn/v_proj/kernel", True),
            p + "attn.out_proj.weight": (f + "self_attn/o_proj/kernel", True),
            p + "mlp.fc_in.weight": (f + "mlp/fc1/kernel", True),
            p + "mlp.fc_in.bias": (f + "mlp/fc1/bias", False),
            p + "mlp.fc_out.weight": (f + "mlp/fc2/kernel", True),
            p + "mlp.fc_out.bias": (f + "mlp/fc2/bias", False),
        }

    def global_map(self, tie_embeddings: bool):
        return {
            "transformer.wte.weight": ("embed_tokens/embedding", False),
            "transformer.ln_f.weight": ("norm/scale", False),
            "transformer.ln_f.bias": ("norm/bias", False),
            "lm_head.weight": ("lm_head/kernel", True),
            "lm_head.bias": ("lm_head/bias", False),
        }


class GPTNeoPolicy(HFCheckpointPolicy):
    """GPT-Neo (reference ``module_inject/containers/gptneo.py``): learned
    positions, alternating global/LOCAL (sliding-window) attention,
    UNSCALED attention logits (no 1/sqrt(d)), bias-free qkv with biased
    out_proj, gelu_new MLP, tied embeddings."""
    arch = "gptneo"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        h = hf_config["hidden_size"]
        # attention_types [[["global","local"], N]] -> per-layer pattern
        pattern = []
        for spec, count in hf_config.get("attention_types",
                                         [[["global"], hf_config["num_layers"]]]):
            pattern.extend(list(spec) * count)
        local_layers = tuple(i for i, t in enumerate(pattern) if t == "local")
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("intermediate_size") or 4 * h,
            num_hidden_layers=hf_config["num_layers"],
            num_attention_heads=hf_config["num_heads"],
            num_key_value_heads=hf_config["num_heads"],
            max_position_embeddings=hf_config.get("max_position_embeddings", 2048),
            rms_norm_eps=hf_config.get("layer_norm_epsilon", 1e-5),
            tie_word_embeddings=True,
            attention_out_bias=True,
            norm_type="layernorm",
            pos_embedding="learned",
            mlp_type="gelu_tanh_fc",
            mlp_bias=True,
            sliding_window=hf_config.get("window_size", 256) if local_layers else None,
            sliding_window_layers=local_layers or None,
            attn_scale=1.0,  # GPT-Neo does not scale attention logits
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"transformer.h.{layer}."
        f = f"layers_{layer}/"
        return {
            p + "ln_1.weight": (f + "input_layernorm/scale", False),
            p + "ln_1.bias": (f + "input_layernorm/bias", False),
            p + "ln_2.weight": (f + "post_attention_layernorm/scale", False),
            p + "ln_2.bias": (f + "post_attention_layernorm/bias", False),
            p + "attn.attention.q_proj.weight": (f + "self_attn/q_proj/kernel", True),
            p + "attn.attention.k_proj.weight": (f + "self_attn/k_proj/kernel", True),
            p + "attn.attention.v_proj.weight": (f + "self_attn/v_proj/kernel", True),
            p + "attn.attention.out_proj.weight": (f + "self_attn/o_proj/kernel", True),
            p + "attn.attention.out_proj.bias": (f + "self_attn/o_proj/bias", False),
            p + "mlp.c_fc.weight": (f + "mlp/fc1/kernel", True),
            p + "mlp.c_fc.bias": (f + "mlp/fc1/bias", False),
            p + "mlp.c_proj.weight": (f + "mlp/fc2/kernel", True),
            p + "mlp.c_proj.bias": (f + "mlp/fc2/bias", False),
        }

    def global_map(self, tie_embeddings: bool):
        return {
            "transformer.wte.weight": ("embed_tokens/embedding", False),
            "transformer.wpe.weight": ("embed_positions/embedding", False),
            "transformer.ln_f.weight": ("norm/scale", False),
            "transformer.ln_f.bias": ("norm/bias", False),
        }


class Starcoder2Policy(HFCheckpointPolicy):
    """StarCoder2: GQA + LayerNorm + biased gelu-tanh fc MLP + sliding
    window + tied embeddings (maps onto existing variant knobs)."""
    arch = "starcoder2"
    col_parallel = ["q_proj", "k_proj", "v_proj", "fc1"]
    row_parallel = ["o_proj", "fc2"]

    def config_from_hf(self, hf_config):
        bias = hf_config.get("use_bias", True)
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            num_key_value_heads=hf_config.get("num_key_value_heads",
                                              hf_config["num_attention_heads"]),
            max_position_embeddings=hf_config.get("max_position_embeddings", 4096),
            rms_norm_eps=hf_config.get("norm_epsilon", 1e-5),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            tie_word_embeddings=hf_config.get("tie_word_embeddings", True),
            attention_bias=bias,
            attention_out_bias=bias,
            norm_type="layernorm",
            mlp_type="gelu_tanh_fc",  # HF "gelu_pytorch_tanh"
            mlp_bias=bias,
            sliding_window=hf_config.get("sliding_window"),
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        out = {}
        for hf, fx in (("q_proj", "q_proj"), ("k_proj", "k_proj"),
                       ("v_proj", "v_proj"), ("o_proj", "o_proj")):
            out[p + f"self_attn.{hf}.weight"] = (f + f"self_attn/{fx}/kernel", True)
            if attention_bias:
                out[p + f"self_attn.{hf}.bias"] = (f + f"self_attn/{fx}/bias", False)
        if attention_bias:
            out[p + "mlp.c_fc.bias"] = (f + "mlp/fc1/bias", False)
            out[p + "mlp.c_proj.bias"] = (f + "mlp/fc2/bias", False)
        out.update({
            p + "mlp.c_fc.weight": (f + "mlp/fc1/kernel", True),
            p + "mlp.c_proj.weight": (f + "mlp/fc2/kernel", True),
            p + "input_layernorm.weight": (f + "input_layernorm/scale", False),
            p + "input_layernorm.bias": (f + "input_layernorm/bias", False),
            p + "post_attention_layernorm.weight": (f + "post_attention_layernorm/scale",
                                                    False),
            p + "post_attention_layernorm.bias": (f + "post_attention_layernorm/bias",
                                                  False),
        })
        return out

    def global_map(self, tie_embeddings: bool):
        out = {
            "model.embed_tokens.weight": ("embed_tokens/embedding", False),
            "model.norm.weight": ("norm/scale", False),
            "model.norm.bias": ("norm/bias", False),
        }
        if not tie_embeddings:
            out["lm_head.weight"] = ("lm_head/kernel", True)
        return out


class StableLmPolicy(HFCheckpointPolicy):
    """StableLM: llama graph with LayerNorm(+bias) norms, partial rotary,
    optional qkv biases, untied head."""
    arch = "stablelm"

    def config_from_hf(self, hf_config):
        if hf_config.get("use_parallel_residual"):
            raise ValueError("stablelm use_parallel_residual=True (NeoX form) "
                             "checkpoints are not supported by this policy")
        if hf_config.get("qk_layernorm"):
            raise ValueError("stablelm qk_layernorm=True is not supported")
        hd = hf_config["hidden_size"] // hf_config["num_attention_heads"]
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            num_key_value_heads=hf_config.get("num_key_value_heads",
                                              hf_config["num_attention_heads"]),
            max_position_embeddings=hf_config.get("max_position_embeddings", 4096),
            rms_norm_eps=hf_config.get("layer_norm_eps", 1e-5),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            rotary_dim=int(hf_config.get("partial_rotary_factor", 0.25) * hd),
            tie_word_embeddings=hf_config.get("tie_word_embeddings", False),
            attention_bias=hf_config.get("use_qkv_bias", False),
            norm_type="layernorm",
        )

    def weight_map(self, layer: int, attention_bias: bool = False):
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        out = {}
        for hf, fx in (("q_proj", "q_proj"), ("k_proj", "k_proj"),
                       ("v_proj", "v_proj"), ("o_proj", "o_proj")):
            out[p + f"self_attn.{hf}.weight"] = (f + f"self_attn/{fx}/kernel", True)
        if attention_bias:
            for proj in ("q_proj", "k_proj", "v_proj"):
                out[p + f"self_attn.{proj}.bias"] = (f + f"self_attn/{proj}/bias", False)
        out.update({
            p + "mlp.gate_proj.weight": (f + "mlp/gate_proj/kernel", True),
            p + "mlp.up_proj.weight": (f + "mlp/up_proj/kernel", True),
            p + "mlp.down_proj.weight": (f + "mlp/down_proj/kernel", True),
            p + "input_layernorm.weight": (f + "input_layernorm/scale", False),
            p + "input_layernorm.bias": (f + "input_layernorm/bias", False),
            p + "post_attention_layernorm.weight": (f + "post_attention_layernorm/scale",
                                                    False),
            p + "post_attention_layernorm.bias": (f + "post_attention_layernorm/bias",
                                                  False),
        })
        return out

    def global_map(self, tie_embeddings: bool):
        out = {
            "model.embed_tokens.weight": ("embed_tokens/embedding", False),
            "model.norm.weight": ("norm/scale", False),
            "model.norm.bias": ("norm/bias", False),
        }
        if not tie_embeddings:
            out["lm_head.weight"] = ("lm_head/kernel", True)
        return out


class BertPolicy:
    """BERT encoder (reference ``module_inject/containers/bert.py``
    HFBertLayerPolicy): post-LN bidirectional layers, MLM head tied to the
    word embeddings. Converts HF ``BertForMaskedLM`` into
    ``models/bert.py BertForMaskedLM`` (root-less param tree)."""
    arch = "bert"
    root = None  # flax tree has no "model" wrapper; paths carry "bert/"
    # tied-decoder duplicates + buffers the conversion legitimately skips
    ignored_suffixes = ("cls.predictions.decoder.weight",
                        "cls.predictions.decoder.bias",
                        "embeddings.position_ids",
                        "seq_relationship.weight", "seq_relationship.bias",
                        "pooler.dense.weight", "pooler.dense.bias")
    col_parallel = ["query", "key", "value", "intermediate"]
    row_parallel = ["output", "mlp_output"]

    def config_from_hf(self, hf_config):
        from ..models.bert import BertConfig
        return BertConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            max_position_embeddings=hf_config.get("max_position_embeddings", 512),
            type_vocab_size=hf_config.get("type_vocab_size", 2),
            layer_norm_eps=hf_config.get("layer_norm_eps", 1e-12),
        )

    def weight_map(self, layer: int, attention_bias: bool = True):
        p = f"bert.encoder.layer.{layer}."
        f = f"bert/layer_{layer}/"
        out = {}
        for hf, fx in (("attention.self.query", "attention/query"),
                       ("attention.self.key", "attention/key"),
                       ("attention.self.value", "attention/value"),
                       ("attention.output.dense", "attention/output"),
                       ("intermediate.dense", "intermediate"),
                       ("output.dense", "mlp_output")):
            out[p + hf + ".weight"] = (f + fx + "/kernel", True)
            out[p + hf + ".bias"] = (f + fx + "/bias", False)
        for hf, fx in (("attention.output.LayerNorm", "attention_layernorm"),
                       ("output.LayerNorm", "output_layernorm")):
            out[p + hf + ".weight"] = (f + fx + "/scale", False)
            out[p + hf + ".bias"] = (f + fx + "/bias", False)
        return out

    def global_map(self, tie_embeddings: bool):
        return {
            "bert.embeddings.word_embeddings.weight": ("bert/word_embeddings/embedding",
                                                       False),
            "bert.embeddings.position_embeddings.weight":
                ("bert/position_embeddings/embedding", False),
            "bert.embeddings.token_type_embeddings.weight":
                ("bert/token_type_embeddings/embedding", False),
            "bert.embeddings.LayerNorm.weight": ("bert/embeddings_layernorm/scale", False),
            "bert.embeddings.LayerNorm.bias": ("bert/embeddings_layernorm/bias", False),
            "cls.predictions.transform.dense.weight": ("transform/kernel", True),
            "cls.predictions.transform.dense.bias": ("transform/bias", False),
            "cls.predictions.transform.LayerNorm.weight": ("transform_layernorm/scale",
                                                           False),
            "cls.predictions.transform.LayerNorm.bias": ("transform_layernorm/bias",
                                                         False),
            "cls.predictions.bias": ("decoder_bias", False),
        }


class DistilBertPolicy(BertPolicy):
    """DistilBERT (reference ``module_inject/containers/distil_bert.py``):
    the BERT graph minus token-type embeddings, different HF naming."""
    arch = "distilbert"
    ignored_suffixes = ("vocab_projector.weight", "embeddings.position_ids")

    def config_from_hf(self, hf_config):
        from ..models.bert import BertConfig
        return BertConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["dim"],
            intermediate_size=hf_config["hidden_dim"],
            num_hidden_layers=hf_config["n_layers"],
            num_attention_heads=hf_config["n_heads"],
            max_position_embeddings=hf_config.get("max_position_embeddings", 512),
            layer_norm_eps=1e-12,
            distilbert=True,
        )

    def weight_map(self, layer: int, attention_bias: bool = True):
        p = f"distilbert.transformer.layer.{layer}."
        f = f"bert/layer_{layer}/"
        out = {}
        for hf, fx in (("attention.q_lin", "attention/query"),
                       ("attention.k_lin", "attention/key"),
                       ("attention.v_lin", "attention/value"),
                       ("attention.out_lin", "attention/output"),
                       ("ffn.lin1", "intermediate"),
                       ("ffn.lin2", "mlp_output")):
            out[p + hf + ".weight"] = (f + fx + "/kernel", True)
            out[p + hf + ".bias"] = (f + fx + "/bias", False)
        for hf, fx in (("sa_layer_norm", "attention_layernorm"),
                       ("output_layer_norm", "output_layernorm")):
            out[p + hf + ".weight"] = (f + fx + "/scale", False)
            out[p + hf + ".bias"] = (f + fx + "/bias", False)
        return out

    def global_map(self, tie_embeddings: bool):
        return {
            "distilbert.embeddings.word_embeddings.weight":
                ("bert/word_embeddings/embedding", False),
            "distilbert.embeddings.position_embeddings.weight":
                ("bert/position_embeddings/embedding", False),
            "distilbert.embeddings.LayerNorm.weight": ("bert/embeddings_layernorm/scale",
                                                       False),
            "distilbert.embeddings.LayerNorm.bias": ("bert/embeddings_layernorm/bias",
                                                     False),
            "vocab_transform.weight": ("transform/kernel", True),
            "vocab_transform.bias": ("transform/bias", False),
            "vocab_layer_norm.weight": ("transform_layernorm/scale", False),
            "vocab_layer_norm.bias": ("transform_layernorm/bias", False),
            "vocab_projector.bias": ("decoder_bias", False),
        }


_POLICIES = {
    "llama": LlamaPolicy,
    "LlamaForCausalLM": LlamaPolicy,
    "mistral": MistralPolicy,
    "MistralForCausalLM": MistralPolicy,
    "qwen2": Qwen2Policy,
    "Qwen2ForCausalLM": Qwen2Policy,
    "mixtral": MixtralPolicy,
    "MixtralForCausalLM": MixtralPolicy,
    "qwen2_moe": Qwen2MoePolicy,
    "qwen2moe": Qwen2MoePolicy,
    "Qwen2MoeForCausalLM": Qwen2MoePolicy,
    "gemma": GemmaPolicy,
    "GemmaForCausalLM": GemmaPolicy,
    "gemma2": Gemma2Policy,
    "Gemma2ForCausalLM": Gemma2Policy,
    "opt": OPTPolicy,
    "OPTForCausalLM": OPTPolicy,
    "phi": PhiPolicy,
    "PhiForCausalLM": PhiPolicy,
    "falcon": FalconPolicy,
    "FalconForCausalLM": FalconPolicy,
    "gpt2": GPT2Policy,
    "GPT2LMHeadModel": GPT2Policy,
    "gptneox": GPTNeoXPolicy,
    "gpt_neox": GPTNeoXPolicy,
    "GPTNeoXForCausalLM": GPTNeoXPolicy,
    "internlm": InternLMPolicy,
    "InternLMForCausalLM": InternLMPolicy,
    "phi3": Phi3Policy,
    "Phi3ForCausalLM": Phi3Policy,
    "baichuan": BaichuanPolicy,
    "BaichuanForCausalLM": BaichuanPolicy,
    "bloom": BloomPolicy,
    "BloomForCausalLM": BloomPolicy,
    "bert": BertPolicy,
    "BertForMaskedLM": BertPolicy,
    "distilbert": DistilBertPolicy,
    "DistilBertForMaskedLM": DistilBertPolicy,
    "gptj": GPTJPolicy,
    "GPTJForCausalLM": GPTJPolicy,
    "gptneo": GPTNeoPolicy,
    "gpt_neo": GPTNeoPolicy,
    "GPTNeoForCausalLM": GPTNeoPolicy,
    "starcoder2": Starcoder2Policy,
    "Starcoder2ForCausalLM": Starcoder2Policy,
    "stablelm": StableLmPolicy,
    "StableLmForCausalLM": StableLmPolicy,
    "olmo": OlmoPolicy,
    "OlmoForCausalLM": OlmoPolicy,
    "olmo2": Olmo2Policy,
    "Olmo2ForCausalLM": Olmo2Policy,
    "cohere": CoherePolicy,
    "CohereForCausalLM": CoherePolicy,
}

SUPPORTED_ARCHS = sorted({p.arch for p in _POLICIES.values()})


def policy_for(arch_or_model_type: str) -> HFCheckpointPolicy:
    """Reference replace_policy.py generic_policies lookup."""
    pol = _POLICIES.get(arch_or_model_type)
    if pol is None:
        raise ValueError(f"no injection policy for '{arch_or_model_type}'; "
                         f"supported: {SUPPORTED_ARCHS}")
    return pol()
