"""Per-architecture injection policies.

Reference: ``deepspeed/module_inject/replace_policy.py`` +
``containers/*`` (~20 archs): each policy knows an architecture's module
layout — which weights feed attention/MLP, which are column- vs row-parallel
— and maps HF modules onto the fused inference containers.

TPU equivalent: the "container" is the native flax Llama-family model
(``models/llama.py``) plus its paged-KV serving twin
(``inference/v2/model.py``); a policy here is (a) the HF→flax parameter name
map with layout fixups (torch Linear stores [out,in]; flax kernels are
[in,out]) and (b) the TP partition hints AutoTP consumes
(``parallel/tp.py``).
"""

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.llama import LlamaConfig


class HFCheckpointPolicy:
    """Base policy: llama-family weight map (LLaMA 2/3, Mistral, Qwen2 share
    the module graph; reference containers/llama.py, mistral, qwen2)."""

    arch: str = "llama"
    supports_bias: bool = False

    # AutoTP hints (reference policy.py container attrs)
    col_parallel = ["q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"]
    row_parallel = ["o_proj", "down_proj"]

    def config_from_hf(self, hf_config: Dict) -> LlamaConfig:
        """Map an HF config dict to LlamaConfig."""
        return LlamaConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_hidden_layers=hf_config["num_hidden_layers"],
            num_attention_heads=hf_config["num_attention_heads"],
            num_key_value_heads=hf_config.get("num_key_value_heads",
                                              hf_config["num_attention_heads"]),
            max_position_embeddings=hf_config.get("max_position_embeddings", 8192),
            rms_norm_eps=hf_config.get("rms_norm_eps", 1e-5),
            rope_theta=hf_config.get("rope_theta", 10000.0),
            tie_word_embeddings=hf_config.get("tie_word_embeddings", False),
        )

    def weight_map(self, layer: int, attention_bias: bool = False
                   ) -> Dict[str, Tuple[str, bool]]:
        """HF name -> (flax path under params['model'], transpose?)."""
        p = f"model.layers.{layer}."
        f = f"layers_{layer}/"
        out = {}
        if attention_bias:  # qwen2-style qkv biases (1-D: no transpose)
            for proj in ("q_proj", "k_proj", "v_proj"):
                out[p + f"self_attn.{proj}.bias"] = (f + f"self_attn/{proj}/bias", False)
        out.update({
            p + "self_attn.q_proj.weight": (f + "self_attn/q_proj/kernel", True),
            p + "self_attn.k_proj.weight": (f + "self_attn/k_proj/kernel", True),
            p + "self_attn.v_proj.weight": (f + "self_attn/v_proj/kernel", True),
            p + "self_attn.o_proj.weight": (f + "self_attn/o_proj/kernel", True),
            p + "mlp.gate_proj.weight": (f + "mlp/gate_proj/kernel", True),
            p + "mlp.up_proj.weight": (f + "mlp/up_proj/kernel", True),
            p + "mlp.down_proj.weight": (f + "mlp/down_proj/kernel", True),
            p + "input_layernorm.weight": (f + "input_layernorm/weight", False),
            p + "post_attention_layernorm.weight": (f + "post_attention_layernorm/weight",
                                                    False),
        })
        return out

    def global_map(self, tie_embeddings: bool) -> Dict[str, Tuple[str, bool]]:
        out = {
            "model.embed_tokens.weight": ("embed_tokens/embedding", False),
            "model.norm.weight": ("norm/weight", False),
        }
        if not tie_embeddings:
            out["lm_head.weight"] = ("lm_head/kernel", True)
        return out


class LlamaPolicy(HFCheckpointPolicy):
    arch = "llama"


class MistralPolicy(HFCheckpointPolicy):
    """Mistral: llama graph w/ sliding-window attn config (served dense here;
    reference containers/mistral)."""
    arch = "mistral"

    def config_from_hf(self, hf_config):
        cfg = super().config_from_hf(hf_config)
        return cfg  # sliding_window handled at attention level when present


class Qwen2Policy(HFCheckpointPolicy):
    """Qwen2 adds attention qkv biases (reference containers/qwen2)."""
    arch = "qwen2"
    supports_bias = True

    def config_from_hf(self, hf_config):
        cfg = super().config_from_hf(hf_config)
        import dataclasses
        return dataclasses.replace(cfg, attention_bias=True)


class MixtralPolicy(HFCheckpointPolicy):
    """Mixtral: llama attention + sparse-MoE MLP (reference
    inference/v2/model_implementations/mixtral). Per-expert HF tensors are
    stacked into [E, ...] arrays — the layout the grouped einsum consumes."""
    arch = "mixtral"

    def config_from_hf(self, hf_config):
        cfg = super().config_from_hf(hf_config)
        import dataclasses
        return dataclasses.replace(
            cfg, num_local_experts=hf_config.get("num_local_experts", 8),
            num_experts_per_tok=hf_config.get("num_experts_per_tok", 2))

    def weight_map(self, layer: int, attention_bias: bool = False):
        out = super().weight_map(layer, attention_bias)
        # mixtral has no dense mlp — drop those entries
        return {k: v for k, v in out.items() if ".mlp." not in k}

    def moe_map(self, layer: int, num_experts: int):
        """HF names → (flax path, stacking) for the MoE block."""
        p = f"model.layers.{layer}.block_sparse_moe."
        f = f"layers_{layer}/block_sparse_moe/"
        gate = {p + "gate.weight": (f + "gate/kernel", True)}
        experts = {}
        for which, tr in (("w1", True), ("w2", True), ("w3", True)):
            experts[f + which] = [p + f"experts.{e}.{which}.weight" for e in range(num_experts)]
        return gate, experts


class Gemma2Policy(HFCheckpointPolicy):
    """Gemma-2: llama-family graph with tied embeddings by default."""
    arch = "gemma2"

    def config_from_hf(self, hf_config):
        cfg = super().config_from_hf(hf_config)
        import dataclasses
        return dataclasses.replace(cfg, tie_word_embeddings=True)


_POLICIES = {
    "llama": LlamaPolicy,
    "LlamaForCausalLM": LlamaPolicy,
    "mistral": MistralPolicy,
    "MistralForCausalLM": MistralPolicy,
    "qwen2": Qwen2Policy,
    "Qwen2ForCausalLM": Qwen2Policy,
    "mixtral": MixtralPolicy,
    "MixtralForCausalLM": MixtralPolicy,
    "gemma2": Gemma2Policy,
    "Gemma2ForCausalLM": Gemma2Policy,
}

SUPPORTED_ARCHS = sorted({p.arch for p in _POLICIES.values()})


def policy_for(arch_or_model_type: str) -> HFCheckpointPolicy:
    """Reference replace_policy.py generic_policies lookup."""
    pol = _POLICIES.get(arch_or_model_type)
    if pol is None:
        raise ValueError(f"no injection policy for '{arch_or_model_type}'; "
                         f"supported: {SUPPORTED_ARCHS}")
    return pol()
