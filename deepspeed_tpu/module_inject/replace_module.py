"""HF checkpoint conversion + injection entry points.

Reference: ``module_inject/replace_module.py:183 replace_transformer_layer``
— walks an HF torch model replacing decoder layers with fused containers and
sharding weights. TPU equivalent: *convert once* into the native flax param
tree (the fused "container" is the whole jitted model), then serve through
``init_inference`` (TP via AutoTP shardings) or the v2 ragged engine.
"""

from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..models.llama import LlamaConfig
from ..utils.logging import logger
from .replace_policy import HFCheckpointPolicy, policy_for


def _nest(flat: Dict[str, np.ndarray]) -> Dict:
    """'a/b/c': x  →  {'a': {'b': {'c': x}}}"""
    out: Dict = {}
    for path, v in flat.items():
        node = out
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _to_numpy(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor without importing torch
        x = x.detach().cpu().float().numpy()
    return np.asarray(x)


def convert_hf_checkpoint(arch: str,
                          hf_state_dict: Dict[str, Any],
                          hf_config: Dict,
                          dtype=jnp.bfloat16) -> Tuple[LlamaConfig, Dict]:
    """HF state dict (torch tensors or arrays) → (LlamaConfig, flax params
    compatible with models/llama.py + inference/v2)."""
    policy = policy_for(arch)
    cfg = policy.config_from_hf(hf_config)
    flat: Dict[str, np.ndarray] = {}
    consumed = set()

    def take(hf_name: str, flax_path: str, transpose: bool):
        if hf_name not in hf_state_dict:
            raise KeyError(f"HF checkpoint missing '{hf_name}' (arch={arch})")
        w = _to_numpy(hf_state_dict[hf_name])
        if transpose:
            w = w.T  # torch Linear [out,in] → flax kernel [in,out]
        flat[flax_path] = w.astype(np.float32)
        consumed.add(hf_name)

    for hf_name, (flax_path, tr) in policy.global_map(cfg.tie_word_embeddings).items():
        take(hf_name, flax_path, tr)
    for layer in range(cfg.num_hidden_layers):
        for hf_name, (flax_path, tr) in policy.weight_map(
                layer, attention_bias=cfg.attention_bias).items():
            take(hf_name, flax_path, tr)
        if hasattr(policy, "moe_map") and cfg.num_local_experts > 0:
            gate, experts = policy.moe_map(layer, cfg.num_local_experts)
            for hf_name, (flax_path, tr) in gate.items():
                take(hf_name, flax_path, tr)
            for flax_path, hf_names in experts.items():
                stacked = np.stack([_to_numpy(hf_state_dict[n]).T for n in hf_names])
                flat[flax_path] = stacked.astype(np.float32)  # [E, in, out]
                consumed.update(hf_names)
        if hasattr(policy, "convert_special"):
            # fused tensors the plain name map can't express (falcon MQA qkv)
            def get_tensor(name):
                consumed.add(name)
                return _to_numpy(hf_state_dict[name])

            def put(path, arr):
                flat[path] = np.asarray(arr, np.float32)

            policy.convert_special(layer, cfg, get_tensor, put)

    ignored = tuple(getattr(policy, "ignored_suffixes", ())) + ("rotary_emb.inv_freq", )
    leftovers = [k for k in hf_state_dict if k not in consumed
                 and not k.endswith(ignored)]
    if leftovers:
        logger.warning(f"unconverted HF tensors: {leftovers[:8]}"
                       f"{'...' if len(leftovers) > 8 else ''}")

    root = getattr(policy, "root", "model")
    params = {root: _nest(flat)} if root else _nest(flat)
    return cfg, params


def export_hf_checkpoint(arch: str, config: LlamaConfig, params: Dict) -> Dict[str, np.ndarray]:
    """Inverse conversion: flax params → HF-layout state dict (numpy)."""
    policy = policy_for(arch)
    flat = {}

    def walk(node, prefix=""):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}{k}/")
        else:
            flat[prefix[:-1]] = np.asarray(node, dtype=np.float32)

    root = getattr(policy, "root", "model")
    walk(params.get(root, params) if root else params)
    out = {}
    maps = dict(policy.global_map(config.tie_word_embeddings))
    for layer in range(config.num_hidden_layers):
        maps.update(policy.weight_map(layer, attention_bias=config.attention_bias))
        if hasattr(policy, "export_special"):
            out.update(policy.export_special(layer, config, flat))
        if hasattr(policy, "moe_map") and config.num_local_experts > 0:
            gate, experts = policy.moe_map(layer, config.num_local_experts)
            maps.update(gate)
            for flax_path, hf_names in experts.items():
                stacked = flat[flax_path]  # [E, in, out]
                for e, hf_name in enumerate(hf_names):
                    out[hf_name] = stacked[e].T
    for hf_name, (flax_path, transpose) in maps.items():
        w = flat[flax_path]
        out[hf_name] = w.T if transpose else w
    return out


def convert_hf_safetensors(arch: str,
                           model_dir: str,
                           hf_config: Optional[Dict] = None,
                           dtype=jnp.bfloat16) -> Tuple[LlamaConfig, Dict]:
    """Streaming conversion from a safetensors checkpoint directory.

    Tensors are read ONE AT A TIME from each ``*.safetensors`` shard and cast
    to the target dtype immediately, so peak host RAM ≈ the converted tree
    (in `dtype`) + one tensor. The whole-dict path (:func:`convert_hf_checkpoint`)
    holds source fp32 AND converted fp32 simultaneously — a 70B model cannot
    do that on a host. Fused tensors a policy converts via
    ``convert_special`` (falcon qkv) and stacked MoE experts are buffered
    only until their conversion completes.
    """
    import glob
    import json
    import os
    from safetensors import safe_open

    if hf_config is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            hf_config = json.load(f)
    policy = policy_for(arch)
    cfg = policy.config_from_hf(hf_config)
    np_dtype = jnp.dtype(dtype)

    mapping: Dict[str, Tuple[str, bool]] = dict(policy.global_map(cfg.tie_word_embeddings))
    stack_map: Dict[str, Tuple[str, int]] = {}   # hf expert tensor -> (path, e)
    stack_shapes: Dict[str, int] = {}
    for layer in range(cfg.num_hidden_layers):
        mapping.update(policy.weight_map(layer, attention_bias=cfg.attention_bias))
        if hasattr(policy, "moe_map") and cfg.num_local_experts > 0:
            gate, experts = policy.moe_map(layer, cfg.num_local_experts)
            mapping.update(gate)
            for flax_path, hf_names in experts.items():
                stack_shapes[flax_path] = len(hf_names)
                for e, n in enumerate(hf_names):
                    stack_map[n] = (flax_path, e)

    special_names = set()
    if hasattr(policy, "special_hf_names"):
        for layer in range(cfg.num_hidden_layers):
            special_names.update(policy.special_hf_names(layer))

    flat: Dict[str, np.ndarray] = {}
    extras: Dict[str, np.ndarray] = {}  # declared convert_special inputs only
    stack_filled: Dict[str, set] = {}
    skipped = []
    shards = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not shards:
        raise FileNotFoundError(f"no *.safetensors under {model_dir}")
    for shard in shards:
        with safe_open(shard, framework="numpy") as f:
            for name in f.keys():
                if name in mapping:
                    path, tr = mapping[name]
                    w = f.get_tensor(name)
                    flat[path] = (w.T if tr else w).astype(np_dtype)
                elif name in stack_map:
                    path, e = stack_map[name]
                    w = f.get_tensor(name).T
                    if path not in flat:
                        flat[path] = np.empty((stack_shapes[path], *w.shape), np_dtype)
                    flat[path][e] = w.astype(np_dtype)
                    stack_filled.setdefault(path, set()).add(e)
                elif name in special_names:
                    extras[name] = f.get_tensor(name)
                elif not name.endswith("rotary_emb.inv_freq"):
                    skipped.append(name)
    if skipped:
        logger.warning(f"unconverted checkpoint tensors: {skipped[:8]}"
                       f"{'...' if len(skipped) > 8 else ''}")
    if hasattr(policy, "convert_special"):
        for layer in range(cfg.num_hidden_layers):
            def get_tensor(name):
                return extras.pop(name)  # freed as consumed

            def put(path, arr):
                flat[path] = np.asarray(arr).astype(np_dtype)

            policy.convert_special(layer, cfg, get_tensor, put)
    missing = [v[0] for k, v in mapping.items() if v[0] not in flat]
    # np.empty preallocation makes a partially-filled expert stack look
    # present — verify every expert slot was actually written
    for path, n in stack_shapes.items():
        if path not in flat:
            missing.append(path)
        elif len(stack_filled.get(path, ())) != n:
            missing.append(f"{path} (only {len(stack_filled.get(path, ()))}/{n} "
                           f"experts present)")
    if missing:
        raise KeyError(f"checkpoint under {model_dir} is missing tensors for: "
                       f"{missing[:6]}{'...' if len(missing) > 6 else ''}")
    root = getattr(policy, "root", "model")
    return cfg, ({root: _nest(flat)} if root else _nest(flat))


def replace_transformer_layer(arch_or_model_type: str,
                              hf_state_dict: Dict[str, Any],
                              hf_config: Dict,
                              tp_size: int = 1,
                              dtype=jnp.bfloat16):
    """Reference entry name kept (replace_module.py:183): converts the HF
    checkpoint and returns a TP-sharded v1 InferenceEngine over it."""
    import deepspeed_tpu
    cfg, params = convert_hf_checkpoint(arch_or_model_type, hf_state_dict, hf_config,
                                        dtype=dtype)
    from ..models.llama import LlamaForCausalLM
    model = LlamaForCausalLM(cfg)
    return deepspeed_tpu.init_inference(
        model, config={"dtype": "bfloat16" if dtype == jnp.bfloat16 else "float32",
                       "tensor_parallel": {"tp_size": tp_size}},
        params=params)


def merge_peft_adapter(arch: str,
                       config: LlamaConfig,
                       params: Dict,
                       adapter_dir: Optional[str] = None,
                       adapter_state: Optional[Dict[str, Any]] = None,
                       adapter_config: Optional[Dict] = None) -> Dict:
    """Merge a PEFT LoRA adapter into converted flax params, in place.

    The serving-side counterpart of ``linear/optimized_linear.py``'s LoRA
    training (reference deploys adapters by merging before inference):
    every ``...<module>.lora_A.weight`` / ``lora_B.weight`` pair becomes
    ``W += (B @ A) * scaling`` on the matching base weight, located through
    the same policy name maps the checkpoint conversion used — so any
    supported arch accepts adapters with zero per-arch code.

    ``scaling`` follows PEFT: ``lora_alpha / r`` (``lora_alpha / sqrt(r)``
    when ``use_rslora``). Pass either ``adapter_dir`` (reads
    ``adapter_config.json`` + ``adapter_model.safetensors``) or
    ``adapter_state`` (+ ``adapter_config``).
    """
    if adapter_dir is not None:
        import json
        import os
        with open(os.path.join(adapter_dir, "adapter_config.json")) as f:
            adapter_config = json.load(f)
        from safetensors import safe_open
        adapter_state = {}
        with safe_open(os.path.join(adapter_dir, "adapter_model.safetensors"),
                       framework="numpy") as f:
            for k in f.keys():
                adapter_state[k] = f.get_tensor(k)
    if adapter_state is None:
        raise ValueError("pass adapter_dir or adapter_state")
    adapter_config = adapter_config or {}
    r = int(adapter_config.get("r", 8))
    alpha = float(adapter_config.get("lora_alpha", r))
    alpha_pattern = adapter_config.get("alpha_pattern") or {}
    if adapter_config.get("fan_in_fan_out"):
        raise ValueError("fan_in_fan_out adapters are not supported")
    if adapter_config.get("use_dora"):
        raise ValueError("DoRA adapters (use_dora) need magnitude "
                         "renormalization; plain merge would be silently "
                         "wrong — merge with PEFT first")

    def _scaling(module: str, r_m: int) -> float:
        # per-module alpha — PEFT's own pattern rule (get_pattern_key):
        # keys are names OR regexes matched as (^|.*\.)key$ ; per-module
        # rank comes from the tensor itself (rank_pattern-safe)
        import re
        a = alpha
        for key, val in alpha_pattern.items():
            if re.match(rf"(^|.*\.){key}$", module):
                a = float(val)
                break
        return a / (r_m ** 0.5 if adapter_config.get("use_rslora") else r_m)

    policy = policy_for(arch)
    name_map: Dict[str, Tuple[str, bool]] = dict(
        policy.global_map(config.tie_word_embeddings))
    for layer in range(config.num_hidden_layers):
        name_map.update(policy.weight_map(layer,
                                          attention_bias=config.attention_bias))

    # pair up PEFT names: base_model.model.<module>.lora_A[.default].weight
    pairs: Dict[str, Dict[str, np.ndarray]] = {}
    unmatched = []
    for name, w in adapter_state.items():
        for part in ("lora_A", "lora_B"):
            tag = f".{part}."
            if tag in name:
                module = name.split(tag)[0]
                for prefix in ("base_model.model.", "base_model.", ""):
                    if module.startswith(prefix):
                        module = module[len(prefix):]
                        break
                pairs.setdefault(module, {})[part] = _to_numpy(w)
                break
        else:
            unmatched.append(name)
    if unmatched:
        # lora_embedding_A/B, trained biases (bias='lora_only'/'all'),
        # modules_to_save full weights, DoRA magnitudes — dropping any of
        # these would serve silently-wrong logits
        raise ValueError(
            "adapter contains tensors a plain lora_A/lora_B merge cannot "
            f"represent: {unmatched[:6]}{'...' if len(unmatched) > 6 else ''}")

    root = getattr(policy, "root", "model")
    tree = params[root] if root else params
    merged = []
    for module, ab in sorted(pairs.items()):
        if set(ab) != {"lora_A", "lora_B"}:
            raise ValueError(f"adapter module '{module}' missing "
                             f"lora_{'B' if 'lora_A' in ab else 'A'}")
        hf_name = module + ".weight"
        if hf_name not in name_map:
            raise ValueError(
                f"adapter targets '{module}', which has no plain weight "
                f"mapping for arch={arch} (fused/special tensors can't "
                "take merged adapters)")
        flax_path, transpose = name_map[hf_name]
        r_m = ab["lora_A"].shape[0]  # tensor-derived rank (rank_pattern)
        delta = (ab["lora_B"].astype(np.float32)
                 @ ab["lora_A"].astype(np.float32)) * _scaling(module, r_m)
        if transpose:
            delta = delta.T  # flax kernel orientation [in, out]
        node = tree
        parts = flax_path.split("/")
        for p in parts[:-1]:
            node = node[p]
        leaf = node[parts[-1]]
        if tuple(delta.shape) != tuple(leaf.shape):
            raise ValueError(f"adapter delta {delta.shape} != base "
                             f"{tuple(leaf.shape)} for '{module}'")
        node[parts[-1]] = (np.asarray(leaf, np.float32) + delta).astype(
            np.asarray(leaf).dtype)
        merged.append(module)
    if not merged:
        raise ValueError("no lora_A/lora_B tensors found in the adapter")
    logger.info(f"merged LoRA adapter into {len(merged)} modules "
                f"(r={r}, alpha={alpha})")
    return params
