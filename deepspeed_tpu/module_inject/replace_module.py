"""HF checkpoint conversion + injection entry points.

Reference: ``module_inject/replace_module.py:183 replace_transformer_layer``
— walks an HF torch model replacing decoder layers with fused containers and
sharding weights. TPU equivalent: *convert once* into the native flax param
tree (the fused "container" is the whole jitted model), then serve through
``init_inference`` (TP via AutoTP shardings) or the v2 ragged engine.
"""

from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..models.llama import LlamaConfig
from ..utils.logging import logger
from .replace_policy import HFCheckpointPolicy, policy_for


def _nest(flat: Dict[str, np.ndarray]) -> Dict:
    """'a/b/c': x  →  {'a': {'b': {'c': x}}}"""
    out: Dict = {}
    for path, v in flat.items():
        node = out
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _to_numpy(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor without importing torch
        x = x.detach().cpu().float().numpy()
    return np.asarray(x)


def convert_hf_checkpoint(arch: str,
                          hf_state_dict: Dict[str, Any],
                          hf_config: Dict,
                          dtype=jnp.bfloat16) -> Tuple[LlamaConfig, Dict]:
    """HF state dict (torch tensors or arrays) → (LlamaConfig, flax params
    compatible with models/llama.py + inference/v2)."""
    policy = policy_for(arch)
    cfg = policy.config_from_hf(hf_config)
    flat: Dict[str, np.ndarray] = {}
    consumed = set()

    def take(hf_name: str, flax_path: str, transpose: bool):
        if hf_name not in hf_state_dict:
            raise KeyError(f"HF checkpoint missing '{hf_name}' (arch={arch})")
        w = _to_numpy(hf_state_dict[hf_name])
        if transpose:
            w = w.T  # torch Linear [out,in] → flax kernel [in,out]
        flat[flax_path] = w.astype(np.float32)
        consumed.add(hf_name)

    for hf_name, (flax_path, tr) in policy.global_map(cfg.tie_word_embeddings).items():
        take(hf_name, flax_path, tr)
    for layer in range(cfg.num_hidden_layers):
        for hf_name, (flax_path, tr) in policy.weight_map(
                layer, attention_bias=cfg.attention_bias).items():
            take(hf_name, flax_path, tr)
        if hasattr(policy, "moe_map") and cfg.num_local_experts > 0:
            gate, experts = policy.moe_map(layer, cfg.num_local_experts)
            for hf_name, (flax_path, tr) in gate.items():
                take(hf_name, flax_path, tr)
            for flax_path, hf_names in experts.items():
                stacked = np.stack([_to_numpy(hf_state_dict[n]).T for n in hf_names])
                flat[flax_path] = stacked.astype(np.float32)  # [E, in, out]
                consumed.update(hf_names)

    leftovers = [k for k in hf_state_dict if k not in consumed
                 and not k.endswith("rotary_emb.inv_freq")]
    if leftovers:
        logger.warning(f"unconverted HF tensors: {leftovers[:8]}"
                       f"{'...' if len(leftovers) > 8 else ''}")

    params = {"model": _nest(flat)}
    return cfg, params


def export_hf_checkpoint(arch: str, config: LlamaConfig, params: Dict) -> Dict[str, np.ndarray]:
    """Inverse conversion: flax params → HF-layout state dict (numpy)."""
    policy = policy_for(arch)
    flat = {}

    def walk(node, prefix=""):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}{k}/")
        else:
            flat[prefix[:-1]] = np.asarray(node, dtype=np.float32)

    walk(params.get("model", params))
    out = {}
    maps = dict(policy.global_map(config.tie_word_embeddings))
    for layer in range(config.num_hidden_layers):
        maps.update(policy.weight_map(layer, attention_bias=config.attention_bias))
        if hasattr(policy, "moe_map") and config.num_local_experts > 0:
            gate, experts = policy.moe_map(layer, config.num_local_experts)
            maps.update(gate)
            for flax_path, hf_names in experts.items():
                stacked = flat[flax_path]  # [E, in, out]
                for e, hf_name in enumerate(hf_names):
                    out[hf_name] = stacked[e].T
    for hf_name, (flax_path, transpose) in maps.items():
        w = flat[flax_path]
        out[hf_name] = w.T if transpose else w
    return out


def replace_transformer_layer(arch_or_model_type: str,
                              hf_state_dict: Dict[str, Any],
                              hf_config: Dict,
                              tp_size: int = 1,
                              dtype=jnp.bfloat16):
    """Reference entry name kept (replace_module.py:183): converts the HF
    checkpoint and returns a TP-sharded v1 InferenceEngine over it."""
    import deepspeed_tpu
    cfg, params = convert_hf_checkpoint(arch_or_model_type, hf_state_dict, hf_config,
                                        dtype=dtype)
    from ..models.llama import LlamaForCausalLM
    model = LlamaForCausalLM(cfg)
    return deepspeed_tpu.init_inference(
        model, config={"dtype": "bfloat16" if dtype == jnp.bfloat16 else "float32",
                       "tensor_parallel": {"tp_size": tp_size}},
        params=params)
