"""deepspeed_tpu — a TPU-native training & inference framework with the
capabilities of DeepSpeed, built on JAX/XLA/pjit/Pallas.

Public API mirrors the reference (``deepspeed/__init__.py``):
  initialize()      — build a training engine from a model + JSON config
  init_inference()  — build an inference engine
  comm              — functional collectives over the device mesh
"""

from .version import __version__
from . import comm
from . import zero
from . import moe
from . import ops
from .config import DeepSpeedTpuConfig
from .runtime import pipe
from .comm.comm import init_distributed

__git_hash__ = None
__git_branch__ = None


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port=29500,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               mesh_param=None,
               config_params=None,
               **kwargs):
    """Build a :class:`deepspeed_tpu.runtime.engine.DeepSpeedTpuEngine`.

    Reference: ``deepspeed/__init__.py:69``. `model` is a flax module (or
    (init_fn, apply_fn) pair); returns (engine, optimizer, dataloader,
    lr_scheduler) like the reference.
    """
    from .runtime.engine import DeepSpeedTpuEngine

    config = config if config is not None else config_params
    if args is not None and config is None:
        config = getattr(args, "deepspeed_config", None)

    # Normalize the config (dict | json path | DeepSpeedTpuConfig) before ANY
    # engine-selection gate so every spelling routes the same way; JSON nulls
    # stay inert.
    from .config import DeepSpeedTpuConfig as _Cfg
    if isinstance(config, str):
        import json as _json
        with open(config) as _f:
            config = _json.load(_f)
    _pd = config._param_dict if isinstance(config, _Cfg) else (
        config if isinstance(config, dict) else {})

    # RLHF hybrid engine (reference __init__.py: DeepSpeedHybridEngine when
    # config.hybrid_engine.enabled)
    if (_pd.get("hybrid_engine") or {}).get("enabled"):
        from .runtime.hybrid_engine import DeepSpeedHybridEngine as DeepSpeedTpuEngine  # noqa: F811

    # ZeRO-3 parameter offload (ZeRO-Infinity): the streaming layer-list
    # executor (reference stage3.py:614 _configure_tensor_swapping path)
    _op = ((_pd.get("zero_optimization") or {}).get("offload_param") or {})
    if str(_op.get("device", "none")) != "none":
        from .runtime.zero_infinity import ZeroInfinityEngine
        if not isinstance(model, (list, tuple)):
            raise ValueError(
                "zero_optimization.offload_param requires the model as a layer "
                "list (the PipelineModule/LayerSpec contract): params stream "
                "host->HBM per layer, which needs explicit layer boundaries")
        if "loss_fn" not in kwargs:
            raise ValueError("offload_param training requires loss_fn=... "
                             "(maps the last layer's output + batch tail to a scalar)")
        engine = ZeroInfinityEngine(
            layers=model, layer_params=model_parameters,
            loss_fn=kwargs.pop("loss_fn"),
            config=_Cfg(config) if not isinstance(config, _Cfg) else config)
        return engine, engine.optimizer, None, None

    engine = DeepSpeedTpuEngine(model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=mpu,
                                collate_fn=collate_fn,
                                config=config,
                                mesh_param=mesh_param,
                                **kwargs)
    return_items = [engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler]
    return tuple(return_items)


def init_inference(model=None, config=None, params=None, **kwargs):
    """Build an inference engine (reference ``deepspeed/__init__.py:291``)."""
    from .inference.engine import InferenceEngine
    from .inference.config import DeepSpeedInferenceConfig
    if config is None:
        config = {}
    if isinstance(config, dict):
        config = DeepSpeedInferenceConfig(**{**config, **kwargs})
    return InferenceEngine(model, config=config, params=params)


def pipeline(model_dir, **kwargs):
    """Text-generation pipeline from a HF checkpoint dir (the MII
    ``mii.pipeline`` surface; see ``inference.v2.pipeline``)."""
    from .inference.v2.pipeline import pipeline as _pipeline
    return _pipeline(model_dir, **kwargs)


def add_config_arguments(parser):
    """Reference ``deepspeed/__init__.py:268`` argparse passthrough."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true")
    group.add_argument("--deepspeed_config", default=None, type=str)
    group.add_argument("--deepscale", default=False, action="store_true")
    group.add_argument("--local_rank", type=int, default=-1)
    return parser
