"""Llama-family decoder (flagship model).

The reference frames models through HF + kernel injection
(``module_inject/containers/llama.py``); here the model is native flax,
designed TPU-first:

- all matmuls batched/bfloat16-friendly (MXU), no data-dependent control flow
- GQA attention with RoPE; mask folded into one fused softmax
- optional ``scan_layers`` wraps the decoder stack in ``nn.scan`` so compile
  time and HLO size stay O(1) in depth (the 70B path)
- logical-axis metadata on every kernel via ``nn.with_partitioning`` against
  *logical* names; ``parallel/tp.py`` maps logical→mesh axes (AutoTP analog)
"""

import dataclasses
from functools import partial
from typing import Any, Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp
import flax.linen as nn
from flax.linen import partitioning as nn_partitioning
from ..ops.registry import on_tpu

# logical axis names; mapped onto mesh axes by parallel/tp.py rules
EMBED = "embed"
HIDDEN = "mlp"
HEADS = "heads"
KV = "kv"
VOCAB = "vocab"


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Decoder-family config. Defaults are Llama; the variant knobs below
    cover the reference's other injection containers (OPT/Falcon/Phi —
    ``module_inject/containers/``, ``inference/v2/model_implementations/``):
    learned positions + LayerNorm + ReLU fc MLP (OPT), parallel
    attention/MLP residual + MQA (Falcon), partial rotary + fused parallel
    block with biases (Phi)."""
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    head_dim: Optional[int] = None
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # qwen2-style qkv biases
    attention_out_bias: bool = False  # OPT/Phi: bias on the output projection
    # ---- architecture variant knobs ----
    # "rmsnorm" | "layernorm" (scale+bias) | "layernorm_nobias" (Cohere:
    # scale only) | "layernorm_np" (OLMo: non-parametric, no scale/bias)
    norm_type: str = "rmsnorm"
    pos_embedding: str = "rope"       # "rope" | "learned" (OPT) | "alibi" (BLOOM)
    embed_layernorm: bool = False     # BLOOM word_embeddings_layernorm
    pos_offset: int = 0               # OPT stores positions at index pos+2
    rotary_dim: Optional[int] = None  # Phi partial rotary; None = full head_dim
    rope_interleaved: bool = False    # GPT-J adjacent-pair rotary layout
    # Mistral/GPT-Neo local attention: keys older than sliding_window are
    # masked. sliding_window_layers = indices using the window (None = all
    # layers when sliding_window is set; GPT-Neo alternates local/global)
    sliding_window: Optional[int] = None
    sliding_window_layers: Optional[Tuple[int, ...]] = None
    attn_scale: Optional[float] = None  # None = 1/sqrt(head_dim); GPT-Neo = 1.0
    clip_qkv: Optional[float] = None  # OLMo: clamp q/k/v projections to ±clip
    logit_scale: Optional[float] = None  # Cohere: logits *= logit_scale
    # OLMo2: RMSNorm on the FLAT q/k projections (q_norm over nq*hd, k_norm
    # over nkv*hd) before the head reshape + rope
    qk_norm: bool = False
    # OLMo2: post-norm residual — x + norm(attn(x)), then x + norm(mlp(x));
    # layer norms are post_attention_layernorm / post_feedforward_layernorm
    post_norm: bool = False
    # Gemma-2: x + post_norm(attn(pre_norm(x))) for BOTH sublayers (norms:
    # input/post_attention + pre_feedforward/post_feedforward)
    sandwich_norm: bool = False
    # Gemma: RMSNorm scales stored as (weight - 1); apply (1 + w) * x_hat
    norm_plus_one: bool = False
    # Gemma: embeddings scaled by sqrt(hidden_size) after lookup
    embed_scale: Optional[float] = None
    # Gemma-2 softcaps: x -> cap * tanh(x / cap)
    attn_logit_softcapping: Optional[float] = None
    final_logit_softcapping: Optional[float] = None
    # "swiglu" | "gelu_fc" (exact erf, Falcon) | "gelu_tanh_fc" (HF
    # "gelu_new", Phi) | "relu_fc" (OPT)
    mlp_type: str = "swiglu"
    mlp_bias: bool = False            # fc1/fc2 biases (OPT/Phi)
    parallel_residual: bool = False   # Falcon/Phi: x + attn(ln(x)) + mlp(ln(x))
    # GPT-NeoX: the parallel MLP branch reads its OWN norm of x
    # (x + attn(ln1(x)) + mlp(ln2(x))); 1 = Falcon/Phi shared-norm form
    parallel_residual_norms: int = 1
    lm_head_bias: bool = False        # Phi
    num_local_experts: int = 0    # >0 = Mixtral-style MoE MLP
    num_experts_per_tok: int = 2
    moe_renormalize: bool = True  # Mixtral renormalizes top-k; Qwen2-MoE not
    # >0: sow the Switch/Mixtral load-balancing loss (reference
    # sharded_moe.py l_aux); the engine adds sown "aux_loss" scalars to the
    # training loss
    router_aux_loss_coef: float = 0.0
    # Qwen2-MoE: dense "shared expert" added to the sparse output, scaled by
    # a sigmoid gate (None = no shared expert)
    shared_expert_intermediate_size: Optional[int] = None
    moe_grouped: bool = True      # grouped GEMM (FLOPs ∝ top-k) vs dense-over-experts
    attn_impl: str = "auto"       # "auto" | "flash" (Pallas) | "xla"
    dtype: Any = jnp.bfloat16
    scan_layers: bool = False
    # ZeRO-3 live-parameter governor (runtime/zero_governor.py): scan over
    # chunks of this many layers — one chunk's params is the hard ceiling on
    # gathered-live elements (reference stage3_max_live_parameters). 1 =
    # tightest ceiling; larger chunks trade memory for fewer scan steps.
    scan_chunk_size: int = 1
    remat: bool = False
    # jax.checkpoint_policies name for selective remat (e.g. "dots_saveable":
    # save matmul outputs, recompute elementwise/norms — most of the memory
    # saving at a fraction of full remat's recompute). None = full recompute.
    remat_policy: "Optional[str]" = None
    # chunked unembed+CE (ops/chunked_ce.py): vocab-chunk size for the
    # streamed logsumexp that never materializes [tokens, vocab] logits.
    # None/0 = dense CE. The big win is large-vocab training (32k: ~2 GB
    # of saved activation at bs16 x 1k; Gemma 256k: ~8 GB).
    ce_chunk_size: "Optional[int]" = None

    @property
    def head_dim_(self):
        return self.head_dim or self.hidden_size // self.num_attention_heads

    def per_layer_elements(self) -> int:
        """Analytic element count of one decoder layer (attention + MLP/MoE
        + norms) — the unit of the ZeRO-3 live-parameter budget."""
        h, hd = self.hidden_size, self.head_dim_
        attn = h * (self.num_attention_heads * hd) * 2 \
            + h * (self.num_key_value_heads * hd) * 2
        proj = 3 if self.mlp_type in ("swiglu", "geglu_tanh") else 2
        if self.num_local_experts > 0:
            mlp = proj * h * self.intermediate_size * self.num_local_experts \
                + h * self.num_local_experts
        else:
            mlp = proj * h * self.intermediate_size
        return attn + mlp + 2 * h

    def with_live_param_budget(self, max_live_parameters: int) -> "LlamaConfig":
        """Return a config whose layer scan chunk honors the ZeRO-3
        ``stage3_max_live_parameters`` budget (runtime/zero_governor.py):
        one chunk's params is the gathered-live ceiling."""
        from ..runtime.zero_governor import chunk_size_for
        chunk = chunk_size_for(self.num_hidden_layers, self.per_layer_elements(),
                               max_live_parameters)
        return dataclasses.replace(self, scan_layers=True, scan_chunk_size=chunk)

    # ---- presets ----
    @staticmethod
    def tiny(**over):
        return LlamaConfig(**{**dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                                     num_hidden_layers=2, num_attention_heads=4,
                                     num_key_value_heads=2, max_position_embeddings=128,
                                     rope_theta=10000.0), **over})

    @staticmethod
    def llama3_8b(**over):
        return LlamaConfig(**{**dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                                     num_hidden_layers=32, num_attention_heads=32,
                                     num_key_value_heads=8), **over})

    @staticmethod
    def llama3_70b(**over):
        return LlamaConfig(**{**dict(vocab_size=128256, hidden_size=8192, intermediate_size=28672,
                                     num_hidden_layers=80, num_attention_heads=64,
                                     num_key_value_heads=8, scan_layers=True), **over})


def precompute_rope(head_dim: int, max_len: int, theta: float, dtype=jnp.float32):
    inv_freq = 1.0 / (theta**(jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin, positions, rotary_dim: Optional[int] = None,
               interleaved: bool = False):
    """x: [b, s, h, d]; rotate-half formulation (reference
    csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu, rebuilt in jnp —
    XLA fuses this into the surrounding matmuls). ``rotary_dim < d`` rotates
    only the leading slice (Phi-style partial rotary). ``interleaved``
    rotates adjacent pairs (x[2i], x[2i+1]) — GPT-J's layout — instead of the
    half-split (x[i], x[i+d/2]) NeoX/Llama layout."""
    if rotary_dim is not None and rotary_dim < x.shape[-1]:
        xr, xp = x[..., :rotary_dim], x[..., rotary_dim:]
        return jnp.concatenate([apply_rope(xr, cos, sin, positions,
                                           interleaved=interleaved), xp],
                               axis=-1).astype(x.dtype)
    c = cos[positions][:, :, None, :]  # [b, s, 1, d/2]
    s = sin[positions][:, :, None, :]
    if interleaved:
        x1, x2 = x[..., ::2], x[..., 1::2]
        r1 = x1 * c - x2 * s
        r2 = x2 * c + x1 * s
        return jnp.stack([r1, r2], axis=-1).reshape(x.shape).astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    plus_one: bool = False  # Gemma stores scales as (weight - 1)

    @nn.compact
    def __call__(self, x):
        scale = self.param("weight",
                           nn.initializers.zeros if self.plus_one
                           else nn.initializers.ones,
                           (x.shape[-1], ), jnp.float32)
        if self.plus_one:
            scale = 1.0 + scale
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        out = xf * jax.lax.rsqrt(var + self.eps)
        return (out * scale).astype(self.dtype)


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (HF ``build_alibi_tensor`` formula, including the
    non-power-of-2 interpolation). Press et al., "Train Short, Test Long"."""
    import math
    closest = 2 ** math.floor(math.log2(n_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = base ** np.arange(1, closest + 1)
    if closest != n_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        extra = extra_base ** np.arange(1, 2 * (n_heads - closest), 2)
        slopes = np.concatenate([slopes, extra])
    return slopes.astype(np.float32)


def _use_cast(w, dtype):
    """Use-site weight cast, hoist-proof (engine ``param_cast="model"``).

    fp32 masters arrive stacked ``[L, ...]`` under ``nn.scan``; each scan
    step must down-convert only ITS slice, or peak HBM grows by a whole
    bf16 copy of the model. XLA undoes a naive in-body ``astype`` —
    ``convert(slice(W))`` commutes to ``slice(convert(W))`` and LICM hoists
    the now loop-invariant whole-tree convert right back out of the scan
    loop (the round-4 OOM pattern, ``.perf/bench_fast_r4_0731T1228.out``).
    The ``optimization_barrier`` between the slice and the cast makes that
    reorder illegal, pinning the convert to chunk granularity. When params
    already arrive at compute dtype (engine-side casting), this is a no-op.
    """
    if w.dtype == dtype:
        return w
    return jax.lax.optimization_barrier(w).astype(dtype)


class _BarrierDense(nn.Module):
    """nn.Dense with a hoist-proof use-site kernel cast (see _use_cast).
    Same param names/shapes/partitioning as nn.Dense."""
    features: int
    dtype: Any
    kernel_init: Any
    bias_init: Any
    use_bias: bool = False

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features))
        y = jax.lax.dot_general(
            x.astype(self.dtype), _use_cast(kernel, self.dtype),
            (((x.ndim - 1, ), (0, )), ((), ())))
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features, ))
            y = y + _use_cast(bias, self.dtype)
        return y


def _dense(features, name, axes, dtype, use_bias=False):
    return _BarrierDense(features, use_bias=use_bias, dtype=dtype, name=name,
                         kernel_init=nn.with_partitioning(nn.initializers.lecun_normal(), axes),
                         bias_init=nn.with_partitioning(nn.initializers.zeros, (axes[-1], )))


def _make_norm(cfg, name):
    if cfg.norm_type == "layernorm":
        return nn.LayerNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype, name=name)
    if cfg.norm_type == "layernorm_nobias":  # Cohere: mean-subtracted, scale only
        return nn.LayerNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype,
                            use_bias=False, name=name)
    if cfg.norm_type == "layernorm_np":  # OLMo: no learnable params at all
        return nn.LayerNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype,
                            use_bias=False, use_scale=False, name=name)
    return RMSNorm(cfg.rms_norm_eps, cfg.dtype, plus_one=cfg.norm_plus_one,
                   name=name)


def _layer_window(cfg, layer_idx: int):
    """Sliding window for this layer (None = global attention)."""
    if cfg.sliding_window is None:
        return None
    if (cfg.sliding_window_layers is not None
            and layer_idx not in cfg.sliding_window_layers):
        return None
    return cfg.sliding_window


class LlamaAttention(nn.Module):
    config: LlamaConfig
    layer_idx: int = 0

    @nn.compact
    def __call__(self, x, cos, sin, positions, attn_mask=None):
        cfg = self.config
        window = _layer_window(cfg, self.layer_idx)
        b, s, _ = x.shape
        hd = cfg.head_dim_
        nq, nkv = cfg.num_attention_heads, cfg.num_key_value_heads

        q = _dense(nq * hd, "q_proj", (EMBED, HEADS), cfg.dtype, cfg.attention_bias)(x)
        k = _dense(nkv * hd, "k_proj", (EMBED, KV), cfg.dtype, cfg.attention_bias)(x)
        v = _dense(nkv * hd, "v_proj", (EMBED, KV), cfg.dtype, cfg.attention_bias)(x)
        if cfg.clip_qkv is not None:  # OLMo stability clamp
            q = jnp.clip(q, -cfg.clip_qkv, cfg.clip_qkv)
            k = jnp.clip(k, -cfg.clip_qkv, cfg.clip_qkv)
            v = jnp.clip(v, -cfg.clip_qkv, cfg.clip_qkv)
        if cfg.qk_norm:  # OLMo2: normalize the flat projections pre-reshape
            q = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="q_norm")(q)
            k = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="k_norm")(k)

        q = q.reshape(b, s, nq, hd)
        k = k.reshape(b, s, nkv, hd)
        v = v.reshape(b, s, nkv, hd)
        if cfg.pos_embedding == "rope":
            q = apply_rope(q, cos, sin, positions, cfg.rotary_dim, cfg.rope_interleaved)
            k = apply_rope(k, cos, sin, positions, cfg.rotary_dim, cfg.rope_interleaved)

        # GQA handled natively by both paths (no materialized K/V head
        # repeat — 4x K/V bandwidth saving at 8B scale). The Pallas flash
        # kernel (fwd AND bwd, ops/attention.py) runs on TPU when the shape
        # tiles cleanly and there's no padding mask; XLA's fused
        # dot_product_attention otherwise.
        from ..ops.attention import flash_attention

        from ..comm.mesh import mesh_is_initialized, get_mesh_context
        mesh_shape = (dict(get_mesh_context().mesh.shape)
                      if mesh_is_initialized() else {})
        sp_sz = mesh_shape.get("seq", 1)
        mp_sz = mesh_shape.get("model", 1)

        # shared flash eligibility (shape/mask/positions); the sharded and
        # unsharded dispatch conditions below both build on it
        flash_shape_ok = (cfg.attn_impl != "xla" and attn_mask is None
                          and cfg.pos_embedding != "alibi"
                          and (s <= 128 or s % 128 == 0))
        on_flash_backend = cfg.attn_impl == "flash" or on_tpu()
        # a raw pallas_call doesn't auto-partition under GSPMD: with a
        # nontrivial seq/model mesh the sharded dispatch below owns the
        # kernel path
        use_flash = (flash_shape_ok and on_flash_backend
                     and sp_sz == 1 and mp_sz == 1)
        if use_flash:
            # the Pallas kernel handles local (sliding-window) attention
            # natively, skipping out-of-window blocks
            attn = flash_attention(q, k, v, causal=True, scale=cfg.attn_scale,
                                   window=window,
                                   softcap=cfg.attn_logit_softcapping,
                                   interpret=not on_tpu())
        else:
            mask = None
            if attn_mask is not None:
                # [b, s] key padding mask -> [b, 1, 1, s]
                mask = attn_mask[:, None, None, :].astype(bool)
            if window is not None:
                # Mistral/GPT-Neo local attention: drop keys older than the
                # window (the causal side is handled by is_causal)
                keep = (positions[:, None, :, None] - positions[:, None, None, :]
                        < window)
                mask = keep if mask is None else (mask & keep)
            bias = None
            if cfg.pos_embedding == "alibi":
                # BLOOM: logits += slope_h * (key_pos - query_pos); future
                # positions are cut by the causal mask
                slopes = jnp.asarray(alibi_slopes(nq))
                dist = (positions[:, None, None, :]
                        - positions[:, None, :, None]).astype(jnp.float32)
                bias = slopes[None, :, None, None] * dist

            def _core_attn(q, k, v):
                if cfg.attn_logit_softcapping is not None:
                    # Gemma-2: scores -> cap*tanh(scores/cap) BEFORE masking;
                    # tanh is not expressible as an additive bias, so this
                    # path computes dense attention by hand — grouped over
                    # KV heads (no materialized GQA repeat)
                    cap = jnp.float32(cfg.attn_logit_softcapping)
                    kvh = k.shape[2]
                    g = q.shape[2] // kvh
                    scl = (cfg.attn_scale if cfg.attn_scale is not None
                           else 1.0 / float(np.sqrt(hd)))
                    qg = q.reshape(b, s, kvh, g, hd).astype(jnp.float32)
                    from ..ops.attention import softcap_scores
                    scores = jnp.einsum("bqkgd,blkd->bkgql", qg,
                                        k.astype(jnp.float32)) * jnp.float32(scl)
                    scores = softcap_scores(scores, cap)
                    causal = (positions[:, :, None]
                              >= positions[:, None, :])[:, None, None]
                    keep_all = causal if mask is None \
                        else (causal & mask[:, :, None])
                    scores = jnp.where(keep_all, scores, -1e30)
                    probs = jax.nn.softmax(scores, axis=-1)
                    out = jnp.einsum("bkgql,blkd->bqkgd", probs,
                                     v.astype(jnp.float32))
                    return out.reshape(b, s, q.shape[2], hd).astype(q.dtype)
                return jax.nn.dot_product_attention(q, k, v, bias=bias, mask=mask,
                                                    is_causal=True,
                                                    scale=cfg.attn_scale)

            attn = None
            if (sp_sz > 1 or mp_sz > 1) and flash_shape_ok and on_flash_backend:
                # flash-inside-shard_map: seq axis = Ulysses all-to-alls
                # (the 32k-seq memory-safe path), model axis = per-head-block
                # kernel (a raw pallas_call can't auto-partition under GSPMD)
                from ..sequence.layer import ulysses_flash
                attn = ulysses_flash(
                    q, k, v, window=window, scale=cfg.attn_scale,
                    softcap=cfg.attn_logit_softcapping,
                    interpret=not on_tpu())
            if attn is None and sp_sz > 1:
                # GSPMD Ulysses: sharding constraints make XLA emit the
                # all-to-all pair around full-sequence attention
                from ..sequence.layer import ulysses_spmd
                attn = ulysses_spmd(_core_attn, q, k, v)
            if attn is None:
                attn = _core_attn(q, k, v)
        out = attn.reshape(b, s, nq * hd)
        return _dense(cfg.hidden_size, "o_proj", (HEADS, EMBED), cfg.dtype,
                      cfg.attention_out_bias)(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        if cfg.mlp_type in ("swiglu", "geglu_tanh"):
            # gated MLP: silu gate (llama) or tanh-gelu gate (gemma)
            gate = _dense(cfg.intermediate_size, "gate_proj", (EMBED, HIDDEN), cfg.dtype)(x)
            up = _dense(cfg.intermediate_size, "up_proj", (EMBED, HIDDEN), cfg.dtype)(x)
            g = (nn.silu(gate) if cfg.mlp_type == "swiglu"
                 else nn.gelu(gate, approximate=True))
            return _dense(cfg.hidden_size, "down_proj", (HIDDEN, EMBED),
                          cfg.dtype)(g * up)
        # fc1/fc2 form: Falcon uses exact (erf) GELU, Phi HF "gelu_new" is
        # the tanh approximation, OPT is ReLU
        act = {"gelu_fc": lambda y: nn.gelu(y, approximate=False),
               "gelu_tanh_fc": lambda y: nn.gelu(y, approximate=True),
               "relu_fc": nn.relu}[cfg.mlp_type]
        h = _dense(cfg.intermediate_size, "fc1", (EMBED, HIDDEN), cfg.dtype,
                   cfg.mlp_bias)(x)
        return _dense(cfg.hidden_size, "fc2", (HIDDEN, EMBED), cfg.dtype,
                      cfg.mlp_bias)(act(h))


class LlamaMoEBlock(nn.Module):
    """Mixtral-style sparse MoE MLP (reference moe/sharded_moe.py gating +
    module_inject/containers mixtral): softmax router over E experts, top-k
    renormalized combine. Compute is a megablocks-style grouped GEMM
    (``ops/grouped_matmul.py``: sort-by-expert → ragged_dot → weighted
    scatter combine) so per-token FLOPs ∝ top-k, matching the reference's
    CUTLASS moe_gemm capability; ``moe_grouped=False`` keeps the
    dense-over-experts oracle (also the better layout when the 'expert'
    logical axis is sharded over a real mesh axis — EP uses moe/layer.py's
    all-to-all dispatch instead). Expert weights carry the 'expert' logical
    axis so EP sharding is a mesh rule like everything else."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        from ..ops.grouped_matmul import moe_grouped_mlp, moe_dense_mlp
        cfg = self.config
        E, k = cfg.num_local_experts, cfg.num_experts_per_tok
        H, F = cfg.hidden_size, cfg.intermediate_size
        logits = _dense(E, "gate", (EMBED, "expert"), jnp.float32)(x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        w, idx = jax.lax.top_k(probs, k)
        if cfg.router_aux_loss_coef > 0:
            # Switch/Mixtral load balance: E * sum_e(frac_routed_e * mean_prob_e)
            pe = probs.reshape(-1, E).mean(axis=0)
            fe = jax.nn.one_hot(idx.reshape(-1), E).mean(axis=0)
            self.sow("aux_loss", "moe_load_balance",
                     cfg.router_aux_loss_coef * E * jnp.sum(fe * pe),
                     reduce_fn=lambda a, b: a + b, init_fn=lambda: jnp.float32(0.0))
        if cfg.moe_renormalize:  # Mixtral; Qwen2-MoE keeps raw softmax mass
            w = w / jnp.sum(w, -1, keepdims=True)
        w = w.astype(cfg.dtype)

        init = nn.with_partitioning(nn.initializers.lecun_normal(), ("expert", EMBED, HIDDEN))
        w1 = _use_cast(self.param("w1", init, (E, H, F), jnp.float32), cfg.dtype)
        w3 = _use_cast(self.param("w3", init, (E, H, F), jnp.float32), cfg.dtype)
        w2 = _use_cast(self.param("w2",
                                  nn.with_partitioning(nn.initializers.lecun_normal(),
                                                       ("expert", HIDDEN, EMBED)),
                                  (E, F, H), jnp.float32), cfg.dtype)

        lead = x.shape[:-1]
        xt = x.reshape(-1, H)
        fn = moe_grouped_mlp if cfg.moe_grouped else moe_dense_mlp
        out = fn(xt, w1, w3, w2, idx.reshape(-1, k), w.reshape(-1, k))
        out = out.reshape(*lead, H)
        if cfg.shared_expert_intermediate_size:  # Qwen2-MoE
            se_cfg = dataclasses.replace(
                cfg, intermediate_size=cfg.shared_expert_intermediate_size,
                num_local_experts=0)
            shared = LlamaMLP(se_cfg, name="shared_expert")(x)
            g = _dense(1, "shared_expert_gate", (EMBED, HIDDEN), jnp.float32)(
                x.astype(jnp.float32))
            out = out + jax.nn.sigmoid(g).astype(cfg.dtype) * shared
        return out


class LlamaDecoderLayer(nn.Module):
    config: LlamaConfig
    layer_idx: int = 0

    @nn.compact
    def __call__(self, x, cos, sin, positions, attn_mask=None):
        cfg = self.config
        if cfg.sandwich_norm:
            # Gemma-2: pre AND post norms around both sublayers
            attn_out = LlamaAttention(cfg, self.layer_idx, name="self_attn")(
                _make_norm(cfg, "input_layernorm")(x), cos, sin, positions,
                attn_mask)
            h = x + _make_norm(cfg, "post_attention_layernorm")(attn_out)
            mlp_out = LlamaMLP(cfg, name="mlp")(
                _make_norm(cfg, "pre_feedforward_layernorm")(h))
            return h + _make_norm(cfg, "post_feedforward_layernorm")(mlp_out)
        if cfg.post_norm:
            # OLMo2: no input norms — the SUBLAYER OUTPUT is normalized
            attn_out = LlamaAttention(cfg, self.layer_idx, name="self_attn")(
                x, cos, sin, positions, attn_mask)
            h = x + _make_norm(cfg, "post_attention_layernorm")(attn_out)
            if cfg.num_local_experts > 0:
                mlp_out = LlamaMoEBlock(cfg, name="block_sparse_moe")(h)
            else:
                mlp_out = LlamaMLP(cfg, name="mlp")(h)
            return h + _make_norm(cfg, "post_feedforward_layernorm")(mlp_out)
        normed = _make_norm(cfg, "input_layernorm")(x)
        attn_out = LlamaAttention(cfg, self.layer_idx, name="self_attn")(
            normed, cos, sin, positions, attn_mask)
        if cfg.parallel_residual:
            # Falcon/Phi: one shared input norm feeds BOTH branches;
            # GPT-NeoX (norms=2): the MLP branch norms x independently
            if cfg.parallel_residual_norms == 2:
                normed = _make_norm(cfg, "post_attention_layernorm")(x)
            return x + attn_out + LlamaMLP(cfg, name="mlp")(normed)
        h = x + attn_out
        normed2 = _make_norm(cfg, "post_attention_layernorm")(h)
        if cfg.num_local_experts > 0:
            h = h + LlamaMoEBlock(cfg, name="block_sparse_moe")(normed2)
        else:
            h = h + LlamaMLP(cfg, name="mlp")(normed2)
        return h


class LMHead(nn.Module):
    """Unembed with bf16 MXU inputs but fp32 accumulation *and* output.

    Keeps the ``lm_head/kernel`` param path (HF conversion + AutoTP policies
    address it) while controlling the matmul output dtype, which ``nn.Dense``
    can't (its output dtype == compute dtype).
    """
    features: int
    dtype: Any
    use_bias: bool = False

    @nn.compact
    def __call__(self, x, return_params=False):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
            (x.shape[-1], self.features))
        bias = self.param(
            "bias", nn.with_partitioning(nn.initializers.zeros, (VOCAB, )),
            (self.features, ), jnp.float32) if self.use_bias else None
        if return_params:  # chunked-CE path: same param tree, no matmul here
            return kernel, bias
        out = jax.lax.dot_general(
            x.astype(self.dtype), kernel.astype(self.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if self.use_bias:
            out = out + bias
        return out


def _remat_layer_cls(cfg):
    """nn.remat with the configured jax.checkpoint_policies policy (selective
    remat — reference activation_checkpointing config's TPU analog)."""
    if cfg.remat_policy:
        pol = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
        if pol is None:
            raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")
        return nn.remat(LlamaDecoderLayer, policy=pol)
    return nn.remat(LlamaDecoderLayer)


class _ScanBody(nn.Module):
    """nn.scan adapter: scan bodies must return (carry, out). With
    ``scan_chunk_size > 1`` one scan step applies a chunk of layers (the
    ZeRO-3 live-parameter governor's chunk)."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, positions, attn_mask=None):
        cfg = self.config
        layer_cls = _remat_layer_cls(cfg) if cfg.remat else LlamaDecoderLayer
        if cfg.scan_chunk_size <= 1:
            return layer_cls(cfg, name="layer")(x, cos, sin, positions, attn_mask), None
        for i in range(cfg.scan_chunk_size):
            x = layer_cls(cfg, name=f"layer_{i}")(x, cos, sin, positions, attn_mask)
        return x, None


class LlamaModel(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, attn_mask=None,
                 return_unembed=False):
        cfg = self.config
        if positions is None:
            positions = jnp.arange(input_ids.shape[1])[None, :].astype(jnp.int32)
            positions = jnp.broadcast_to(positions, input_ids.shape)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         embedding_init=nn.with_partitioning(nn.initializers.normal(0.02),
                                                             (VOCAB, EMBED)),
                         name="embed_tokens")
        x = embed(input_ids)
        if cfg.embed_scale is not None:  # Gemma: sqrt(hidden) normalizer,
            # rounded through the compute dtype exactly as HF does
            x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
        if cfg.embed_layernorm:  # BLOOM word_embeddings_layernorm
            x = nn.LayerNorm(epsilon=cfg.rms_norm_eps, dtype=cfg.dtype,
                             name="embed_layernorm")(x)
        if cfg.pos_embedding == "learned":
            # OPT-style learned positions (HF offsets the table by pos_offset)
            pos_table = nn.Embed(cfg.max_position_embeddings + cfg.pos_offset,
                                 cfg.hidden_size, dtype=cfg.dtype,
                                 embedding_init=nn.with_partitioning(
                                     nn.initializers.normal(0.02), (VOCAB, EMBED)),
                                 name="embed_positions")
            x = x + pos_table(positions + cfg.pos_offset)
        cos, sin = precompute_rope(cfg.rotary_dim or cfg.head_dim_,
                                   cfg.max_position_embeddings, cfg.rope_theta)

        if cfg.scan_layers:
            # scan over depth: O(1) HLO in layer count (the 70B compile path);
            # gathered-live params are hard-bounded to ONE scan step's chunk
            # (the ZeRO-3 max_live_parameters governor, zero_governor.py)
            if cfg.sliding_window_layers is not None:
                raise ValueError(
                    "scan_layers requires homogeneous layers; per-layer "
                    "sliding_window_layers patterns need scan_layers=False")
            if cfg.num_hidden_layers % cfg.scan_chunk_size != 0:
                raise ValueError(
                    f"num_hidden_layers={cfg.num_hidden_layers} not divisible "
                    f"by scan_chunk_size={cfg.scan_chunk_size}")
            # aux_loss rides the scan as a stacked per-step axis (the engine
            # sums all leaves, so stacking ≡ the unscanned reduce_fn sum)
            ScanLayer = nn.scan(_ScanBody,
                                variable_axes={"params": 0, "aux_loss": 0},
                                split_rngs={"params": True},
                                in_axes=nn.broadcast,
                                length=cfg.num_hidden_layers // cfg.scan_chunk_size,
                                metadata_params={nn.PARTITION_NAME: "layers"})
            x, _ = ScanLayer(cfg, name="layers")(x, cos, sin, positions, attn_mask)
        else:
            layer_cls = _remat_layer_cls(cfg) if cfg.remat else LlamaDecoderLayer
            for i in range(cfg.num_hidden_layers):
                x = layer_cls(cfg, i, name=f"layers_{i}")(x, cos, sin, positions,
                                                          attn_mask)
        x = _make_norm(cfg, "norm")(x)
        if return_unembed:
            # chunked-CE path (ops/chunked_ce.py): hand back the raw unembed
            # weight [H, V] (+bias) instead of materialized logits; scale and
            # softcap are applied per chunk inside the op
            if cfg.tie_word_embeddings:
                return x, embed.embedding.T, None
            w, b = LMHead(cfg.vocab_size, cfg.dtype, use_bias=cfg.lm_head_bias,
                          name="lm_head")(x, return_params=True)
            return x, w, b
        # unembed: bf16 inputs ride the MXU fast path (fp32 matmul is several×
        # slower), but the accumulator stays fp32 and the *output* is emitted
        # fp32 (preferred_element_type) — rounding logits to bf16 before the
        # CE logsumexp loses precision at large vocab sizes
        if cfg.tie_word_embeddings:
            logits = jax.lax.dot_general(
                x.astype(cfg.dtype), embed.embedding.astype(cfg.dtype),
                (((x.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            logits = LMHead(cfg.vocab_size, cfg.dtype, use_bias=cfg.lm_head_bias,
                            name="lm_head")(x)
        if cfg.logit_scale is not None:  # Cohere
            logits = logits * jnp.float32(cfg.logit_scale)
        if cfg.final_logit_softcapping is not None:  # Gemma-2
            cap = jnp.float32(cfg.final_logit_softcapping)
            logits = cap * jnp.tanh(logits / cap)
        return logits


def cross_entropy_loss(logits, labels, ignore_index: int = -100):
    """Token-mean CE with shift-by-one (causal LM)."""
    logits = logits[:, :-1].astype(jnp.float32)
    targets = labels[:, 1:]
    mask = (targets != ignore_index).astype(jnp.float32)
    targets = jnp.where(targets == ignore_index, 0, targets)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    return nll.sum() / jnp.maximum(mask.sum(), 1.0)


class LlamaForCausalLM(nn.Module):
    """Engine-contract wrapper: returns scalar loss when labels given."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, positions=None, attn_mask=None):
        cfg = self.config
        if labels is not None and cfg.ce_chunk_size:
            from ..ops.chunked_ce import chunked_cross_entropy_loss
            x, w, b = LlamaModel(cfg, name="model")(input_ids, positions,
                                                    attn_mask,
                                                    return_unembed=True)
            return chunked_cross_entropy_loss(
                x, w, b, labels, cfg.ce_chunk_size,
                logit_scale=cfg.logit_scale,
                softcap=cfg.final_logit_softcapping,
                compute_dtype=cfg.dtype)
        logits = LlamaModel(cfg, name="model")(input_ids, positions, attn_mask)
        if labels is None:
            return logits
        return cross_entropy_loss(logits, labels)


def unbox_params(params):
    """Strip flax Partitioned metadata boxes → plain array pytree."""
    return jax.tree_util.tree_map(
        lambda x: x.unbox() if hasattr(x, "unbox") else x, params,
        is_leaf=lambda x: hasattr(x, "unbox"))


def logical_axis_tree(params):
    """Pytree of logical-axis tuples (or None) per leaf, for parallel/tp.py."""
    return jax.tree_util.tree_map(
        lambda x: tuple(x.names) if hasattr(x, "names") else None, params,
        is_leaf=lambda x: hasattr(x, "unbox"))


def init_llama(config: LlamaConfig, seed: int = 0, seq_len: int = 8):
    model = LlamaForCausalLM(config)
    ids = jnp.ones((1, seq_len), dtype=jnp.int32)
    variables = model.init(jax.random.PRNGKey(seed), ids)
    return model, unbox_params(variables["params"])
