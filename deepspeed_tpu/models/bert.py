"""BERT-family encoder (TPU-native flax).

Reference: ``module_inject/containers/bert.py`` (HFBertLayerPolicy) +
``containers/distil_bert.py`` — the reference injects fused kernels into HF
``BertLayer``s; here the whole encoder is a native flax module the HF
checkpoint converts into (``module_inject/replace_policy.py BertPolicy``),
jitted as one program.

Post-LN architecture (attention → add&norm → FFN → add&norm), bidirectional
attention with a key-padding mask, learned word+position(+token-type)
embeddings with an embedding LayerNorm. DistilBERT is the same graph minus
token-type embeddings and pooler (``distilbert=True``).
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from .llama import EMBED, HEADS, HIDDEN, VOCAB, _dense
from ..ops.registry import on_tpu


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    distilbert: bool = False   # no token-type embeddings / pooler
    # converter duck-typing (module_inject/replace_module.py walks these)
    tie_word_embeddings: bool = True   # MLM decoder ties to word_embeddings
    attention_bias: bool = True
    num_local_experts: int = 0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def _ln(cfg, name):
    return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name=name)


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attn_mask=None):
        cfg = self.config
        b, s, _ = x.shape
        n, hd = cfg.num_attention_heads, cfg.head_dim
        q = _dense(n * hd, "query", (EMBED, HEADS), cfg.dtype, True)(x).reshape(b, s, n, hd)
        k = _dense(n * hd, "key", (EMBED, HEADS), cfg.dtype, True)(x).reshape(b, s, n, hd)
        v = _dense(n * hd, "value", (EMBED, HEADS), cfg.dtype, True)(x).reshape(b, s, n, hd)
        mask = None
        if attn_mask is not None:
            mask = attn_mask[:, None, None, :].astype(bool)  # key padding
        # unmasked encoder attention rides the Pallas flash kernel on TPU
        # (bidirectional; the legacy DeepSpeedTransformerLayer training path
        # — reference csrc/transformer fused BERT kernels); padding masks,
        # non-tiling lengths, and nontrivial seq/model meshes (a raw
        # pallas_call can't auto-partition under GSPMD) use XLA attention
        from ..comm.mesh import mesh_is_initialized, get_mesh_context
        unsharded = (not mesh_is_initialized()
                     or (get_mesh_context().axis_size("seq") == 1
                         and get_mesh_context().axis_size("model") == 1))
        if (mask is None and unsharded and on_tpu()
                and (s <= 128 or s % 128 == 0)):
            from ..ops.attention import flash_attention
            attn = flash_attention(q, k, v, causal=False)
        else:
            attn = jax.nn.dot_product_attention(q, k, v, mask=mask)
        out = attn.reshape(b, s, n * hd)
        return _dense(cfg.hidden_size, "output", (HEADS, EMBED), cfg.dtype, True)(out)


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attn_mask=None):
        cfg = self.config
        # post-LN: norm AFTER each residual add
        attn = BertSelfAttention(cfg, name="attention")(x, attn_mask)
        x = _ln(cfg, "attention_layernorm")(x + attn)
        h = _dense(cfg.intermediate_size, "intermediate", (EMBED, HIDDEN),
                   cfg.dtype, True)(x)
        h = jax.nn.gelu(h, approximate=False)
        h = _dense(cfg.hidden_size, "mlp_output", (HIDDEN, EMBED), cfg.dtype, True)(h)
        return _ln(cfg, "output_layernorm")(x + h)


class BertModel(nn.Module):
    """Encoder trunk: [b, s] ids (+ mask, token types) → [b, s, h] states."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attn_mask=None, token_type_ids=None,
                 return_embed_matrix: bool = False):
        cfg = self.config
        embed_mod = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                             embedding_init=nn.with_partitioning(
                                 nn.initializers.normal(0.02), (VOCAB, EMBED)),
                             name="word_embeddings")
        emb = embed_mod(input_ids)
        pos = jnp.arange(input_ids.shape[1], dtype=jnp.int32)[None, :]
        emb = emb + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                             dtype=cfg.dtype,
                             embedding_init=nn.with_partitioning(
                                 nn.initializers.normal(0.02), (VOCAB, EMBED)),
                             name="position_embeddings")(pos)
        if not cfg.distilbert:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            emb = emb + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                                 embedding_init=nn.with_partitioning(
                                     nn.initializers.normal(0.02), (VOCAB, EMBED)),
                                 name="token_type_embeddings")(token_type_ids)
        x = _ln(cfg, "embeddings_layernorm")(emb)
        for i in range(cfg.num_hidden_layers):
            x = BertLayer(cfg, name=f"layer_{i}")(x, attn_mask)
        if return_embed_matrix:  # weight tying for the MLM decoder
            mat = embed_mod.embedding
            return x, (mat.unbox() if hasattr(mat, "unbox") else mat)
        return x


class BertForMaskedLM(nn.Module):
    """MLM head: transform (dense+gelu+LN) then decode against the word
    embeddings (HF ties the decoder to word_embeddings)."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attn_mask=None, token_type_ids=None):
        cfg = self.config
        x, embed_mat = BertModel(cfg, name="bert")(input_ids, attn_mask, token_type_ids,
                                                   return_embed_matrix=True)
        x = _dense(cfg.hidden_size, "transform", (EMBED, EMBED), cfg.dtype, True)(x)
        x = jax.nn.gelu(x, approximate=False)
        x = _ln(cfg, "transform_layernorm")(x)
        logits = jax.lax.dot_general(
            x.astype(cfg.dtype), embed_mat.astype(cfg.dtype).T,
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        bias = self.param("decoder_bias", nn.initializers.zeros, (cfg.vocab_size, ),
                          jnp.float32)
        return logits + bias


def init_bert(cfg: BertConfig, seed: int = 0, mlm: bool = True):
    model = (BertForMaskedLM if mlm else BertModel)(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), ids)["params"]
    return model, params
