from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel, cross_entropy_loss, init_llama,
                    unbox_params, logical_axis_tree)
