"""Linear/LoRA configs (reference ``deepspeed/linear/config.py`` — same
fields)."""

from dataclasses import dataclass, field
from typing import Optional, Tuple

LORA_DTYPES = ("bfloat16", "float32", "float16")

# projection kernels a serving-side adapter may target (the subset of the
# AutoTP-recognized names the ragged forward exposes a LoRA hook on)
LORA_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj",
                "gate_proj", "up_proj", "down_proj")


@dataclass
class LoRAConfig:
    """Reference linear/config.py LoRAConfig — extended to double as the
    serving-side adapter spec (inference/v2/adapters): training and
    serving share ONE dataclass and one scaling rule
    (``alpha / sqrt(r)``, matching ``LoRAOptimizedLinear``)."""
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1  # shard the frozen base over 'model' axis
    lora_dtype: str = "bfloat16"
    # serving-side: which projection kernels the adapter's factors cover
    # (training-side LoRAOptimizedLinear wraps one layer and ignores this)
    targets: Tuple[str, ...] = field(default_factory=lambda: ("q_proj", "v_proj"))

    def __post_init__(self):
        self.validate()

    def validate(self) -> "LoRAConfig":
        if int(self.lora_r) < 1:
            raise ValueError(f"lora_r must be >= 1, got {self.lora_r}")
        if float(self.lora_alpha) < 0:
            # alpha == 0 is the explicit "disabled adapter" sentinel
            # (OptimizedLinear's quantized-only path): scaling 0 zeroes the
            # LoRA branch exactly
            raise ValueError(f"lora_alpha must be >= 0, got {self.lora_alpha}")
        if self.lora_dtype not in LORA_DTYPES:
            raise ValueError(f"lora_dtype must be one of {LORA_DTYPES}, "
                             f"got {self.lora_dtype!r}")
        if int(self.base_weight_sharding) < 1:
            raise ValueError("base_weight_sharding must be >= 1, got "
                             f"{self.base_weight_sharding}")
        self.targets = tuple(self.targets)
        for t in self.targets:
            if t not in LORA_TARGETS:
                raise ValueError(f"unknown LoRA target {t!r}; expected a "
                                 f"subset of {LORA_TARGETS}")
        if not self.targets:
            raise ValueError("LoRA targets must name at least one kernel")
        return self

    @property
    def scaling(self) -> float:
        """The LoRAOptimizedLinear scaling — ONE rule for train + serve."""
        return float(self.lora_alpha) / (int(self.lora_r) ** 0.5)


@dataclass
class QuantizationConfig:
    """Reference linear/config.py QuantizationConfig (FP quantization of the
    frozen base weight; int8 blockwise here — the TPU-native cheap format)."""
    q_bits: int = 8
    mantissa_bits: int = 3  # accepted for parity; int8 path ignores it
    group_size: int = 512
