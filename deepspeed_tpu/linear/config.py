"""Linear/LoRA configs (reference ``deepspeed/linear/config.py`` — same
fields)."""

from dataclasses import dataclass


@dataclass
class LoRAConfig:
    """Reference linear/config.py LoRAConfig."""
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1  # shard the frozen base over 'model' axis


@dataclass
class QuantizationConfig:
    """Reference linear/config.py QuantizationConfig (FP quantization of the
    frozen base weight; int8 blockwise here — the TPU-native cheap format)."""
    q_bits: int = 8
    mantissa_bits: int = 3  # accepted for parity; int8 path ignores it
    group_size: int = 512
