"""OptimizedLinear / LoRAOptimizedLinear.

Reference: ``deepspeed/linear/optimized_linear.py:18 OptimizedLinear`` (a
factory: plain Linear, or LoRAOptimizedLinear :76 when lora_config given —
frozen possibly-quantized sharded base weight + trainable low-rank A·B).

TPU design: flax modules. The frozen base weight is a *constant* captured in
the module (not a trainable param) — optionally int8-quantized storage
(dequant fuses into the matmul under jit) and sharded over the ``model``
mesh axis by AutoTP rules; only lora_A/lora_B are flax params, so the
optimizer state is rank-r (the entire point of LoRA).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from ..utils.logging import logger
from .config import LoRAConfig, QuantizationConfig
from .quantization import QuantizedParameter


class LoRAOptimizedLinear(nn.Module):
    """y = x @ W_base(frozen) + (x @ A) @ B * (alpha / sqrt(r))."""
    output_dim: int
    base_weight: Any  # jnp array [in, out] or QuantizedParameter
    lora_config: LoRAConfig
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        cfg = self.lora_config
        w = self.base_weight
        if isinstance(w, QuantizedParameter):
            w = w.dequantized()
        w = jax.lax.stop_gradient(w.astype(self.dtype))
        in_dim = w.shape[0]
        # reference scales by alpha/sqrt(r) (linear/optimized_linear.py:76)
        scaling = cfg.lora_alpha / (cfg.lora_r**0.5)
        lora_a = self.param("lora_a", nn.initializers.lecun_normal(),
                            (in_dim, cfg.lora_r), jnp.float32)
        lora_b = self.param("lora_b", nn.initializers.zeros,
                            (cfg.lora_r, self.output_dim), jnp.float32)
        base = x @ w
        delta = (x @ lora_a.astype(self.dtype)) @ lora_b.astype(self.dtype)
        return base + delta * scaling


def OptimizedLinear(input_dim: int,
                    output_dim: int,
                    base_weight=None,
                    lora_config: Optional[LoRAConfig] = None,
                    quantization_config: Optional[QuantizationConfig] = None,
                    dtype=jnp.bfloat16,
                    seed: int = 0):
    """Factory (reference optimized_linear.py:18): returns a flax module —
    plain Dense when no lora_config; LoRAOptimizedLinear otherwise. A given
    ``base_weight`` is quantized per quantization_config."""
    if lora_config is None and quantization_config is None:
        return nn.Dense(output_dim, use_bias=False, dtype=dtype)
    if base_weight is None:
        key = jax.random.PRNGKey(seed)
        base_weight = nn.initializers.lecun_normal()(key, (input_dim, output_dim),
                                                     jnp.float32)
    if quantization_config is not None:
        base_weight = QuantizedParameter.quantize(jnp.asarray(base_weight),
                                                  quantization_config)
    if lora_config is None:
        # quantized-only linear: frozen quantized weight, no adapters
        lora_config = LoRAConfig(lora_r=1, lora_alpha=0.0)
    return LoRAOptimizedLinear(output_dim=output_dim, base_weight=base_weight,
                               lora_config=lora_config, dtype=dtype)
