from .config import LoRAConfig, QuantizationConfig
from .optimized_linear import OptimizedLinear, LoRAOptimizedLinear
from .quantization import QuantizedParameter
