"""Quantized parameter storage.

Reference: ``deepspeed/linear/quantization.py:18 QuantizedParameter`` — a
tensor subclass that stores FP6/FP8-quantized data and dequantizes on use.
TPU version: a small container of (packed values, fp32 scales) produced by
the blockwise Pallas/XLA quantizer (``ops/quantizer.py``), dequantized
inside jit where XLA fuses it into the consuming matmul. Formats: int8
(1 byte/weight), fp6 e3m2 (0.75 bytes/weight, the FP6-LLM point —
``ops/fp_quantizer/quantize.py:43``), int4 (0.5 bytes/weight).
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..ops.quantizer import (dequantize_int8_blockwise, quantize_int8_blockwise,
                             dequantize_int4_blockwise, quantize_int4_blockwise,
                             dequantize_fp6_blockwise, quantize_fp6_blockwise)
from .config import QuantizationConfig

_FMTS = {
    8: (quantize_int8_blockwise, dequantize_int8_blockwise),
    6: (quantize_fp6_blockwise, dequantize_fp6_blockwise),
    4: (quantize_int4_blockwise, dequantize_int4_blockwise),
}


class QuantizedParameter:

    def __init__(self, values, scales, shape: Tuple[int, ...], block_size: int,
                 dtype=jnp.bfloat16, q_bits: int = 8):
        self.values = values
        self.scales = scales
        self.shape = tuple(shape)
        self.block_size = block_size
        self.dtype = dtype
        self.q_bits = q_bits

    @staticmethod
    def quantize(w, config: QuantizationConfig = None) -> "QuantizedParameter":
        config = config or QuantizationConfig()
        if config.q_bits not in _FMTS:
            raise ValueError(f"q_bits must be one of {sorted(_FMTS)} "
                             f"(int8 / fp6-e3m2 / int4), got {config.q_bits}")
        quant, _ = _FMTS[config.q_bits]
        values, scales = quant(w, block_size=config.group_size)
        return QuantizedParameter(values, scales, w.shape, config.group_size,
                                  dtype=w.dtype, q_bits=config.q_bits)

    def dequantized(self):
        _, dequant = _FMTS[self.q_bits]
        return dequant(self.values, self.scales, self.shape,
                       self.block_size).astype(self.dtype)

    @property
    def nbytes(self) -> int:
        return int(self.values.size * self.values.dtype.itemsize
                   + self.scales.size * self.scales.dtype.itemsize)


# pytree registration so QuantizedParameter flows through jit/device_put
jax.tree_util.register_pytree_node(
    QuantizedParameter,
    lambda qp: ((qp.values, qp.scales),
                (qp.shape, qp.block_size, qp.dtype, qp.q_bits)),
    lambda aux, kids: QuantizedParameter(kids[0], kids[1], aux[0], aux[1],
                                         aux[2], aux[3]))
