"""Quantized parameter storage.

Reference: ``deepspeed/linear/quantization.py:18 QuantizedParameter`` — a
tensor subclass that stores FP6/FP8-quantized data and dequantizes on use.
TPU version: a small container of (packed values, fp32 scales) produced by
the blockwise Pallas/XLA quantizer (``ops/quantizer.py``), dequantized
inside jit where XLA fuses it into the consuming matmul. Formats: int8
(1 byte/weight), fp6 e3m2 (0.75 bytes/weight, the FP6-LLM point —
``ops/fp_quantizer/quantize.py:43``), int4 (0.5 bytes/weight).
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..ops.quantizer import (dequantize_int8_blockwise, quantize_int8_blockwise,
                             dequantize_int4_blockwise, quantize_int4_blockwise,
                             dequantize_fp6_blockwise, quantize_fp6_blockwise)
from .config import QuantizationConfig

_FMTS = {
    8: (quantize_int8_blockwise, dequantize_int8_blockwise),
    6: (quantize_fp6_blockwise, dequantize_fp6_blockwise),
    4: (quantize_int4_blockwise, dequantize_int4_blockwise),
}


class QuantizedParameter:
    """Packed (values, scales) container, optionally *shard-major*.

    ``shards == 1`` is the legacy flat layout: the whole tensor is flattened
    row-major and block-quantized as one stream. With ``shards == S`` and a
    ``shard_dim``, the tensor is first permuted so ``shard_dim`` leads, then
    split into S equal contiguous chunks along it, and each chunk is
    quantized *independently* (per-chunk tail padding, so no block ever
    crosses a shard boundary). values/scales stay flat 1-D with S
    equal-length segments — shardable as ``P("model")`` on dim 0, and each
    TP worker dequantizes its own segment locally with no neighbor data.
    """

    def __init__(self, values, scales, shape: Tuple[int, ...], block_size: int,
                 dtype=jnp.bfloat16, q_bits: int = 8,
                 shard_dim: "int | None" = None, shards: int = 1):
        self.values = values
        self.scales = scales
        self.shape = tuple(shape)
        self.block_size = block_size
        self.dtype = dtype
        self.q_bits = q_bits
        self.shard_dim = shard_dim
        self.shards = int(shards)

    @staticmethod
    def quantize(w, config: QuantizationConfig = None,
                 shard_dim: "int | None" = None,
                 shards: int = 1) -> "QuantizedParameter":
        config = config or QuantizationConfig()
        if config.q_bits not in _FMTS:
            raise ValueError(f"q_bits must be one of {sorted(_FMTS)} "
                             f"(int8 / fp6-e3m2 / int4), got {config.q_bits}")
        quant, _ = _FMTS[config.q_bits]
        if shards <= 1 or shard_dim is None:
            values, scales = quant(w, block_size=config.group_size)
            return QuantizedParameter(values, scales, w.shape, config.group_size,
                                      dtype=w.dtype, q_bits=config.q_bits)
        shard_dim = shard_dim % w.ndim
        if w.shape[shard_dim] % shards != 0:
            raise ValueError(
                f"shard_dim {shard_dim} of shape {w.shape} not divisible by "
                f"{shards} shards")
        perm = jnp.moveaxis(w, shard_dim, 0)
        rows = perm.shape[0] // shards
        vs, ss = [], []
        for i in range(shards):
            v, s = quant(perm[i * rows:(i + 1) * rows],
                         block_size=config.group_size)
            vs.append(v)
            ss.append(s)
        return QuantizedParameter(jnp.concatenate(vs), jnp.concatenate(ss),
                                  w.shape, config.group_size, dtype=w.dtype,
                                  q_bits=config.q_bits, shard_dim=shard_dim,
                                  shards=shards)

    def dequantized(self):
        _, dequant = _FMTS[self.q_bits]
        if self.shards <= 1 or self.shard_dim is None:
            return dequant(self.values, self.scales, self.shape,
                           self.block_size).astype(self.dtype)
        # Shard-major decode, vectorized over ALL shards at once. Every
        # per-shard segment is padded to whole blocks, so the concatenated
        # stream is itself a valid flat blockwise stream: decode it globally
        # (elementwise over dim-0-sharded blocks), then strip each shard's
        # tail pad with a slice on the NON-sharded dim. Never slice or
        # concatenate along the sharded dim itself — a per-chunk
        # slice+concat loop here made XLA's SPMD partitioner mispair
        # values with neighboring blocks' scales inside large jitted
        # graphs (wrong dequant by exactly a scale ratio).
        perm_shape = (self.shape[self.shard_dim], ) + tuple(
            d for i, d in enumerate(self.shape) if i != self.shard_dim)
        chunk_rows = perm_shape[0] // self.shards
        chunk_elems = chunk_rows
        for d in perm_shape[1:]:
            chunk_elems *= d
        total_blocks = self.scales.shape[0]
        elems_padded = total_blocks * self.block_size
        flat = dequant(self.values, self.scales, (elems_padded, ),
                       self.block_size)
        x = flat.reshape(self.shards, elems_padded // self.shards)
        x = x[:, :chunk_elems]
        perm = x.reshape((self.shards * chunk_rows, ) + perm_shape[1:])
        return jnp.moveaxis(perm, 0, self.shard_dim).astype(self.dtype)

    @property
    def nbytes(self) -> int:
        return int(self.values.size * self.values.dtype.itemsize
                   + self.scales.size * self.scales.dtype.itemsize)


# pytree registration so QuantizedParameter flows through jit/device_put
jax.tree_util.register_pytree_node(
    QuantizedParameter,
    lambda qp: ((qp.values, qp.scales),
                (qp.shape, qp.block_size, qp.dtype, qp.q_bits,
                 qp.shard_dim, qp.shards)),
    lambda aux, kids: QuantizedParameter(kids[0], kids[1], aux[0], aux[1],
                                         aux[2], aux[3], aux[4], aux[5]))
