"""Quantized parameter storage.

Reference: ``deepspeed/linear/quantization.py:18 QuantizedParameter`` — a
tensor subclass that stores FP6/FP8-quantized data and dequantizes on use.
TPU version: a small container of (int8 values, bf16 scales) produced by the
blockwise Pallas/XLA quantizer (``ops/quantizer.py``), dequantized inside
jit where XLA fuses it into the consuming matmul.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..ops.quantizer import dequantize_int8_blockwise, quantize_int8_blockwise
from .config import QuantizationConfig


class QuantizedParameter:

    def __init__(self, values, scales, shape: Tuple[int, ...], block_size: int,
                 dtype=jnp.bfloat16):
        self.values = values
        self.scales = scales
        self.shape = tuple(shape)
        self.block_size = block_size
        self.dtype = dtype

    @staticmethod
    def quantize(w, config: QuantizationConfig = None) -> "QuantizedParameter":
        config = config or QuantizationConfig()
        assert config.q_bits == 8, "int8 is the supported quantized storage"
        values, scales = quantize_int8_blockwise(w, block_size=config.group_size)
        return QuantizedParameter(values, scales, w.shape, config.group_size,
                                  dtype=w.dtype)

    def dequantized(self):
        return dequantize_int8_blockwise(self.values, self.scales, self.shape,
                                         self.block_size).astype(self.dtype)

    @property
    def nbytes(self) -> int:
        return int(self.values.size + self.scales.size * self.scales.dtype.itemsize)


# pytree registration so QuantizedParameter flows through jit/device_put
jax.tree_util.register_pytree_node(
    QuantizedParameter,
    lambda qp: ((qp.values, qp.scales), (qp.shape, qp.block_size, qp.dtype)),
    lambda aux, kids: QuantizedParameter(kids[0], kids[1], aux[0], aux[1], aux[2]))
