"""Config-driven compression (reference ``compression/compress.py``).

``init_compression(apply_fn, params, ds_config)`` returns a wrapped apply_fn
that fake-quantizes / masks the matching parameter leaves inside the jitted
forward (the functional analog of the reference's module replacement with
``LinearLayer_Compress``), plus the transform object for inspection.
``redundancy_clean`` applies the masks/quantization permanently to a param
tree (the reference's post-training cleanup that materializes pruning).

Config schema = the reference's ``compression_training`` block:
  {"weight_quantization": {"shared_parameters": {...}, "different_groups":
     {"wq1": {"params": {"target_bits": 8}, "modules": ["attention.*"]}}},
   "sparse_pruning": {...}, "row_pruning": {...}, "head_pruning": {...},
   "channel_pruning": {...}, "activation_quantization": {...}}
"""

import fnmatch
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from ..utils.logging import logger
from . import basic_layer as B

_TECHNIQUES = ("weight_quantization", "activation_quantization", "sparse_pruning",
               "row_pruning", "head_pruning", "channel_pruning", "layer_reduction")


def check_deepspeed_config(config) -> dict:
    """Reference compress.py:20."""
    if hasattr(config, "_param_dict"):
        config = config._param_dict
    if not isinstance(config, dict):
        raise ValueError("expected a ds_config dict")
    return config.get("compression_training", {})


class _Rule:

    def __init__(self, technique: str, group: str, patterns: List[str], params: dict,
                 offset: int = 0, offset_end: Optional[int] = None):
        self.technique = technique
        self.group = group
        self.patterns = patterns
        self.params = params
        self.offset = offset
        self.offset_end = offset_end

    def matches(self, path: str) -> bool:
        return any(fnmatch.fnmatch(path, pat) or fnmatch.fnmatch(path, f"*{pat}*")
                   for pat in self.patterns)

    def apply(self, w):
        p = self.params
        if self.technique == "weight_quantization":
            return B.quantize_weight_ste(w, bits=p.get("target_bits", 8),
                                         symmetric=p.get("symmetric", True))
        if self.technique == "sparse_pruning":
            return B.prune_magnitude(w, p.get("dense_ratio_complement",
                                              1.0 - p.get("dense_ratio", 0.5)))
        if self.technique == "row_pruning":
            return B.prune_rows(w, 1.0 - p.get("dense_ratio", 0.5))
        if self.technique == "channel_pruning":
            return B.prune_channels(w, 1.0 - p.get("dense_ratio", 0.5))
        if self.technique == "head_pruning":
            return B.prune_heads(w, 1.0 - p.get("dense_ratio", 0.5),
                                 num_heads=p.get("num_heads", 1))
        return w


class CompressionTransform:
    """Collected rules; applies matching techniques to a param tree."""

    def __init__(self, rules: List[_Rule]):
        self.rules = rules

    @staticmethod
    def from_config(ds_config) -> "CompressionTransform":
        cc = check_deepspeed_config(ds_config)
        rules = []
        for tech in _TECHNIQUES:
            block = cc.get(tech)
            if tech == "layer_reduction":
                if block and block.get("enabled", False):
                    # not a per-forward transform: depth reduction happens at
                    # init via student_initialization — surface that instead
                    # of silently accepting the key
                    logger.warning(
                        "layer_reduction is applied by "
                        "compression.student_initialization(student_params, "
                        "teacher_params, ds_config) at model build time, not "
                        "by init_compression's forward transform")
                continue
            if not block:
                continue
            shared = block.get("shared_parameters", {})
            if not shared.get("enabled", False):
                continue
            for group, spec in block.get("different_groups", {}).items():
                rules.append(_Rule(
                    tech, group,
                    spec.get("modules", ["*"]),
                    spec.get("params", {}),
                    offset=shared.get("schedule_offset", 0),
                    offset_end=shared.get("schedule_offset_end")))
        return CompressionTransform(rules)

    def active_rules(self, step: Optional[int]) -> List[_Rule]:
        if step is None:
            return self.rules
        return [r for r in self.rules
                if step >= r.offset and (r.offset_end is None or step <= r.offset_end)]

    def __call__(self, params, step: Optional[int] = None):
        rules = self.active_rules(step)
        if not rules:
            return params
        flat = _flatten_with_paths(params)
        out = {}
        for path, leaf in flat.items():
            for r in rules:
                if hasattr(leaf, "ndim") and r.matches(path):
                    leaf = r.apply(leaf)
            out[path] = leaf
        return _unflatten_like(out, params)


def _flatten_with_paths(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_with_paths(v, f"{prefix}{k}." if prefix or True else k))
    else:
        out[prefix[:-1]] = tree
    return out


def _unflatten_like(flat: Dict[str, Any], like):
    def rebuild(tree, prefix=""):
        if isinstance(tree, dict):
            return {k: rebuild(v, f"{prefix}{k}.") for k, v in tree.items()}
        return flat[prefix[:-1]]
    return rebuild(like)


def init_compression(apply_fn: Callable, ds_config, mpu=None,
                     step_fn: Optional[Callable[[], int]] = None
                     ) -> Tuple[Callable, CompressionTransform]:
    """Reference compress.py:100 init_compression — returns
    (compressed_apply_fn, transform). The wrapped fn fake-compresses matching
    params on every forward (QAT); jit-safe."""
    transform = CompressionTransform.from_config(ds_config)
    if not transform.rules:
        logger.warning("init_compression: no enabled compression techniques in config")
        return apply_fn, transform

    def compressed_apply(params, *args, **kwargs):
        step = step_fn() if step_fn is not None else None
        return apply_fn(transform(params, step), *args, **kwargs)

    return compressed_apply, transform


def _resolve_path(tree: dict, dotted: str):
    """Walk 'a.b.c' into a nested dict; returns (parent, leaf_key) or None."""
    parts = [p for p in dotted.split(".") if p]
    node, parent, key = tree, None, None
    for p in parts:
        if not isinstance(node, dict) or p not in node:
            return None
        parent, key = node, p
        node = node[p]
    return parent, key


def student_initialization(student_params, teacher_params, ds_config):
    """Depth-reduction (distillation) student init — reference
    ``compression/compress.py:192 student_initialization``: copy the teacher
    layers listed in ``teacher_layer`` onto the student's (fewer) layers, and
    copy ``other_module_name`` subtrees (embeddings, pooler, lm head)
    verbatim. Returns a NEW student param tree.

    Config block (same keys as the reference)::

        "compression_training": {"layer_reduction": {
            "enabled": true,
            "keep_number_layer": 2,
            "module_name_prefix": "model",   # subtree holding layers_{i}
            "teacher_layer": [1, 3],          # teacher depth indices to keep
            "other_module_name": ["model.embed_tokens", "model.norm",
                                  "model.lm_head"]}}

    The reference addresses torch modules ``{prefix}.{i}.``; flax layer
    children are ``layers_{i}`` under the prefix subtree (both spellings of
    the prefix — with or without a trailing ``.layers`` — are accepted).
    """
    cc = check_deepspeed_config(ds_config).get("layer_reduction", {})
    if not cc or not cc.get("enabled", False):
        return student_params
    keep = int(cc["keep_number_layer"])
    teacher_layer = list(cc["teacher_layer"])
    if len(teacher_layer) != keep:
        raise ValueError(f"layer_reduction: keep_number_layer={keep} but "
                         f"teacher_layer has {len(teacher_layer)} entries")
    prefix = cc.get("module_name_prefix", "model")
    if prefix.endswith(".layers"):  # torch spelling of the flax layers_{i}
        prefix = prefix[:-len(".layers")]

    student = jax.tree_util.tree_map(lambda x: x, student_params)  # copy tree

    def _subtree(tree, dotted):
        hit = _resolve_path(tree, dotted)
        if hit is None:
            raise KeyError(f"layer_reduction: '{dotted}' not found in params "
                           f"(top-level keys: {list(tree)})")
        parent, key = hit
        return parent[key], parent, key

    t_sub, _, _ = _subtree(teacher_params, prefix)
    s_sub, _, _ = _subtree(student, prefix)
    for j, t_idx in enumerate(teacher_layer):
        t_name, s_name = f"layers_{t_idx}", f"layers_{j}"
        if t_name not in t_sub:
            raise KeyError(f"layer_reduction: teacher has no '{prefix}.{t_name}' "
                           "(scan_layers trees are stacked — unstack first)")
        if s_name not in s_sub:
            raise KeyError(f"layer_reduction: student has no '{prefix}.{s_name}' "
                           f"(expected {keep} layers)")
        s_sub[s_name] = jax.tree_util.tree_map(lambda x: x, t_sub[t_name])
    for name in cc.get("other_module_name", []):
        src, _, _ = _subtree(teacher_params, name)
        _, parent, key = _subtree(student, name)
        parent[key] = jax.tree_util.tree_map(lambda x: x, src)
    return student


def redundancy_clean(params, ds_config, mpu=None):
    """Reference compress.py:148 — materialize compression into the weights
    (post-QAT export): returns a new param tree with masks/quant applied."""
    transform = CompressionTransform.from_config(ds_config)
    return jax.tree_util.tree_map(lambda x: x, transform(params, step=None))
