from .compress import (init_compression, redundancy_clean, CompressionTransform,
                       student_initialization)
from .basic_layer import (quantize_weight_ste, quantize_activation, prune_magnitude,
                          prune_rows, prune_heads, prune_channels)
from .scheduler import CompressionScheduler
