"""Compression schedule gating (reference ``compression/scheduler.py``:
techniques activate at schedule_offset steps)."""

from typing import Dict, List


class CompressionScheduler:

    def __init__(self, groups: List[dict]):
        """groups: [{name, offset, offset_end}]"""
        self.groups = groups

    def active(self, step: int) -> Dict[str, bool]:
        out = {}
        for g in self.groups:
            start = g.get("schedule_offset", 0)
            end = g.get("schedule_offset_end", None)
            out[g["name"]] = step >= start and (end is None or step <= end)
        return out
