"""Compression primitives — quantization-aware training + pruning, functional.

Reference: ``deepspeed/compression/basic_layer.py`` (LinearLayer_Compress &
friends: wrapper modules that fake-quantize/mask weights in forward) and
``utils.py`` (TopKBinarizer, SymQuantizer...). The torch design wraps
modules; the TPU design is pure functions applied to param leaves inside the
jitted loss — straight-through estimators (STE) via
``x + stop_gradient(q(x) - x)`` so the compression is differentiable-through
and fuses into the XLA step (no wrapper-module overhead).
"""

from typing import Optional

import jax
import jax.numpy as jnp


def _ste(x, qx):
    """Straight-through: forward sees qx, gradient flows to x."""
    return x + jax.lax.stop_gradient(qx - x)


def quantize_weight_ste(w, bits: int = 8, symmetric: bool = True,
                        per_channel: bool = True):
    """Fake-quantize weights for QAT (reference SymQuantizer/AsymQuantizer in
    compression/utils.py; LinearLayer_Compress weight path)."""
    axis = tuple(range(w.ndim - 1)) if per_channel and w.ndim >= 2 else None
    if symmetric:
        qmax = 2.0**(bits - 1) - 1
        scale = jnp.max(jnp.abs(w), axis=axis, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax) * scale
    else:
        qmax = 2.0**bits - 1
        lo = jnp.min(w, axis=axis, keepdims=True)
        hi = jnp.max(w, axis=axis, keepdims=True)
        scale = jnp.maximum((hi - lo) / qmax, 1e-8)
        q = (jnp.clip(jnp.round((w - lo) / scale), 0, qmax)) * scale + lo
    return _ste(w, q)


def quantize_activation(x, bits: int = 8, symmetric: bool = True):
    """Activation fake-quant (reference activation_quantization; dynamic
    range per tensor)."""
    return quantize_weight_ste(x, bits=bits, symmetric=symmetric, per_channel=False)


def prune_magnitude(w, ratio: float, method: str = "l1"):
    """Unstructured sparse pruning mask by |w| (reference sparse_pruning
    method l1/topk: keep the largest (1-ratio) fraction)."""
    if ratio <= 0:
        return w
    k = int(w.size * (1.0 - ratio))
    if k <= 0:
        return jnp.zeros_like(w)
    flat = jnp.abs(w).reshape(-1)
    thresh = jax.lax.top_k(flat, k)[0][-1]
    mask = (jnp.abs(w) >= thresh).astype(w.dtype)
    return _ste(w, w * mask)


def prune_rows(w, ratio: float):
    """Structured row pruning (reference row_pruning): zero the lowest-L1
    output rows of a [in, out] kernel."""
    if ratio <= 0 or w.ndim < 2:
        return w
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(w.ndim - 1)))  # per output col
    k = max(1, int(norms.size * (1.0 - ratio)))
    thresh = jax.lax.top_k(norms, k)[0][-1]
    mask = (norms >= thresh).astype(w.dtype)
    return _ste(w, w * mask)


def prune_channels(w, ratio: float):
    """Structured input-channel pruning (reference channel_pruning)."""
    if ratio <= 0 or w.ndim < 2:
        return w
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(1, w.ndim)))  # per input row
    k = max(1, int(norms.size * (1.0 - ratio)))
    thresh = jax.lax.top_k(norms, k)[0][-1]
    mask = (norms >= thresh).astype(w.dtype).reshape((-1, ) + (1, ) * (w.ndim - 1))
    return _ste(w, w * mask)


def prune_heads(w, ratio: float, num_heads: int):
    """Head pruning for attention output projections (reference head_pruning:
    mask whole heads of a [heads*dim, out] kernel)."""
    if ratio <= 0 or w.ndim != 2 or w.shape[0] % num_heads != 0:
        return w
    head_dim = w.shape[0] // num_heads
    per_head = jnp.sum(jnp.abs(w.reshape(num_heads, head_dim, -1)), axis=(1, 2))
    k = max(1, int(num_heads * (1.0 - ratio)))
    thresh = jax.lax.top_k(per_head, k)[0][-1]
    mask = jnp.repeat((per_head >= thresh).astype(w.dtype), head_dim)[:, None]
    return _ste(w, w * mask)
