"""One dtype-name resolver for every config surface (the reference scatters
``DtypeEnum``/torch-dtype parsing across engines; here one table keeps the
accepted spellings from drifting between the training engine, the v1
inference engine and the KV cache config)."""

from typing import Optional

import jax.numpy as jnp

_DTYPES = {
    "float32": jnp.float32, "fp32": jnp.float32, "float": jnp.float32,
    "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "int8": jnp.int8,
}


def resolve_dtype(name, default=None) -> Optional[type]:
    """'bf16' / 'torch.float16' / jnp dtype -> jnp dtype; ``default`` when
    name is falsy; raises on an unknown spelling (silent fallbacks hide
    config typos)."""
    if not name:
        return default
    if name in _DTYPES.values():
        return name
    key = str(name).replace("torch.", "").lower()
    if key not in _DTYPES:
        raise ValueError(f"unknown dtype {name!r}; expected one of "
                         f"{sorted(set(_DTYPES))}")
    return _DTYPES[key]
