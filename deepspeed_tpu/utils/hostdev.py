"""Forced host-device environments for multi-device tests and benches.

JAX pins its backend at first import, so a process that wants N virtual
CPU devices (``--xla_force_host_platform_device_count``) must set the
environment BEFORE the interpreter imports jax — i.e. in a subprocess (or
the conftest re-exec). Every mesh test / TP bench used to hand-roll the
same four env edits; this is the one canonical builder.
"""

import os
from typing import Dict, Optional

_FORCE_FLAG = "--xla_force_host_platform_device_count"


def force_host_devices_env(n: int,
                           base_env: Optional[Dict[str, str]] = None,
                           extra: Optional[Dict[str, str]] = None
                           ) -> Dict[str, str]:
    """Subprocess environment exposing ``n`` virtual CPU devices.

    Scrubs the TPU (axon) plugin trigger, pins ``JAX_PLATFORMS=cpu``,
    forces the host device count (replacing any prior force flag in
    ``XLA_FLAGS``), and disables x64 — the same recipe tests/conftest.py
    applies on its re-exec. ``base_env`` defaults to ``os.environ``;
    ``extra`` entries are merged last (callers add PYTHONPATH etc.).
    """
    env = dict(os.environ if base_env is None else base_env)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disables axon plugin registration
    env["JAX_PLATFORMS"] = "cpu"
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith(_FORCE_FLAG)]
    env["XLA_FLAGS"] = " ".join([f"{_FORCE_FLAG}={int(n)}"] + kept)
    env["JAX_ENABLE_X64"] = "0"
    if extra:
        env.update(extra)
    return env
