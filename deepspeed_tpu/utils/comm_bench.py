"""Collective micro-benchmark (reference ``bin/ds_bench`` →
DeepSpeedExamples communication benchmarks): sweeps message sizes over a
chosen collective on the live mesh and prints latency + algorithm/bus
bandwidth using the same busbw conventions as the reference CommsLogger
(allreduce busbw = 2(n-1)/n × size/t)."""

import argparse
import time
from typing import List

import jax
import jax.numpy as jnp
import numpy as np


def _bw_factor(op: str, n: int) -> float:
    if op == "all_reduce":
        return 2.0 * (n - 1) / n
    if op in ("all_gather", "reduce_scatter"):
        return (n - 1) / n
    return 1.0


def run_sweep(op: str = "all_reduce", sizes: List[int] = None, trials: int = 20,
              dtype=jnp.bfloat16, group: str = "data") -> List[dict]:
    from .. import comm as dist
    if not dist.is_initialized():
        dist.init_distributed()
    ctx = dist.get_mesh_context()
    n = ctx.axis_size(group)
    sizes = sizes or [2**p for p in range(12, 27, 2)]  # 4KB..128MB elements/2
    results = []
    fns = {
        "all_reduce": lambda x: dist.all_reduce(x, group=group),
        "all_gather": lambda x: dist.all_gather(x, group=group),
        "reduce_scatter": lambda x: dist.reduce_scatter(x, group=group),
        "all_to_all": lambda x: dist.all_to_all(x, group=group),
    }
    fn = fns[op]
    for size in sizes:
        x = jnp.ones((size, ), dtype=dtype)
        out = fn(x)  # compile + warmup
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(trials):
            out = fn(x)
        jax.block_until_ready(out)
        # axon-relay quirk: force a host readback to close the timing region
        float(np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        dt = (time.perf_counter() - t0) / trials
        nbytes = size * jnp.dtype(dtype).itemsize
        busbw = _bw_factor(op, n) * nbytes / dt / 1e9
        results.append({"op": op, "size_bytes": nbytes, "latency_us": dt * 1e6,
                        "busbw_GBps": busbw, "world": n})
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(description="deepspeed_tpu comm sweep (ds_bench)")
    ap.add_argument("--op", default="all_reduce",
                    choices=["all_reduce", "all_gather", "reduce_scatter", "all_to_all"])
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--group", default="data")
    ap.add_argument("--maxsize", type=int, default=26, help="log2 max element count")
    args = ap.parse_args(argv)
    sizes = [2**p for p in range(12, args.maxsize + 1, 2)]
    rows = run_sweep(args.op, sizes, args.trials, group=args.group)
    print(f"{'size':>12} {'latency(us)':>12} {'busbw(GB/s)':>12}")
    for r in rows:
        print(f"{r['size_bytes']:>12} {r['latency_us']:>12.1f} {r['busbw_GBps']:>12.2f}")
    return 0


if __name__ == "__main__":
    main()
