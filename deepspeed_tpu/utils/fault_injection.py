"""Deterministic fault-injection harness.

The resilience layer (crash-consistent checkpoints, preemption autosave,
anomaly rollback, comm-init retry) is only trustworthy if every failure path
is exercised by tests — so the production code carries explicit, normally
inert fault *sites*, and this module decides when a site fires.

Faults are configured from the ``resilience.fault_injection`` config block or
the ``DS_FAULT_INJECT`` env var; firing is purely occurrence-counted (the
``nth`` visit to a site, for ``times`` visits), never random — a configured
fault plan replays identically on every run. Corruption *content* uses a
seeded RNG for the same reason.

Registered sites (the code that hosts them decides the fault's meaning):

- ``checkpoint.torn_write``   — commit() tears the checkpoint (truncated
  entry, no manifest/commit marker) and reports failure: a crash mid-write.
- ``checkpoint.corrupt``      — after a successful commit, flip bytes in one
  manifest-covered entry: silent storage corruption the manifest must catch.
- ``train.sigterm``           — deliver SIGTERM to this process mid-step:
  a preemption notice arriving while the step pipeline is in flight.
- ``train.nan_grads``         — poison the micro-batch with NaNs so the
  backward produces non-finite gradients: a NaN episode.
- ``comm.init_timeout``       — the distributed rendezvous attempt raises
  TimeoutError: a slow-to-arrive host.
- ``serve.tick_error``        — one serving-scheduler tick raises: a
  transient device/dispatch failure the tick boundary must retry.
- ``serve.tick_hang``         — one serving-scheduler tick stalls for
  ``args["seconds"]``: a wedged dispatch the watchdog must surface.
- ``serve.request_poison``    — any engine dispatch whose batch contains
  ``args["uid"]`` raises: a request whose shape/content reliably breaks
  the forward, which quarantine must isolate from the wave.
- ``serve.slow_consumer``     — a streamed token delivery behaves as if
  the consumer stopped draining: the bounded stream queue must cancel.
- ``serve.crash``             — the serving daemon dies mid-tick. With
  ``args["mode"] == "exit"`` the process hard-exits (``os._exit``) so the
  supervisor's relaunch path is exercised; the default "drop" mode kills
  just the scheduler loop (a BaseException that skips tick retry AND
  quarantine) so in-process tests replay the journal over the same engine.
- ``journal.torn_write``      — a journal append writes only half its
  frame: a crash mid-write the recovery scan must resync past.
- ``journal.corrupt_record``  — a journal append lands with a flipped
  payload byte: silent bit-rot the CRC must quarantine per-record.
- ``disagg.transfer_stall``   — a prefill→decode KV handoff transfer
  batch wedges (never becomes ready): the disagg watchdog must degrade
  the request to in-group prefill instead of stalling admission.
- ``router.replica_crash``    — the fleet router SIGKILLs one of its own
  replicas at probe time: a daemon death the crash-migration path must
  absorb (journal drained from disk, peer replays mid-stream).
- ``router.probe_timeout``    — one replica health probe behaves as timed
  out: consecutive timeouts must quarantine the replica and a later
  healthy probe must re-admit it.
- ``router.migrate_stall``    — a journal export/import leg of a live
  migration wedges past the stall budget: the router must fall back to
  error-finishing the affected requests with Retry-After instead of
  hanging the fleet.
- ``router.split_brain_uid``  — a journal import collides with a uid the
  target replica already owns (two replicas claiming one request): the
  import must refuse exactly that entry and the router must surface the
  conflict instead of double-serving the stream.

Env syntax: ``DS_FAULT_INJECT="site[@nth][*times][;site2...]"`` e.g.
``DS_FAULT_INJECT="checkpoint.torn_write@2;train.nan_grads@5*3"``.
"""

import os
from typing import Any, Dict, List, Optional

import numpy as np

from .logging import logger

KNOWN_SITES = (
    "checkpoint.torn_write",
    "checkpoint.corrupt",
    "train.sigterm",
    "train.nan_grads",
    "comm.init_timeout",
    "serve.tick_error",
    "serve.tick_hang",
    "serve.request_poison",
    "serve.slow_consumer",
    "serve.crash",
    "journal.torn_write",
    "journal.corrupt_record",
    "disagg.transfer_stall",
    "router.replica_crash",
    "router.probe_timeout",
    "router.migrate_stall",
    "router.split_brain_uid",
)


class InjectedFault(RuntimeError):
    """Raised by sites whose fault is an exception (e.g. comm timeouts)."""


class FaultInjector:
    """Occurrence-counted fault plan. One global instance drives the whole
    process (fault sites live in several layers); tests configure/reset it
    around each scenario."""

    def __init__(self):
        self._plans: Dict[str, List[dict]] = {}
        self._visits: Dict[str, int] = {}
        self._fired: List[str] = []
        self.seed = 0

    # -- configuration ---------------------------------------------------

    def configure(self, spec: Optional[Dict[str, Any]]):
        """Install a fault plan from a ``resilience.fault_injection``-shaped
        dict: ``{"seed": 0, "faults": [{"site": ..., "nth": 1, "times": 1,
        "args": {...}}]}``. Replaces any existing plan and resets counters."""
        self.reset()
        if not spec:
            return
        if hasattr(spec, "model_dump"):  # pydantic ConfigModel
            spec = spec.model_dump()
        if not spec.get("enabled", True):
            return
        self.seed = int(spec.get("seed", 0))
        for f in spec.get("faults", []):
            site = f["site"]
            if site not in KNOWN_SITES:
                raise ValueError(
                    f"unknown fault site {site!r}; known: {KNOWN_SITES}")
            self._plans.setdefault(site, []).append({
                "nth": int(f.get("nth", 1)),
                "times": int(f.get("times", 1)),
                "args": dict(f.get("args", {})),
            })

    def configure_env(self, text: Optional[str] = None):
        """Parse ``DS_FAULT_INJECT`` (see module docstring)."""
        text = text if text is not None else os.environ.get("DS_FAULT_INJECT", "")
        faults = []
        for part in filter(None, (p.strip() for p in text.split(";"))):
            site, nth, times = part, 1, 1
            if "*" in site:
                site, t = site.rsplit("*", 1)
                times = int(t)
            if "@" in site:
                site, n = site.rsplit("@", 1)
                nth = int(n)
            faults.append({"site": site, "nth": nth, "times": times})
        if faults:
            self.configure({"faults": faults})

    def reset(self):
        self._plans.clear()
        self._visits.clear()
        self._fired.clear()
        self.seed = 0

    # -- firing ----------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return bool(self._plans)

    def fire(self, site: str, **ctx) -> Optional[dict]:
        """Record a visit to ``site``; return the fault's ``args`` dict if a
        configured fault covers this visit, else None. Sites without a plan
        are not counted (zero steady-state overhead)."""
        plans = self._plans.get(site)
        if not plans:
            return None
        n = self._visits.get(site, 0) + 1
        self._visits[site] = n
        for p in plans:
            if p["nth"] <= n < p["nth"] + p["times"]:
                self._fired.append(f"{site}#{n}")
                logger.warning(f"[fault-injection] firing {site} (visit {n})")
                return p["args"]
        return None

    @property
    def fired(self) -> List[str]:
        """Every fault fired so far (``site#visit``), for test assertions."""
        return list(self._fired)


_INJECTOR = FaultInjector()
_ENV_LOADED = False


def get_fault_injector() -> FaultInjector:
    """The process-global injector; lazily absorbs ``DS_FAULT_INJECT`` once."""
    global _ENV_LOADED
    if not _ENV_LOADED:
        _ENV_LOADED = True
        try:
            _INJECTOR.configure_env()
        except (ValueError, KeyError) as e:
            logger.warning(f"DS_FAULT_INJECT ignored (parse error: {e})")
    return _INJECTOR


# ---------------------------------------------------------------------------
# fault actions — the concrete damage a firing site inflicts
# ---------------------------------------------------------------------------


def tear_checkpoint_dir(path: str, truncate_to: int = 16) -> Optional[str]:
    """Simulate a crash mid-write: truncate the largest file under ``path``
    (a half-flushed array shard). Returns the torn file's path."""
    victim, size = None, truncate_to
    for root, _, files in os.walk(path):
        for f in files:
            p = os.path.join(root, f)
            try:
                s = os.path.getsize(p)
            except OSError:
                continue
            if s > size:
                victim, size = p, s
    if victim is not None:
        with open(victim, "r+b") as fh:
            fh.truncate(truncate_to)
        logger.warning(f"[fault-injection] tore {victim} to {truncate_to}B")
    return victim


def corrupt_file_in(path: str, seed: int = 0, skip=("ds_manifest.json", "ds_commit")) -> Optional[str]:
    """Silent bit-rot: deterministically flip bytes mid-file in the largest
    entry under ``path`` not in ``skip`` — the manifest checksum must catch
    it. Returns the corrupted file's path."""
    victim, size = None, 0
    for root, _, files in os.walk(path):
        for f in files:
            if f in skip:
                continue
            p = os.path.join(root, f)
            try:
                s = os.path.getsize(p)
            except OSError:
                continue
            if s > size:
                victim, size = p, s
    if victim is not None:
        rng = np.random.default_rng(seed)
        n = min(64, max(1, size // 4))
        off = size // 2
        with open(victim, "r+b") as fh:
            fh.seek(off)
            orig = fh.read(n)
            garbage = bytes(rng.integers(0, 256, len(orig), dtype=np.uint8))
            if garbage == orig:  # vanishingly unlikely; force a difference
                garbage = bytes((orig[0] ^ 0xFF, )) + garbage[1:]
            fh.seek(off)
            fh.write(garbage)
        logger.warning(f"[fault-injection] corrupted {len(orig)}B in {victim}")
    return victim
