"""Bounded retry with exponential backoff.

Storage writes (checkpoint manifests, `latest` pointers, retention GC) and
the distributed rendezvous both talk to systems that fail transiently —
NFS/GCS hiccups, a coordinator that isn't up yet. Every resilience-layer
caller routes through this one helper so the retry budget is bounded and
uniform: no unbounded spin, no bare ``while True`` around IO.
"""

import time
from typing import Callable, Optional, Tuple, Type

from .logging import logger


class RetriesExhausted(RuntimeError):
    """All attempts failed; ``__cause__`` is the last underlying error."""


def retry_with_backoff(fn: Callable,
                       retries: int = 3,
                       base_delay: float = 0.05,
                       max_delay: float = 2.0,
                       exceptions: Tuple[Type[BaseException], ...] = (OSError, ),
                       desc: Optional[str] = None,
                       sleep: Callable[[float], None] = time.sleep):
    """Call ``fn()`` up to ``retries`` times, sleeping ``base_delay * 2**i``
    (capped at ``max_delay``) between attempts. Non-matching exceptions
    propagate immediately; exhausting the budget raises
    :class:`RetriesExhausted` chained to the last error."""
    retries = max(1, int(retries))
    last = None
    for attempt in range(retries):
        try:
            return fn()
        except exceptions as e:  # noqa: PERF203 — the retry IS the point
            last = e
            if attempt + 1 < retries:
                delay = min(max_delay, base_delay * (2 ** attempt))
                logger.warning(
                    f"{desc or getattr(fn, '__name__', 'op')}: attempt "
                    f"{attempt + 1}/{retries} failed ({e}); retrying in "
                    f"{delay:.2f}s")
                sleep(delay)
    raise RetriesExhausted(
        f"{desc or getattr(fn, '__name__', 'op')} failed after {retries} "
        f"attempts: {last}") from last
