"""Bounded retry with exponential backoff.

Storage writes (checkpoint manifests, `latest` pointers, retention GC) and
the distributed rendezvous both talk to systems that fail transiently —
NFS/GCS hiccups, a coordinator that isn't up yet. Every resilience-layer
caller routes through this one helper so the retry budget is bounded and
uniform: no unbounded spin, no bare ``while True`` around IO.

Jitter: with a whole replica fleet retrying against the same peers (the
router's failover submits, the supervisor's relaunch backoff), plain
exponential backoff synchronizes every client onto the same retry
instants — a thundering-herd storm exactly when the surviving replica is
most loaded. ``jitter="full"`` draws each delay uniformly from
``[0, min(max_delay, base * 2**i)]`` (the AWS "full jitter" policy), which
decorrelates the fleet while keeping the same expected backoff envelope.
The draw comes from a caller-suppliable RNG so tests replay the exact
delay sequence from a seed.
"""

import random
import time
from typing import Callable, Optional, Tuple, Type

from .logging import logger


class RetriesExhausted(RuntimeError):
    """All attempts failed; ``__cause__`` is the last underlying error."""


def backoff_delay(attempt: int,
                  base_delay: float = 0.05,
                  max_delay: float = 2.0,
                  jitter: str = "none",
                  rng: Optional[random.Random] = None) -> float:
    """Delay before retry ``attempt`` (0-based): ``base * 2**attempt``
    capped at ``max_delay``; with ``jitter="full"`` a uniform draw from
    ``[0, cap]``. Deterministic when ``rng`` is seeded."""
    if jitter not in ("none", "full"):
        raise ValueError(f"jitter must be 'none' or 'full', got {jitter!r}")
    cap = min(max_delay, base_delay * (2 ** attempt))
    if jitter == "none":
        return cap
    return (rng or random).uniform(0.0, cap)


def retry_with_backoff(fn: Callable,
                       retries: int = 3,
                       base_delay: float = 0.05,
                       max_delay: float = 2.0,
                       exceptions: Tuple[Type[BaseException], ...] = (OSError, ),
                       desc: Optional[str] = None,
                       sleep: Callable[[float], None] = time.sleep,
                       jitter: str = "none",
                       rng: Optional[random.Random] = None):
    """Call ``fn()`` up to ``retries`` times, sleeping ``base_delay * 2**i``
    (capped at ``max_delay``, uniformly jittered down under
    ``jitter="full"``) between attempts. Non-matching exceptions propagate
    immediately; exhausting the budget raises :class:`RetriesExhausted`
    chained to the last error."""
    retries = max(1, int(retries))
    last = None
    for attempt in range(retries):
        try:
            return fn()
        except exceptions as e:  # noqa: PERF203 — the retry IS the point
            last = e
            if attempt + 1 < retries:
                delay = backoff_delay(attempt, base_delay, max_delay,
                                      jitter=jitter, rng=rng)
                logger.warning(
                    f"{desc or getattr(fn, '__name__', 'op')}: attempt "
                    f"{attempt + 1}/{retries} failed ({e}); retrying in "
                    f"{delay:.2f}s")
                sleep(delay)
    raise RetriesExhausted(
        f"{desc or getattr(fn, '__name__', 'op')} failed after {retries} "
        f"attempts: {last}") from last
