"""Wall-clock and throughput timers.

TPU-native analog of the reference timers (``deepspeed/utils/timer.py``):
``SynchronizedWallClockTimer`` (reference :44) used CUDA events; here a
"synchronized" read calls ``jax.block_until_ready`` on a token the caller
passes (or ``jax.effects_barrier``) before reading the host clock, since XLA
dispatch is async. ``ThroughputTimer`` (reference :199) is host arithmetic and
ports directly.
"""

import time
from collections import OrderedDict

from .logging import logger, log_dist

try:
    import psutil
    _HAS_PSUTIL = True
except ImportError:  # pragma: no cover
    _HAS_PSUTIL = False

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
BACKWARD_INNER_MICRO_TIMER = "bwd_inner_microstep"
BACKWARD_INNER_GLOBAL_TIMER = "bwd_inner"
BACKWARD_REDUCE_MICRO_TIMER = "bwd_allreduce_microstep"
BACKWARD_REDUCE_GLOBAL_TIMER = "bwd_allreduce"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"

TIME_EPSILON = 1e-6


def _sync():
    """Drain outstanding device work so host wall-clock brackets device time."""
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Group of named timers; synchronized reads drain the device queue."""

    class Timer:

        def __init__(self, name):
            self.name_ = name
            self.started_ = False
            self.elapsed_ = 0.0
            self.start_time = time.time()
            self.records = []

        def start(self):
            assert not self.started_, f"{self.name_} timer has already been started"
            _sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False, record=False):
            assert self.started_, "timer is not started"
            _sync()
            elapsed = time.time() - self.start_time
            if reset:
                self.elapsed_ = elapsed
            else:
                self.elapsed_ += elapsed
            if record:
                self.records.append(self.elapsed_)
            self.started_ = False

        def reset(self):
            self.started_ = False
            self.elapsed_ = 0.0
            self.records = []

        def elapsed(self, reset=True):
            started = self.started_
            if self.started_:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed_

        def mean(self):
            if not self.records:
                return 0.0
            return sum(self.records) / len(self.records)

    def __init__(self):
        self.timers = OrderedDict()

    def get_timers(self):
        return self.timers

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        if not _HAS_PSUTIL:
            return ""
        vm = psutil.virtual_memory()
        return f"host mem used: {vm.used / (1024**3):.2f} GB ({vm.percent}%)"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        means = {}
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].mean() * 1000.0 / normalizer
                means[name] = round(elapsed_time, 2)
        return means


class NoopTimer:

    class Timer:

        def start(self):
            ...

        def reset(self):
            ...

        def stop(self, **kwargs):
            ...

        def elapsed(self, **kwargs):
            return 0

        def mean(self):
            return 0

    def __init__(self):
        self.timer = self.Timer()

    def __call__(self, name):
        return self.timer

    def get_timers(self):
        return {}

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        ...

    def get_mean(self, names, normalizer=1.0, reset=True):
        ...


class ThroughputTimer:
    """Samples/sec + TFLOPs accounting (reference ``utils/timer.py:199``).

    ``synchronize=False`` is the async-pipeline variant: start/stop skip the
    per-step ``effects_barrier`` — the single biggest steady-state host stall
    under async XLA dispatch — and the measured wall clock brackets DISPATCH
    time per step. The device time is still fully accounted over a sync
    window: the engine's boundary fetch blocks on every in-flight step, so
    that boundary step's stop() absorbs the accumulated device time and
    multi-step averages stay accurate."""

    def __init__(self, config, batch_size, start_step=2, steps_per_output=None, monitor_memory=False, logging_fn=None,
                 synchronize=True):
        self.config = config
        self.synchronize = synchronize
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = batch_size or 1
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False

    @property
    def enabled(self):
        return getattr(self.config, "enabled", True)

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        if not self.enabled:
            return
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            if self.synchronize:
                _sync()
            self.start_time = time.time()

    def stop(self, global_step=False, report_speed=True, steps: int = 1):
        """``steps``: real optimizer steps covered by this start/stop window
        (fused multi-step dispatch runs K steps per dispatch — counting one
        would understate samples/sec K-fold)."""
        if not self.enabled or not self.started:
            return
        self.started = False
        self.micro_step_count += steps
        if global_step:
            self.global_step_count += steps
        if self.start_time > 0:
            if self.synchronize:
                _sync()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            self._steps_in_window = getattr(self, "_steps_in_window", 0) + steps
            if global_step:
                # crossed-boundary cadence: a K-step dispatch advances the
                # count by K, so == 0 would skip reports whenever K doesn't
                # divide steps_per_output
                crossed = (self.steps_per_output and
                           (self.global_step_count // self.steps_per_output
                            > (self.global_step_count - steps) // self.steps_per_output))
                if report_speed and crossed:
                    n = self._steps_in_window
                    self.logging(
                        f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                        f"global_step={self.global_step_count}, "
                        f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.6f}, "
                        f"CurrSamplesPerSec={self._steps_to_samples(n) / (self.step_elapsed_time + TIME_EPSILON):.6f}")
                self.step_elapsed_time = 0
                self._steps_in_window = 0

    def _steps_to_samples(self, steps):
        return steps * self.batch_size

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step:
            samples_per_step = self.batch_size
            total_step_offset = self.global_step_count - self.start_step
            avg_time_per_step = self.total_elapsed_time / max(total_step_offset, 1)
            return samples_per_step / (avg_time_per_step + TIME_EPSILON)
        return float("-inf")


def trim_mean(data, trim_percent):
    """Compute the trimmed mean of a list of numbers."""
    assert 0.0 <= trim_percent <= 1.0
    n = len(data)
    data.sort()
    k = int(round(n * trim_percent))
    if len(data[k:n - k]) == 0:
        return sum(data) / n
    return sum(data[k:n - k]) / max(len(data[k:n - k]), 1)
