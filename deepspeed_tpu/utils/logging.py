"""Logging utilities.

Mirrors the reference's ``deepspeed/utils/logging.py`` surface (``logger``,
``log_dist``, ``should_log_le``) without the torch dependency: rank is taken
from ``jax.process_index()`` when initialised, else from env.
"""

import logging
import os
import sys
import functools

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class LoggerFactory:

    @staticmethod
    def create_logger(name=None, level=logging.INFO):
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d:%(funcName)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(name="DeepSpeedTPU", level=logging.INFO)


@functools.lru_cache(None)
def warn_once(msg: str):
    logger.warning(msg)


def _get_rank():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", 0))


def log_dist(message, ranks=None, level=logging.INFO):
    """Log on selected process ranks only (rank -1 or None = all)."""
    rank = _get_rank()
    my_rank_in = ranks is None or len(ranks) == 0 or (-1 in ranks) or (rank in ranks)
    if my_rank_in:
        final_message = f"[Rank {rank}] {message}"
        logger.log(level, final_message)


def print_rank_0(message):
    if _get_rank() == 0:
        print(message, flush=True)


def should_log_le(max_log_level_str):
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    max_log_level_str = max_log_level_str.lower()
    if max_log_level_str not in LOG_LEVELS:
        raise ValueError(f"{max_log_level_str} is not one of the `logging` levels")
    return logger.getEffectiveLevel() <= LOG_LEVELS[max_log_level_str]
