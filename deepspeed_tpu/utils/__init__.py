from .logging import logger, log_dist, print_rank_0, should_log_le, warn_once
from .timer import SynchronizedWallClockTimer, NoopTimer, ThroughputTimer, trim_mean
from .retry import retry_with_backoff, RetriesExhausted
from .fault_injection import FaultInjector, InjectedFault, get_fault_injector
