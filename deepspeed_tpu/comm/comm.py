"""Functional communication API.

TPU-native rebuild of ``deepspeed/comm/comm.py:222-786``: the module-level
collective functions (`all_reduce`, `all_gather`, `reduce_scatter`,
`all_to_all_single`, `broadcast`, `barrier`, ...) with *groups replaced by
mesh axis names*.

Two call contexts are supported, dispatched automatically:

1. **In-trace** (inside `jit`/`shard_map` with named mesh axes): thin wrappers
   over `jax.lax` collectives — the hot path. `async_op=True` returns a
   handle whose `.wait()` is a no-op (XLA dispatch is already async).
2. **Eager** (host level, on global `jax.Array`s): implemented with
   `shard_map` over the global mesh; used for init-time broadcast, tests and
   the comms benchmark sweep. These are timed and logged by `CommsLogger`
   exactly where the reference wraps ops with ``@timed_op`` (comm.py:101).

`init_distributed` (reference comm.py:619) initializes `jax.distributed` for
multi-host when coordinator env vars are present, then builds the global mesh.
"""

import functools
import os
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import MeshContext, get_mesh_context, set_mesh_context, mesh_is_initialized, MESH_AXES
from .reduce_op import ReduceOp
from .comms_logging import get_comms_logger
from ..utils.logging import logger

AxisNames = Union[str, Sequence[str], None]

_INITIALIZED = False


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _norm_group(group: AxisNames):
    """None = the full data-parallel world (all axes)."""
    if group is None:
        return tuple(get_mesh_context().axis_names)
    if isinstance(group, str):
        return (group, )
    return tuple(group)


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class CommHandle:
    """Async handle with reference `.wait()` semantics; XLA dispatch is
    already asynchronous so wait() only blocks when `sync` requested."""

    def __init__(self, value=None):
        self.value = value

    def wait(self, sync=False):
        if sync and self.value is not None:
            jax.block_until_ready(self.value)
        return self.value


def timed_op(func):
    """Eager-path analog of reference ``comm.py:101 timed_op``."""
    import inspect
    sig = inspect.signature(func)

    @functools.wraps(func)
    def wrapper(tensor, *args, **kwargs):
        cl = get_comms_logger()
        do_log = cl.enabled and not _in_trace(tensor)
        if do_log:
            jax.block_until_ready(tensor)
            t0 = time.time()
        result = func(tensor, *args, **kwargs)
        if do_log:
            out = result.value if isinstance(result, CommHandle) else result
            jax.block_until_ready(out)
            dt = time.time() - t0
            bound = sig.bind(tensor, *args, **kwargs)
            group = bound.arguments.get("group", None)
            n = get_world_size(group)
            size = tensor.size * tensor.dtype.itemsize
            cl.append(func.__name__, kwargs.get("log_name", func.__name__), dt, size, n)
        return result

    return wrapper


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def parse_slurm_nodelist(nodelist: str) -> list:
    """Expand Slurm's compact nodelist syntax ("n[001-003,007],login-0",
    bracket groups may carry suffixes or repeat: "rack[1-2]-n[1-4]") into
    hostnames, without shelling out to ``scontrol show hostnames``."""

    def _split_top(s):
        parts, depth, cur = [], 0, []
        for ch in s:
            if ch == "[":
                depth += 1
            elif ch == "]":
                depth -= 1
            if ch == "," and depth == 0:
                parts.append("".join(cur))
                cur = []
            else:
                cur.append(ch)
        if cur:
            parts.append("".join(cur))
        return parts

    def _expand(tok):
        i = tok.find("[")
        if i < 0:
            return [tok]
        j = tok.index("]", i)
        prefix, body, rest = tok[:i], tok[i + 1:j], tok[j + 1:]
        vals = []
        for part in body.split(","):
            if "-" in part:
                lo, hi = part.split("-")
                width = len(lo)
                vals.extend(f"{v:0{width}d}" for v in range(int(lo), int(hi) + 1))
            else:
                vals.append(part)
        return [prefix + v + tail for v in vals for tail in _expand(rest)]

    return [h for tok in _split_top(nodelist) if tok for h in _expand(tok)]


def mpi_discovery(distributed_port: int = 29500, auto: bool = True):
    """Derive ``(coordinator_address, num_processes, process_id)`` from the
    scheduler environment — the rendezvous analog of reference
    ``comm/comm.py:688 mpi_discovery`` (which allgathers rank 0's hostname
    over mpi4py; here the coordinator is read from the launcher's env
    directly, no MPI dependency).

    Recognized environments, in priority order:
    - explicit: ``JAX_COORDINATOR_ADDRESS`` / ``COORDINATOR_ADDRESS`` +
      ``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` (what ``bin/deepspeed``'s ssh
      fan-out exports)
    - OpenMPI (``mpirun``): ``OMPI_COMM_WORLD_SIZE/RANK``; coordinator from
      ``OMPI_MCA_orte_hnp_uri`` ("...;tcp://ip1,ip2:port" — first IP of the
      head node)
    - Slurm (``srun``): ``SLURM_NTASKS``/``SLURM_PROCID``; coordinator =
      first host of ``SLURM_STEP_NODELIST``/``SLURM_JOB_NODELIST``
    - PDSH-style: ``DS_HOSTLIST`` (comma-separated, exported identically to
      every node) — process_id = this host's position in the list

    Returns ``(None, 1, 0)`` when nothing distributed is detected. Each of
    the three fields is resolved INDEPENDENTLY: explicit env always wins,
    and whichever scheduler family is present fills only the missing pieces
    (so ``mpirun -x JAX_NUM_PROCESSES=4`` still gets its rank from
    ``OMPI_COMM_WORLD_RANK``). ``auto=False`` disables scheduler probing but
    keeps the explicit env contract.
    """

    def _env(*names, default=None):
        for n in names:
            if os.environ.get(n) not in (None, ""):
                return os.environ[n]
        return default

    coord = _env("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")
    # mpirun's size/rank env is part of the EXPLICIT contract (the pre-probe
    # code honored it unconditionally, and reference auto_mpi_discovery=False
    # only disables the mpi4py probing, not the env) — auto gates only the
    # coordinator guessing and the Slurm/pdsh families below
    nproc = _env("JAX_NUM_PROCESSES", "NUM_PROCESSES", "OMPI_COMM_WORLD_SIZE")
    pid = _env("JAX_PROCESS_ID", "PROCESS_ID", "OMPI_COMM_WORLD_RANK")

    if auto and _env("OMPI_COMM_WORLD_SIZE"):
        if coord is None:
            uri = _env("OMPI_MCA_orte_hnp_uri", "PMIX_SERVER_URI2", default="")
            if "tcp://" in uri:
                head = uri.split("tcp://", 1)[1].split(",")[0].split(":")[0]
                coord = f"{head}:{distributed_port}"
    elif auto and _env("SLURM_STEP_NUM_TASKS"):
        # STEP-scoped vars only: srun sets SLURM_STEP_NUM_TASKS per task,
        # while a bare `sbatch`/`salloc` shell has SLURM_NTASKS (the
        # allocation) without any step — treating the allocation size as a
        # rendezvous world would block forever waiting for peers that were
        # never launched
        nproc = nproc if nproc is not None else _env("SLURM_STEP_NUM_TASKS")
        pid = pid if pid is not None else _env("SLURM_PROCID", default="0")
        if coord is None:
            nodelist = _env("SLURM_STEP_NODELIST", "SLURM_JOB_NODELIST")
            if nodelist:
                coord = f"{parse_slurm_nodelist(nodelist)[0]}:{distributed_port}"
    elif auto and (_env("MV2_COMM_WORLD_SIZE") or _env("PMI_SIZE")):
        # MPICH / Intel MPI hydra (PMI_RANK/PMI_SIZE) and MVAPICH2
        # (MV2_COMM_WORLD_RANK/SIZE) — reference multinode_runner.py
        # MPICH/IMPI/MVAPICH runners. The PMI v1 env carries no coordinator
        # address, so the launcher must pin JAX_COORDINATOR_ADDRESS (ours
        # do); without it the explicit-env requirement surfaces below.
        nproc = nproc if nproc is not None else _env("MV2_COMM_WORLD_SIZE", "PMI_SIZE")
        pid = pid if pid is not None else _env("MV2_COMM_WORLD_RANK", "PMI_RANK",
                                               default="0")
    elif auto and _env("DS_HOSTLIST"):
        import socket
        hosts = [h for h in _env("DS_HOSTLIST").split(",") if h]
        nproc = nproc if nproc is not None else str(len(hosts))
        if pid is None:
            me = socket.gethostname()
            cands = [i for i, h in enumerate(hosts)
                     if h == me or h.split(".")[0] == me.split(".")[0]]
            if not cands:
                raise RuntimeError(
                    f"DS_HOSTLIST={_env('DS_HOSTLIST')} does not contain this "
                    f"host ({me}); every node would claim process_id=0 and "
                    "the rendezvous would hang. Use hostnames matching "
                    "`hostname` output in the hostfile, or export "
                    "JAX_PROCESS_ID explicitly.")
            pid = str(cands[0])
        if coord is None:
            coord = f"{hosts[0]}:{distributed_port}"

    return coord, int(nproc or "1"), int(pid or "0")


# rendezvous guard rails: a slow-to-arrive host should surface as bounded
# retries + a clear error, never an indefinite hang (env-overridable so an
# operator can widen the window for giant pods without a code change)
DIST_INIT_TIMEOUT_SECS = float(os.environ.get("DS_DIST_INIT_TIMEOUT", 300))
DIST_INIT_RETRIES = int(os.environ.get("DS_DIST_INIT_RETRIES", 3))
DIST_INIT_BACKOFF_SECS = float(os.environ.get("DS_DIST_INIT_BACKOFF", 1.0))


def _initialize_distributed_guarded(coord, nproc, pid, timeout=None):
    """``jax.distributed.initialize`` with bounded retry + timeout.

    The bare call blocks until every process reaches the coordinator — a
    wedged peer hangs the whole pod forever. Here each attempt carries JAX's
    ``initialization_timeout`` (when the installed version supports it) and
    transient failures retry with backoff; exhaustion raises
    ``RetriesExhausted`` so the scheduler can reschedule the job instead of
    leaking a hung allocation."""
    import inspect
    from ..utils.retry import retry_with_backoff
    from ..utils.fault_injection import get_fault_injector, InjectedFault

    if timeout is None:
        timeout = DIST_INIT_TIMEOUT_SECS
    elif hasattr(timeout, "total_seconds"):  # torch-style timedelta
        timeout = timeout.total_seconds()
    kwargs = dict(coordinator_address=coord, num_processes=nproc, process_id=pid)
    try:
        sig = inspect.signature(jax.distributed.initialize)
        if "initialization_timeout" in sig.parameters:
            kwargs["initialization_timeout"] = int(timeout)
    except (TypeError, ValueError):  # pragma: no cover — builtin/no signature
        pass

    def _attempt():
        if get_fault_injector().fire("comm.init_timeout",
                                     coordinator=coord) is not None:
            raise InjectedFault(
                f"comm.init_timeout: rendezvous with {coord} timed out")
        jax.distributed.initialize(**kwargs)

    retry_with_backoff(
        _attempt, retries=DIST_INIT_RETRIES, base_delay=DIST_INIT_BACKOFF_SECS,
        max_delay=30.0,
        exceptions=(InjectedFault, RuntimeError, OSError, TimeoutError),
        desc=f"jax.distributed.initialize({coord})")


def exchange_host_state(payload, timeout: Optional[float] = None):
    """All-gather a small pickleable host payload across processes, with a
    timeout guard: one wedged peer raises ``TimeoutError`` here instead of
    hanging the exchange forever. Returns ``[payload_0, ..., payload_{n-1}]``
    (single-process: ``[payload]`` immediately)."""
    if jax.process_count() == 1:
        return [payload]
    import pickle
    import concurrent.futures
    from jax.experimental import multihost_utils

    if timeout is None:
        timeout = DIST_INIT_TIMEOUT_SECS
    blob = np.frombuffer(pickle.dumps(payload), np.uint8)

    def _run():
        # two rounds: sizes first (payloads differ per host), then the
        # max-size padded byte buffers
        sizes = np.asarray(multihost_utils.process_allgather(
            np.asarray([blob.size], np.int64))).ravel()
        padded = np.zeros(int(sizes.max()), np.uint8)
        padded[:blob.size] = blob
        out = np.asarray(multihost_utils.process_allgather(padded))
        return [pickle.loads(bytes(out[i][:int(sizes[i])]))
                for i in range(out.shape[0])]

    ex = concurrent.futures.ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="ds-host-exchange")
    try:
        return ex.submit(_run).result(timeout=timeout)
    except concurrent.futures.TimeoutError as e:
        raise TimeoutError(
            f"host-state exchange timed out after {timeout}s — a peer "
            "process is unreachable or wedged") from e
    finally:
        # wait=False: on timeout the gather thread is stuck in a collective;
        # joining it would reintroduce the very hang this guard removes
        ex.shutdown(wait=False)


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1,
                     mesh_axes: Optional[dict] = None):
    """Initialize multi-host JAX (if applicable) and the global mesh.

    Reference: ``comm/comm.py:619``. On TPU the rendezvous is
    `jax.distributed.initialize` (coordinator discovered from env:
    COORDINATOR_ADDRESS / MEGASCALE / SLURM / OpenMPI env, mirroring
    `mpi_discovery` comm.py:688), after which `jax.devices()` is global.
    """
    global _INITIALIZED

    # scheduler env discovery: ssh fan-out (JAX_*, always honored), plus
    # mpirun (OMPI_*) / srun (SLURM_*) / pdsh (DS_HOSTLIST) probing unless
    # auto_mpi_discovery=False — see mpi_discovery
    coord, nproc, pid = mpi_discovery(distributed_port, auto=auto_mpi_discovery)
    if rank >= 0:
        pid = rank
    if world_size > 0:
        nproc = world_size
    # NOTE: decide from env only — touching jax.process_count() here would
    # initialize the XLA backend and make jax.distributed.initialize raise
    # ("must be called before any JAX computations").
    if coord and nproc > 1 and not _INITIALIZED:
        if verbose:
            logger.info(f"init_distributed: coordinator={coord} procs={nproc} id={pid}")
        _initialize_distributed_guarded(coord, nproc, pid, timeout)
    if not mesh_is_initialized():
        set_mesh_context(MeshContext.create(axis_sizes=mesh_axes))
    _INITIALIZED = True
    return get_mesh_context()


def is_initialized():
    return _INITIALIZED or mesh_is_initialized()


def initialize_mesh_device(mesh_shape, mesh_axis_names):
    """Reference ``comm.py:603``; returns the global MeshContext."""
    sizes = dict(zip(mesh_axis_names, mesh_shape))
    ctx = MeshContext.create(axis_sizes=sizes, axis_order=tuple(mesh_axis_names))
    set_mesh_context(ctx)
    return ctx


# ---------------------------------------------------------------------------
# topology queries
# ---------------------------------------------------------------------------


def get_world_size(group: AxisNames = None) -> int:
    return get_mesh_context().axis_size(_norm_group(group))


def get_rank(group: AxisNames = None) -> int:
    """Host-level rank = process index (SPMD single-controller semantics).
    For a per-device rank along mesh axes inside a traced function, use
    `get_axis_index`."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0


def get_axis_index(axis: AxisNames):
    """In-trace rank along `axis` (flattened over multiple axes)."""
    axes = _norm_group(axis)
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


# ---------------------------------------------------------------------------
# in-trace collectives (hot path)
# ---------------------------------------------------------------------------

_REDUCE_FNS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
    ReduceOp.AVG: lax.pmean,
}

_EAGER_JIT_CACHE = {}


def _input_spec(a):
    if hasattr(a, "sharding") and isinstance(a.sharding, NamedSharding):
        return a.sharding.spec
    return P()


def _eager_collective(key, make_fn, tensor, group, out_spec=None):
    """Run an axis-collective eagerly over the global mesh via shard_map.

    `key` must uniquely identify the computation (op name + static params);
    jitted callables are cached on (key, axes, in_spec, out_spec) so repeated
    eager collectives don't retrace.
    """
    ctx = get_mesh_context()
    axes = _norm_group(group)
    in_spec = _input_spec(tensor)
    out_spec = in_spec if out_spec is None else out_spec
    cache_key = (key, axes, in_spec, out_spec, ctx.epoch)
    fn = _EAGER_JIT_CACHE.get(cache_key)
    if fn is None:
        from jax.experimental.shard_map import shard_map
        fn = jax.jit(
            shard_map(make_fn(axes), mesh=ctx.mesh, in_specs=(in_spec, ), out_specs=out_spec,
                      check_rep=False))
        _EAGER_JIT_CACHE[cache_key] = fn
    return fn(tensor)


def _reduce_in_trace(x, op, axes):
    if op == ReduceOp.PRODUCT:
        # No native product collective: gather and multiply (correct for
        # zeros/negatives, unlike exp(psum(log)) tricks).
        g = lax.all_gather(x, axes, axis=0, tiled=False)
        return jnp.prod(g, axis=0)
    if op not in _REDUCE_FNS:
        raise NotImplementedError(f"ReduceOp {op} is not supported on TPU")
    return _REDUCE_FNS[op](x, axes)


@timed_op
def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None, async_op=False, **kw):
    axes = _norm_group(group)
    if _in_trace(tensor):
        out = _reduce_in_trace(tensor, op, axes)
    else:
        out = _eager_collective(("all_reduce", op), lambda ax: (lambda x: _reduce_in_trace(x, op, ax)),
                                tensor, group)
    return CommHandle(out) if async_op else out


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None, **kw):
    """Reference comm.py:500 fast path — identical on TPU (XLA picks the
    algorithm); kept for API parity."""
    return all_reduce(tensor, op=op, group=group)


@timed_op
def all_gather(tensor, group: AxisNames = None, axis: int = 0, tiled: bool = True, async_op=False, **kw):
    """Gather shards along `axis`. In-trace this is `lax.all_gather(tiled=)`.
    Reference: all_gather_into_tensor (comm.py:317).

    Eager semantics: every participant's *local shard* is concatenated and the
    result is replicated — i.e. a sharded global array comes back with the
    same content, replicated; a replicated one comes back tiled `n` times
    (matching torch, where each rank contributes its local copy)."""
    axes = _norm_group(group)
    if _in_trace(tensor):
        out = lax.all_gather(tensor, axes, axis=axis, tiled=tiled)
    else:
        out = _eager_collective(("all_gather", axis, tiled),
                                lambda ax: (lambda x: lax.all_gather(x, ax, axis=axis, tiled=tiled)),
                                tensor, group, out_spec=P())
    return CommHandle(out) if async_op else out


# reference-parity aliases
def all_gather_into_tensor(output_tensor, tensor, group=None, async_op=False):
    res = all_gather(tensor, group=group, axis=0, tiled=True, async_op=async_op)
    return res


def has_all_gather_into_tensor():
    return True


def has_reduce_scatter_tensor():
    return True


@timed_op
def reduce_scatter(tensor, group: AxisNames = None, axis: int = 0, op: ReduceOp = ReduceOp.SUM,
                   async_op=False, **kw):
    """Reduce-scatter along `axis` (reference reduce_scatter_tensor comm.py:257)."""
    axes = _norm_group(group)

    n = get_mesh_context().axis_size(axes)

    def _make(ax):

        def _rs(x):
            out = lax.psum_scatter(x, ax, scatter_dimension=axis, tiled=True)
            if op == ReduceOp.AVG:
                out = out / n
            return out

        return _rs

    if _in_trace(tensor):
        out = _make(axes)(tensor)
    else:
        # Eager: output is sharded along `axis` over the group — rank k holds
        # the reduced k-th chunk; assembled global = elementwise reduction of
        # the participants' local tensors.
        spec = [None] * tensor.ndim
        spec[axis] = axes if len(axes) > 1 else axes[0]
        out = _eager_collective(("reduce_scatter", op, axis), _make, tensor, group,
                                out_spec=P(*spec))
    return CommHandle(out) if async_op else out


def reduce_scatter_tensor(output_tensor, tensor, op=ReduceOp.SUM, group=None, async_op=False):
    return reduce_scatter(tensor, group=group, op=op, async_op=async_op)


@timed_op
def all_to_all_single(tensor, group: AxisNames = None, split_axis: int = 0, concat_axis: int = 0,
                      async_op=False, **kw):
    """All-to-all (reference comm.py:360): split `split_axis` into world
    chunks, exchange, concatenate on `concat_axis`. The Ulysses hot op."""
    axes = _norm_group(group)

    def _make(ax):
        return lambda x: lax.all_to_all(x, ax, split_axis=split_axis, concat_axis=concat_axis, tiled=True)

    if _in_trace(tensor):
        out = _make(axes)(tensor)
    else:
        # Eager: treat the global array as sharded over `split_axis`; the
        # exchange moves the sharding to `concat_axis` with content preserved
        # (the Ulysses seq<->head reshard). Reshard input if needed.
        ctx = get_mesh_context()
        ax = axes if len(axes) > 1 else axes[0]
        in_spec = [None] * tensor.ndim
        in_spec[split_axis] = ax
        out_spec = [None] * tensor.ndim
        out_spec[concat_axis] = ax
        tensor = jax.device_put(tensor, ctx.sharding(*in_spec))
        out = _eager_collective(("all_to_all", split_axis, concat_axis), _make, tensor, group,
                                out_spec=P(*out_spec))
    return CommHandle(out) if async_op else out


def all_to_all(output_tensor_list, input_tensor_list, group=None, async_op=False):
    """List form: stack → all_to_all_single → unstack."""
    x = jnp.stack(input_tensor_list, axis=0)
    out = all_to_all_single(x, group=group, split_axis=0, concat_axis=0)
    n = get_world_size(group)
    chunks = jnp.split(out, n, axis=0)
    return [c.squeeze(0) if c.shape[0] == 1 else c for c in chunks]


@timed_op
def broadcast(tensor, src: int = 0, group: AxisNames = None, async_op=False, **kw):
    """Every participant ends with src's value. In-trace: gather + index
    (XLA lowers to a broadcast-from-root collective)."""
    axes = _norm_group(group)

    def _make(ax):

        def _bc(x):
            g = lax.all_gather(x, ax, axis=0, tiled=False)
            return g[src]

        return _bc

    if _in_trace(tensor):
        out = _make(axes)(tensor)
    else:
        # Eager: every participant ends with participant `src`'s local value;
        # the result is replicated (output shape == the local shard shape).
        out = _eager_collective(("broadcast", src), _make, tensor, group, out_spec=P())
    return CommHandle(out) if async_op else out


def ppermute(tensor, perm, group: AxisNames = None):
    """Point-to-point ring shift; the TPU analog of send/recv pairs
    (reference pipe/p2p.py). perm = list of (src, dst) pairs."""
    axes = _norm_group(group)
    return lax.ppermute(tensor, axes[0] if len(axes) == 1 else axes, perm=perm)


def send(tensor, dst, group=None, tag=0):
    raise NotImplementedError(
        "Raw send/recv is not expressible in SPMD/XLA; use comm.ppermute "
        "(both ends participate) — see parallel/pipe.py for the schedule-level replacement.")


def recv(tensor, src, group=None, tag=0):
    raise NotImplementedError(
        "Raw send/recv is not expressible in SPMD/XLA; use comm.ppermute.")


def barrier(group: AxisNames = None):
    """Host-level barrier: drain device queues; in multi-host, a tiny psum."""
    jax.effects_barrier()
    if jax.process_count() > 1:
        x = jnp.ones((), dtype=jnp.int32)
        jax.block_until_ready(
            _eager_collective(("barrier", ), lambda ax: (lambda v: lax.psum(v, ax)), x, group,
                              out_spec=P()))


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    return barrier(group)


# coalesced forms: under XLA, passing a list and letting the compiler fuse is
# the coalescing (reference comm.py all_reduce_coalesced / all_gather_coalesced)
def all_reduce_coalesced(tensors, op=ReduceOp.SUM, group=None, async_op=False):
    return [all_reduce(t, op=op, group=group) for t in tensors]


def all_gather_coalesced(tensors, group=None, async_op=False):
    return [all_gather(t, group=group) for t in tensors]


def reduce_scatter_coalesced(tensors, group=None, async_op=False):
    """Reference ``runtime/comm/coalesced_collectives.py:81``."""
    return [reduce_scatter(t, group=group) for t in tensors]


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, async_op=False):
    """SPMD has no rooted reduce; all participants get the result (superset
    of reference semantics)."""
    return all_reduce(tensor, op=op, group=group, async_op=async_op)


def gather(tensor, gather_list=None, dst=0, group=None, async_op=False):
    return all_gather(tensor, group=group, async_op=async_op)


def scatter(tensor, scatter_list=None, src=0, group=None, async_op=False):
    raise NotImplementedError("scatter from a root is host-side under SPMD; use jax.device_put with a sharding")


# ---------------------------------------------------------------------------
# logging controls (reference comm.py:404-434)
# ---------------------------------------------------------------------------


def configure(deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
    cl = get_comms_logger()
    if deepspeed_config is not None:
        cl.configure(deepspeed_config)
    if enabled is not None:
        cl.enabled = enabled
    if prof_all is not None:
        cl.prof_all = prof_all
    if prof_ops is not None:
        cl.prof_ops = prof_ops
    if verbose is not None:
        cl.verbose = verbose
    if debug is not None:
        cl.debug = debug


def log_summary(show_straggler=False):
    return get_comms_logger().log_all(show_straggler=show_straggler)
