from .comm import *  # noqa: F401,F403
from .comm import (all_reduce, all_gather, all_gather_into_tensor, reduce_scatter, reduce_scatter_tensor,
                   all_to_all, all_to_all_single, broadcast, barrier, init_distributed, is_initialized,
                   exchange_host_state,
                   get_world_size, get_rank, get_local_rank, get_axis_index, ppermute, inference_all_reduce,
                   initialize_mesh_device, log_summary, configure, CommHandle,
                   mpi_discovery, parse_slurm_nodelist)
from .bucketing import (Bucket, BucketLayout, BucketSlot, WIRE_TIERS, all_gather_bucket,
                        allreduce_bucket, bucket_wire_bytes, bucketed_allreduce_tree,
                        dequantize_block_int8, flatten_buckets, init_error_buckets,
                        plan_buckets, quantize_block_int8, record_bucket_traffic,
                        reduce_scatter_bucket, unflatten_buckets)
from .mesh import MeshContext, get_mesh_context, set_mesh_context, reset_mesh_context, MESH_AXES
from .reduce_op import ReduceOp
