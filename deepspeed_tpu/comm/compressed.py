"""Error-compensated 1-bit compressed allreduce — the WIRE path.

Reference: ``runtime/comm/nccl.py:16 NcclBackend.compressed_allreduce`` /
``runtime/comm/compressed.py:13`` — the momentum exchange behind 1-bit
Adam/LAMB/0-1 Adam packs sign bits + a per-worker scale so the wire carries
~1/32 of the fp32 bytes.

TPU shape: inside a ``shard_map`` region with the data-parallel axes manual,
each worker packs its error-corrected tensor's SIGN BITS into uint8 (8 signs
per byte — the arrays XLA actually moves over ICI are the packed ones),
``lax.all_gather``s packed bits + scales, and decompresses/averages locally:

    worker i:  c_i = x_i + e_i;  s_i = mean|c_i|;  wire_i = signbits(c_i)
    result  =  mean_i( sign(wire_i) * s_i );   e_i' = c_i - sign(c_i)*s_i

Wire volume per worker: N/8 bytes + 4, vs 4N for an fp32 gather — 32x, the
reference's headline (docs/_tutorials/onebit-adam.md).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def pack_signs(x) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [N] float → (packed uint8 [ceil(N/8)], scale scalar). The sign
    convention: bit=1 means non-negative."""
    n = x.shape[0]
    pad = (-n) % 8
    bits = (jnp.pad(x, (0, pad)) >= 0).astype(jnp.uint8).reshape(-1, 8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :]
    packed = jnp.sum(bits * weights, axis=1).astype(jnp.uint8)
    scale = jnp.mean(jnp.abs(x))
    return packed, scale


def unpack_signs(packed, n: int) -> jnp.ndarray:
    """packed uint8 [..., ceil(N/8)] → signs ±1.0 float32 [..., N]."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (packed[..., :, None] >> shifts) & jnp.uint8(1)
    signs = bits.astype(jnp.float32) * 2.0 - 1.0
    return signs.reshape(*packed.shape[:-1], -1)[..., :n]


def compressed_allreduce_intrace(x, error, axis_names):
    """One error-compensated compressed allreduce step (must run inside
    shard_map with ``axis_names`` manual). x/error are flat [N] float arrays;
    returns (averaged_result [N], new_error [N])."""
    n = x.shape[0]
    corrected = x + error
    packed, scale = pack_signs(corrected)
    # THE wire: uint8 sign bits + one fp32 scale per worker
    all_packed = lax.all_gather(packed, axis_names)      # [W, N/8] uint8
    all_scales = lax.all_gather(scale, axis_names)       # [W]
    signs = unpack_signs(all_packed, n)                  # [W, N]
    avg = jnp.mean(signs * all_scales[:, None], axis=0)
    my_compressed = unpack_signs(packed, n) * scale
    new_error = corrected - my_compressed
    return avg, new_error


def compressed_allreduce_tree(tree, error_tree, axis_names):
    """Pytree version: each leaf raveled, exchanged, restored."""
    def one(x, e):
        flat, err = x.ravel(), e.ravel()
        avg, new_err = compressed_allreduce_intrace(flat, err, axis_names)
        return avg.reshape(x.shape).astype(x.dtype), new_err.reshape(x.shape).astype(e.dtype)

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    err_leaves, err_treedef = jax.tree_util.tree_flatten(error_tree)
    # a silent zip of mismatched trees would pair wrong error buffers with
    # wrong leaves (or drop trailing leaves entirely) — validate up front
    if err_treedef != treedef:
        raise ValueError(
            "compressed_allreduce_tree: error_tree structure does not match "
            f"tree (tree: {treedef}, error_tree: {err_treedef}); the "
            "error-feedback buffers must be built from the same pytree")
    for i, (x, e) in enumerate(zip(leaves, err_leaves)):
        xs = tuple(getattr(x, "shape", ()))
        es = tuple(getattr(e, "shape", ()))
        if xs != es:
            raise ValueError(
                f"compressed_allreduce_tree: leaf {i} has shape {xs} but its "
                f"error buffer has shape {es} — error buffers must mirror "
                "the gradient leaves exactly")
    out = [one(x, e) for x, e in zip(leaves, err_leaves)]
    avg = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_err = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    return avg, new_err


def wire_bytes(n_elements: int, world: int, block_size: int = 256) -> dict:
    """Accounting: per-worker receive-side bytes for each wire tier.

    - fp32: the uncompressed gather (4 bytes/element)
    - int8: blockwise-quantized tier (1 byte/element + per-block fp32
      scale + zero-point, 8 bytes per ``block_size`` elements)
    - onebit (``compressed_bytes``): packed sign bits + one fp32 scale
      per worker — the 1-bit Adam wire, ~32x
    """
    packed = world * ((n_elements + 7) // 8 + 4)
    n_blocks = (n_elements + block_size - 1) // block_size
    int8 = world * (n_elements + 8 * n_blocks)
    fp32 = world * n_elements * 4
    return {"compressed_bytes": packed, "int8_bytes": int8, "fp32_bytes": fp32,
            "reduction": fp32 / packed,
            "int8_reduction": fp32 / int8}
