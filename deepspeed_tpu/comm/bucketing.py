"""Gradient comm planner: bucketed + quantized collectives.

Reference: the hook-driven bucketed reduce of ``runtime/zero/stage_1_and_2.py``
(``reduce_bucket_size`` / ``reduce_ipg_grads``) and the coalesced collectives
of ``runtime/comm/coalesced_collectives.py`` — the wire wins by carrying a few
LARGE flat arrays instead of one collective per parameter tensor.

TPU shape (everything here runs inside ``shard_map`` with the data-parallel
axes manual, like ``comm/compressed.py``):

1. **Bucketing** — ``plan_buckets`` flattens a gradient pytree into
   dtype-homogeneous flat buckets of at most ``bucket_size_mb`` each, with a
   deterministic layout (leaves in ``tree_flatten`` order, greedy fill). The
   ``BucketLayout`` records, per slot, the leaf index / offset / shape, so
   ``unflatten_buckets`` restores the exact pytree. The wire then carries
   ``ceil(total_bytes / bucket_size)`` collectives per dtype instead of one
   per leaf.

2. **Quantized wire tier** — EQuARX-style blockwise int8: each block of
   ``block_size`` elements is affinely mapped to int8 with a per-block fp32
   scale + zero-point. The arrays XLA actually moves over ICI are the int8
   codes + the (tiny) per-block scales. Wire volume ~N bytes + 8N/block,
   vs 4N fp32 — a ~4x cut with <1% blockwise quantization error, sitting
   between fp32 and the 1-bit sign path (~32x) from ``compressed.py``.

3. **Two-step exchange** — ``reduce_scatter_bucket`` + ``all_gather_bucket``
   compose into a quantized allreduce (both halves independently quantizable,
   as in EQuARX); ZeRO-1/2 consumers stop after the reduce-scatter, whose
   output IS each worker's gradient shard.

Error feedback (optional, matching the 1-bit path's residual): the
quantization residual of THIS worker's outgoing codes is returned so callers
can fold it into the next step's gradients.
"""

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .compressed import pack_signs, unpack_signs

WIRE_TIERS = ("fp32", "int8", "onebit")

DEFAULT_BLOCK_SIZE = 256


# ---------------------------------------------------------------------------
# bucket layout
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BucketSlot:
    """One leaf's placement inside a flat bucket."""
    leaf_index: int          # position in tree_flatten order
    offset: int              # start element inside the bucket
    size: int                # number of elements
    shape: Tuple[int, ...]   # original leaf shape


@dataclass(frozen=True)
class Bucket:
    dtype: Any               # numpy dtype of every slot in this bucket
    size: int                # total elements (sum of slot sizes, pre-padding)
    padded_size: int         # size rounded up so every worker/block divides
    slots: Tuple[BucketSlot, ...] = ()


@dataclass(frozen=True)
class BucketLayout:
    """Deterministic flat-bucket layout for one gradient pytree."""
    buckets: Tuple[Bucket, ...]
    treedef: Any
    n_leaves: int

    def buckets_for_dtype(self, dtype) -> List[int]:
        dt = np.dtype(dtype)
        return [i for i, b in enumerate(self.buckets) if np.dtype(b.dtype) == dt]

    @property
    def dtypes(self) -> Tuple[Any, ...]:
        seen = []
        for b in self.buckets:
            if b.dtype not in seen:
                seen.append(b.dtype)
        return tuple(seen)


def _pad_to(n: int, multiple: int) -> int:
    if multiple <= 1:
        return n
    return n + (-n) % multiple


def plan_buckets(tree, bucket_size_mb: float = 25.0,
                 pad_multiple: int = 1) -> BucketLayout:
    """Plan dtype-homogeneous flat buckets over ``tree``'s leaves.

    Deterministic: leaves are visited in ``tree_flatten`` order and packed
    greedily per dtype; a bucket closes when adding the next leaf would
    exceed ``bucket_size_mb`` (a single leaf larger than the budget gets its
    own bucket — leaves are never split across buckets, so unflattening is a
    pure slice + reshape). ``pad_multiple``: each bucket's wire length is
    rounded up so reduce-scatter shards and quantization blocks divide.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    budget = int(bucket_size_mb * 1024 * 1024)
    if budget <= 0:
        raise ValueError(f"bucket_size_mb must be positive, got {bucket_size_mb}")
    open_buckets: Dict[Any, Tuple[list, int]] = {}  # dtype -> (slots, fill)
    done: List[Bucket] = []

    def _close(dt):
        slots, fill = open_buckets.pop(dt)
        done.append(Bucket(dtype=dt, size=fill,
                           padded_size=_pad_to(fill, pad_multiple),
                           slots=tuple(slots)))

    for i, leaf in enumerate(leaves):
        shape = tuple(getattr(leaf, "shape", ()))
        dt = np.dtype(getattr(leaf, "dtype", np.float32))
        size = int(np.prod(shape)) if shape else 1
        nbytes = size * dt.itemsize
        if dt in open_buckets:
            slots, fill = open_buckets[dt]
            if (fill + size) * dt.itemsize > budget and fill > 0:
                _close(dt)
        if dt not in open_buckets:
            open_buckets[dt] = ([], 0)
        slots, fill = open_buckets[dt]
        slots.append(BucketSlot(leaf_index=i, offset=fill, size=size, shape=shape))
        open_buckets[dt] = (slots, fill + size)
        if (fill + size) * dt.itemsize >= budget:
            _close(dt)
    for dt in list(open_buckets):
        _close(dt)
    # deterministic order: by first leaf index
    done.sort(key=lambda b: b.slots[0].leaf_index)
    return BucketLayout(buckets=tuple(done), treedef=treedef, n_leaves=len(leaves))


def flatten_buckets(tree, layout: BucketLayout) -> List[jnp.ndarray]:
    """Pytree -> list of flat 1-D bucket arrays (padded with zeros)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) != layout.n_leaves:
        raise ValueError(
            f"tree has {len(leaves)} leaves but the bucket layout was planned "
            f"for {layout.n_leaves} — replan with plan_buckets")
    out = []
    for b in layout.buckets:
        parts = [leaves[s.leaf_index].reshape(-1) for s in b.slots]
        flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        if b.padded_size > b.size:
            flat = jnp.pad(flat, (0, b.padded_size - b.size))
        out.append(flat)
    return out


def unflatten_buckets(bucket_arrays: Sequence[jnp.ndarray],
                      layout: BucketLayout, example_tree=None):
    """Inverse of ``flatten_buckets``: slice each bucket back into leaves and
    rebuild the pytree (dtypes restored from the bucket dtype; pass
    ``example_tree`` to also restore leaf dtypes that differ)."""
    if len(bucket_arrays) != len(layout.buckets):
        raise ValueError(f"expected {len(layout.buckets)} buckets, "
                         f"got {len(bucket_arrays)}")
    example_leaves = (jax.tree_util.tree_leaves(example_tree)
                      if example_tree is not None else None)
    leaves: List[Optional[jnp.ndarray]] = [None] * layout.n_leaves
    for arr, b in zip(bucket_arrays, layout.buckets):
        for s in b.slots:
            leaf = lax.dynamic_slice_in_dim(arr, s.offset, s.size).reshape(s.shape)
            if example_leaves is not None:
                leaf = leaf.astype(example_leaves[s.leaf_index].dtype)
            else:
                leaf = leaf.astype(b.dtype)
            leaves[s.leaf_index] = leaf
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


# ---------------------------------------------------------------------------
# blockwise int8 quantization (EQuARX-style scale + zero-point per block)
# ---------------------------------------------------------------------------


def quantize_block_int8(x, block_size: int = DEFAULT_BLOCK_SIZE):
    """Flat [N] float -> (codes int8 [ceil(N/B), B], scale fp32 [nb],
    zero fp32 [nb]). Affine per block: x ≈ (codes + 128) * scale + zero,
    codes spanning [-128, 127] over the block's [min, max] range."""
    n = x.shape[0]
    pad = (-n) % block_size
    xb = jnp.pad(x.astype(jnp.float32), (0, pad)).reshape(-1, block_size)
    lo = jnp.min(xb, axis=1, keepdims=True)
    hi = jnp.max(xb, axis=1, keepdims=True)
    scale = (hi - lo) / 255.0
    safe = jnp.where(scale > 0, scale, 1.0)
    codes = jnp.clip(jnp.round((xb - lo) / safe), 0, 255) - 128
    return codes.astype(jnp.int8), scale[:, 0], lo[:, 0]


def dequantize_block_int8(codes, scale, zero, n: Optional[int] = None):
    """Inverse of ``quantize_block_int8``; trims padding back to ``n``."""
    x = (codes.astype(jnp.float32) + 128.0) * scale[..., :, None] \
        + zero[..., :, None]
    flat = x.reshape(*codes.shape[:-2], -1)
    return flat if n is None else flat[..., :n]


# ---------------------------------------------------------------------------
# wire tiers: bucket-level collectives (in-trace, inside shard_map)
# ---------------------------------------------------------------------------


def _axis_size(name) -> int:
    # lax.axis_size is jax>=0.5; under 0.4 the trace-time axis env carries it
    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(name))
    import jax.core as _core
    return int(_core.axis_frame(name))


def _world(axis_names) -> int:
    axes = (axis_names, ) if isinstance(axis_names, str) else tuple(axis_names)
    w = 1
    for a in axes:
        w *= _axis_size(a)
    return w


def allreduce_bucket(x, axis_names, tier: str = "fp32",
                     block_size: int = DEFAULT_BLOCK_SIZE, mean: bool = True):
    """Average (or sum) a flat bucket over ``axis_names``, through the chosen
    wire tier. Returns (result [N], residual [N]) — residual is this worker's
    quantization error (zeros for fp32), for error feedback."""
    if tier not in WIRE_TIERS:
        raise ValueError(f"unknown wire tier {tier!r}; expected one of {WIRE_TIERS}")
    w = _world(axis_names)
    n = x.shape[0]
    if tier == "fp32":
        total = lax.psum(x, axis_names)
        return (total / w if mean else total), jnp.zeros_like(x)
    if tier == "int8":
        codes, scale, zero = quantize_block_int8(x, block_size)
        # THE wire: int8 codes + per-block fp32 scale/zero
        all_codes = lax.all_gather(codes, axis_names)   # [W, nb, B] int8
        all_scale = lax.all_gather(scale, axis_names)   # [W, nb]
        all_zero = lax.all_gather(zero, axis_names)     # [W, nb]
        vals = dequantize_block_int8(all_codes, all_scale, all_zero, n)  # [W, N]
        agg = jnp.mean(vals, axis=0) if mean else jnp.sum(vals, axis=0)
        mine = dequantize_block_int8(codes, scale, zero, n)
        return agg, x - mine
    # onebit: sign bits + one scale per worker (compressed.py wire)
    packed, scale = pack_signs(x)
    all_packed = lax.all_gather(packed, axis_names)
    all_scales = lax.all_gather(scale, axis_names)
    signs = unpack_signs(all_packed, n)
    vals = signs * all_scales[:, None]
    agg = jnp.mean(vals, axis=0) if mean else jnp.sum(vals, axis=0)
    mine = unpack_signs(packed, n) * scale
    return agg, x - mine


def reduce_scatter_bucket(x, axis_names, tier: str = "fp32",
                          block_size: int = DEFAULT_BLOCK_SIZE):
    """Reduce-scatter a flat bucket: worker k returns (shard [N/W] holding the
    SUM of every worker's k-th chunk, residual [N]). ``x`` length must divide
    by the axis world (plan with ``pad_multiple=world*block_size``).

    int8 tier: each worker quantizes its N/W-chunks and the exchange is an
    all-to-all of int8 codes + per-block scales — the summation happens in
    fp32 after dequantize, so scales never have to match across workers."""
    if tier not in WIRE_TIERS:
        raise ValueError(f"unknown wire tier {tier!r}; expected one of {WIRE_TIERS}")
    w = _world(axis_names)
    n = x.shape[0]
    if n % w != 0:
        raise ValueError(f"bucket length {n} must divide the dp world {w}; "
                         f"plan_buckets(pad_multiple=world*block) pads for this")
    if tier == "fp32":
        return lax.psum_scatter(x, axis_names, scatter_dimension=0, tiled=True), \
            jnp.zeros_like(x)
    chunk = n // w
    if tier == "int8":
        codes, scale, zero = quantize_block_int8(x, block_size)
        nb = codes.shape[0]
        if nb % w != 0:
            raise ValueError(f"{nb} quantization blocks must divide world {w}; "
                             f"pad buckets to world*block_size")
        # all-to-all: worker k receives every worker's k-th chunk of codes
        ccodes = codes.reshape(w, nb // w, block_size)
        cscale = scale.reshape(w, nb // w)
        czero = zero.reshape(w, nb // w)
        rcodes = lax.all_to_all(ccodes, axis_names, split_axis=0, concat_axis=0,
                                tiled=False)
        rscale = lax.all_to_all(cscale, axis_names, split_axis=0, concat_axis=0,
                                tiled=False)
        rzero = lax.all_to_all(czero, axis_names, split_axis=0, concat_axis=0,
                               tiled=False)
        vals = dequantize_block_int8(rcodes, rscale, rzero)  # [W, chunk]
        shard = jnp.sum(vals, axis=0)
        mine = dequantize_block_int8(codes, scale, zero, n)
        return shard, x - mine
    # onebit reduce-scatter: pack per-chunk signs with a per-chunk scale and
    # all-to-all them (the 1-bit analog of the quantized exchange)
    xc = x.reshape(w, chunk)
    packs, scales = [], []
    for k in range(w):  # static unroll: w is a trace-time constant
        p, s = pack_signs(xc[k])
        packs.append(p)
        scales.append(s)
    packed = jnp.stack(packs)                      # [W, chunk/8] uint8
    scale = jnp.stack(scales)                      # [W]
    rpacked = lax.all_to_all(packed, axis_names, split_axis=0, concat_axis=0,
                             tiled=False)
    rscale = lax.all_to_all(scale, axis_names, split_axis=0, concat_axis=0,
                            tiled=False)
    vals = unpack_signs(rpacked, chunk) * rscale[:, None]
    shard = jnp.sum(vals, axis=0)
    mine = (unpack_signs(packed, chunk) * scale[:, None]).reshape(-1)
    return shard, x - mine


def all_gather_bucket(shard, axis_names, tier: str = "fp32",
                      block_size: int = DEFAULT_BLOCK_SIZE):
    """Gather per-worker shards back into the full flat bucket (the second
    half of a two-step allreduce). int8 tier gathers quantized shards —
    deterministic dequantize, so every worker reconstructs identical values."""
    if tier not in WIRE_TIERS:
        raise ValueError(f"unknown wire tier {tier!r}; expected one of {WIRE_TIERS}")
    if tier == "fp32":
        return lax.all_gather(shard, axis_names, axis=0, tiled=True)
    n = shard.shape[0]
    if tier == "int8":
        codes, scale, zero = quantize_block_int8(shard, block_size)
        all_codes = lax.all_gather(codes, axis_names)
        all_scale = lax.all_gather(scale, axis_names)
        all_zero = lax.all_gather(zero, axis_names)
        return dequantize_block_int8(all_codes, all_scale, all_zero, n).reshape(-1)
    packed, scale = pack_signs(shard)
    all_packed = lax.all_gather(packed, axis_names)
    all_scales = lax.all_gather(scale, axis_names)
    return (unpack_signs(all_packed, n) * all_scales[:, None]).reshape(-1)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def param_gather_bucket(shard, axis_names, fwd_tier: str = "fp32",
                        bwd_tier: str = "fp32",
                        block_size: int = DEFAULT_BLOCK_SIZE):
    """Differentiable bucket all-gather for ZeRO-3 parameter epochs.

    Forward: ``all_gather_bucket(shard, fwd_tier)`` — int8 when
    ``zero_quantized_weights`` (qwZ wire on the flat bucket). Backward: the
    cotangent of the full bucket reduce-scatters back to shard shape through
    ``bwd_tier`` (int8 = qgZ). For fp32/fp32 this is EXACTLY the transpose
    pair XLA uses for a tiled all-gather (psum_scatter), so the scheduled
    stage-3 gradient exchange is bitwise the stage-2 bucket reduce-scatter;
    the custom_vjp exists so the quantized tiers — whose forward rounding is
    not differentiable — ride the same straight-through estimator as
    ``zeropp.quantized_gather_param``, but on flat buckets."""
    return all_gather_bucket(shard, axis_names, fwd_tier, block_size)


def _pgb_fwd(shard, axis_names, fwd_tier, bwd_tier, block_size):
    return all_gather_bucket(shard, axis_names, fwd_tier, block_size), None


def _pgb_bwd(axis_names, fwd_tier, bwd_tier, block_size, _, g):
    shard, _residual = reduce_scatter_bucket(g, axis_names, bwd_tier,
                                             block_size)
    return (shard, )


param_gather_bucket.defvjp(_pgb_fwd, _pgb_bwd)


# ---------------------------------------------------------------------------
# tree-level entry point
# ---------------------------------------------------------------------------


def bucketed_allreduce_tree(tree, axis_names, layout: Optional[BucketLayout] = None,
                            tier: str = "fp32",
                            block_size: int = DEFAULT_BLOCK_SIZE,
                            bucket_size_mb: float = 25.0,
                            error_buckets: Optional[Sequence[jnp.ndarray]] = None,
                            mean: bool = True):
    """Average ``tree`` over ``axis_names`` via flat buckets: ~2-4 large
    collectives instead of one per leaf. Must run inside ``shard_map`` with
    the axes manual (same contract as ``compressed_allreduce_tree``).

    ``error_buckets``: previous step's quantization residuals (bucket-shaped),
    folded in before quantizing (error feedback). Returns
    ``(averaged_tree, new_error_buckets)``.
    """
    if layout is None:
        layout = plan_buckets(tree, bucket_size_mb, pad_multiple=block_size)
    buckets = flatten_buckets(tree, layout)
    if error_buckets is not None:
        if len(error_buckets) != len(buckets):
            raise ValueError(
                f"error_buckets has {len(error_buckets)} entries for "
                f"{len(buckets)} buckets — pass init_error_buckets(layout)")
        buckets = [b + e for b, e in zip(buckets, error_buckets)]
    outs, errs = [], []
    for b in buckets:
        avg, err = allreduce_bucket(b, axis_names, tier=tier,
                                    block_size=block_size, mean=mean)
        outs.append(avg)
        errs.append(err)
    return unflatten_buckets(outs, layout, example_tree=tree), errs


def init_error_buckets(layout: BucketLayout) -> List[jnp.ndarray]:
    """Zero residual buffers matching ``layout`` (fp32, padded length)."""
    return [jnp.zeros((b.padded_size, ), jnp.float32) for b in layout.buckets]


# ---------------------------------------------------------------------------
# wire accounting
# ---------------------------------------------------------------------------


def bucket_wire_bytes(layout: BucketLayout, world: int, tier: str = "fp32",
                      block_size: int = DEFAULT_BLOCK_SIZE) -> dict:
    """Receive-side wire bytes per worker for one allreduce of the layout,
    per tier (per-block scale/zero overhead included), plus collective counts.
    """
    from .compressed import wire_bytes as _leaf_wire_bytes
    total_elems = sum(b.padded_size for b in layout.buckets)
    per_tier = _leaf_wire_bytes(total_elems, world, block_size=block_size)
    counts: Dict[str, int] = {}
    for b in layout.buckets:
        key = str(np.dtype(b.dtype))
        counts[key] = counts.get(key, 0) + 1
    return {
        "n_buckets": len(layout.buckets),
        "collectives_per_dtype": counts,
        "elements": total_elems,
        "fp32_bytes": per_tier["fp32_bytes"],
        "int8_bytes": per_tier["int8_bytes"],
        "onebit_bytes": per_tier["compressed_bytes"],
        "wire_bytes": per_tier[{"fp32": "fp32_bytes", "int8": "int8_bytes",
                                "onebit": "compressed_bytes"}[tier]],
    }


def record_bucket_traffic(layout: BucketLayout, world: int, tier: str,
                          block_size: int = DEFAULT_BLOCK_SIZE,
                          duration: float = 0.0, op: str = "all_reduce",
                          record_name: str = "bucketed_grad_comm"):
    """Register one step's bucketed wire volume with the CommsLogger (the
    in-trace path can't time itself — byte counts flow through
    ``calc_bw_log`` with the caller-measured ``duration``, see
    comms_logging.py module docstring)."""
    from .comms_logging import get_comms_logger
    cl = get_comms_logger()
    if not cl.enabled:
        return None
    stats = bucket_wire_bytes(layout, world, tier, block_size)
    cl.append(op, f"{record_name}[{tier}]", duration, stats["wire_bytes"],
              n_participants=world)
    return stats
