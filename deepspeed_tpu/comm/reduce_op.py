"""ReduceOp enum (reference ``deepspeed/comm/reduce_op.py``)."""

from enum import Enum


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    BAND = 4
    BOR = 5
    BXOR = 6
    AVG = 7
    UNUSED = 8
