"""Device-mesh management.

This replaces the reference's ProcessGroup machinery
(``deepspeed/utils/groups.py``, ``comm/comm.py:603 initialize_mesh_device``):
a single global `jax.sharding.Mesh` with named axes

    (pipe, data, fsdp, seq, expert, model)

where every reference "group" maps to an axis (or tuple of axes):

| reference group                          | mesh axis/axes          |
|------------------------------------------|-------------------------|
| data-parallel group (groups.py:...)      | ("data", "fsdp")        |
| ZeRO partition group                     | "fsdp" (stage>=1)       |
| model/tensor-parallel group (:68)        | "model"                 |
| expert-parallel group (:117)             | "expert"                |
| expert-data-parallel group (:188)        | data axes minus expert  |
| sequence-parallel group (:472)           | "seq"                   |
| pipeline stage group                     | "pipe"                  |
| ZeRO++ hpZ secondary group (:529)        | "fsdp" innermost slice  |

Axis sizes come from ``MeshConfig``; -1 fills with remaining devices.
"""

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.logging import logger

MESH_AXES = ("pipe", "data", "fsdp", "seq", "expert", "model")

_GLOBAL_MESH_CTX: Optional["MeshContext"] = None


def resolve_axis_sizes(n_devices: int, sizes: Dict[str, int], order: Sequence[str] = MESH_AXES) -> Dict[str, int]:
    """Resolve -1 entries: the first -1 axis absorbs all remaining devices."""
    fixed = {k: v for k, v in sizes.items() if v != -1}
    prod = int(np.prod([max(v, 1) for v in fixed.values()])) if fixed else 1
    free = [k for k in order if sizes.get(k, 1) == -1]
    out = {k: max(sizes.get(k, 1), 1) for k in order}
    if free:
        if n_devices % prod != 0:
            raise ValueError(f"Device count {n_devices} not divisible by fixed mesh axes {fixed}")
        rem = n_devices // prod
        out[free[0]] = rem
        for k in free[1:]:
            out[k] = 1
    total = int(np.prod(list(out.values())))
    if total != n_devices:
        raise ValueError(f"Mesh axes {out} (={total}) do not cover {n_devices} devices")
    return out


_MESH_EPOCH = 0


class MeshContext:
    """Holds the global mesh and the axis-name algebra used by every layer."""

    def __init__(self, mesh: Mesh):
        global _MESH_EPOCH
        self.mesh = mesh
        # monotonic id for caches: a GC'd mesh can alias a new mesh's id(),
        # so cache keys must use this epoch, never id(mesh)
        _MESH_EPOCH += 1
        self.epoch = _MESH_EPOCH

    # -------- construction --------

    @classmethod
    def create(cls,
               axis_sizes: Optional[Dict[str, int]] = None,
               devices=None,
               axis_order: Sequence[str] = MESH_AXES) -> "MeshContext":
        devices = devices if devices is not None else jax.devices()
        n = len(devices)
        axis_sizes = dict(axis_sizes) if axis_sizes else {"data": -1}
        if all(v != -1 for v in axis_sizes.values()):
            # let "data" absorb leftover devices when not fully specified
            axis_sizes.setdefault("data", -1)
        sizes = resolve_axis_sizes(n, axis_sizes, order=axis_order)
        shape = tuple(sizes[a] for a in axis_order)
        dev_array = np.asarray(devices).reshape(shape)
        mesh = Mesh(dev_array, axis_names=tuple(axis_order))
        logger.info(f"Created mesh {dict(zip(axis_order, shape))} over {n} devices")
        return cls(mesh)

    # -------- axis algebra --------

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    def axis_size(self, axis) -> int:
        if axis is None:
            return self.world_size
        if isinstance(axis, (tuple, list)):
            return int(np.prod([self.axis_size(a) for a in axis]))
        # an axis the mesh doesn't name is unsharded (custom axis_order
        # meshes via initialize_mesh_device routinely omit standard axes)
        return dict(self.mesh.shape).get(axis, 1)

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    @property
    def dp_axes(self) -> Tuple[str, ...]:
        """Axes over which pure data parallelism happens (incl. ZeRO axis)."""
        return tuple(a for a in ("data", "fsdp") if self.axis_size(a) > 1) or ("data", )

    @property
    def dp_size(self) -> int:
        return self.axis_size("data") * self.axis_size("fsdp")

    @property
    def fsdp_size(self) -> int:
        return self.axis_size("fsdp")

    @property
    def mp_size(self) -> int:
        return self.axis_size("model")

    @property
    def sp_size(self) -> int:
        return self.axis_size("seq")

    @property
    def ep_size(self) -> int:
        return self.axis_size("expert")

    @property
    def pp_size(self) -> int:
        return self.axis_size("pipe")

    # -------- sharding helpers --------

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def __enter__(self):
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


# ---------------- global accessors ----------------


def set_mesh_context(ctx: MeshContext):
    global _GLOBAL_MESH_CTX
    _GLOBAL_MESH_CTX = ctx


def get_mesh_context() -> MeshContext:
    global _GLOBAL_MESH_CTX
    if _GLOBAL_MESH_CTX is None:
        _GLOBAL_MESH_CTX = MeshContext.create()
    return _GLOBAL_MESH_CTX


def mesh_is_initialized() -> bool:
    return _GLOBAL_MESH_CTX is not None


def reset_mesh_context():
    global _GLOBAL_MESH_CTX
    _GLOBAL_MESH_CTX = None
