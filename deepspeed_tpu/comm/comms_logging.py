"""Communication logging (reference ``deepspeed/utils/comms_logging.py:67``).

Records per-op name/size/latency and computes algorithmic + bus bandwidth with
the same formulas the reference uses (``get_bw``, comms_logging.py:12-45).
Latency on TPU is host wall-clock around a blocking dispatch, which is only
meaningful for the eager collective API; in-trace collectives register their
byte counts at trace time and timing comes from xprof.
"""

import math
from ..utils.logging import logger, log_dist


def get_caller_func(frame=3):
    import sys
    return sys._getframe(frame).f_code.co_name


def calc_bw_log(comm_op, size, duration, n):
    """Return (algbw, busbw) in GB/s for a collective of `size` bytes over
    `n` participants; factors follow the reference's nccl-tests convention."""
    duration = max(duration, 1e-9)
    if comm_op in ("all_to_all_single", "all_to_all"):
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / max(n, 1))
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter", "reduce_scatter_tensor"):
        size *= n
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / max(n, 1))
    elif comm_op == "all_reduce":
        tput = size * 2 / duration
        busbw = (size / duration) * (2 * (n - 1) / max(n, 1))
    else:  # broadcast / send / recv / barrier / pt2pt
        tput = size / duration
        busbw = tput
    tput /= 1e9
    busbw /= 1e9
    return tput, busbw


class CommsLogger:

    def __init__(self, config=None):
        from ..config.feature_configs import CommsLoggerConfig
        config = config or CommsLoggerConfig()
        self.comms_dict = {}
        self.verbose = config.verbose
        self.debug = config.debug
        self.prof_ops = config.prof_ops
        self.prof_all = config.prof_all
        self.enabled = config.enabled

    def configure(self, config):
        self.enabled = config.comms_config.enabled
        self.verbose = config.comms_config.verbose
        self.debug = config.comms_config.debug
        self.prof_ops = config.comms_config.prof_ops
        self.prof_all = config.comms_config.prof_all

    def start_profiling_comms(self):
        self.prof_all = True

    def stop_profiling_comms(self):
        self.prof_all = False

    def start_profiling_op(self, op_name_list):
        self.prof_ops = list(set(self.prof_ops) | set(op_name_list))

    def stop_profiling_op(self, op_name_list):
        self.prof_ops = [op for op in self.prof_ops if op not in op_name_list]

    def append(self, raw_name, record_name, latency, msg_size, n_participants=1):
        algbw, busbw = calc_bw_log(raw_name, msg_size, latency, n_participants)
        if record_name in self.comms_dict:
            if msg_size in self.comms_dict[record_name]:
                self.comms_dict[record_name][msg_size][0] += 1
                self.comms_dict[record_name][msg_size][1].append(latency)
                self.comms_dict[record_name][msg_size][2].append(algbw)
                self.comms_dict[record_name][msg_size][3].append(busbw)
            else:
                self.comms_dict[record_name][msg_size] = [1, [latency], [algbw], [busbw]]
        else:
            self.comms_dict[record_name] = {msg_size: [1, [latency], [algbw], [busbw]]}
        if self.verbose:
            log_str = f"comm op: {record_name} | time (ms): {latency * 1000:.2f} | msg size: {convert_size(msg_size)} | algbw (Gbps): {algbw * 8:.2f} | busbw (Gbps): {busbw * 8:.2f}"
            log_dist(log_str, [0])

    def log_all(self, print_log=True, show_straggler=False):
        from ..utils.timer import trim_mean
        if print_log:
            print(f"{'Comm. Op': <20}{'Message Size': <20}{'Count': <20}"
                  f"{'Total Latency(ms)': <20}{'Avg Latency(ms)': <20}"
                  f"{'tput_avg (Gbps)': <20}{'busbw_avg (Gbps)': <20}")
        for record_name in self.comms_dict.keys():
            if print_log:
                print(record_name)
            for msg_size, vals in sorted(self.comms_dict[record_name].items()):
                count = vals[0]
                total_lat = sum(vals[1])
                avg_lat = trim_mean(vals[1], 0.1)
                avg_algbw = trim_mean(vals[2], 0.1)
                avg_busbw = trim_mean(vals[3], 0.1)
                if print_log:
                    print(f"{' ': <20}{convert_size(msg_size): <20}{count: <20}"
                          f"{total_lat * 1000: <20.2f}{avg_lat * 1000: <20.2f}"
                          f"{avg_algbw * 8: <20.2f}{avg_busbw * 8: <20.2f}")
        return self.comms_dict


def convert_size(size_bytes):
    if size_bytes == 0:
        return "0B"
    size_name = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    s = round(size_bytes / p, 2)
    return "%s %s" % (s, size_name[i])


_COMMS_LOGGER = None


def get_comms_logger() -> CommsLogger:
    global _COMMS_LOGGER
    if _COMMS_LOGGER is None:
        _COMMS_LOGGER = CommsLogger()
    return _COMMS_LOGGER
