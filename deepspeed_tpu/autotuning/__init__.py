from .config import AutotuningConfig
from .autotuner import Autotuner
