"""Autotuning config (reference ``deepspeed/autotuning/config.py``
DeepSpeedAutotuningConfig — same JSON keys)."""

from typing import List, Optional

from ..config.config_utils import ConfigModel


class AutotuningConfig(ConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = True
    metric: str = "throughput"  # latency | throughput | flops
    start_profile_step: int = 3
    end_profile_step: int = 5
    tuner_type: str = "gridsearch"  # gridsearch | random | model_based
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    max_train_micro_batch_size_per_gpu: Optional[int] = None
    min_train_micro_batch_size_per_gpu: int = 1
    num_tuning_micro_batch_sizes: int = 3
    zero_stages: Optional[List[int]] = None  # restrict search space
    # include engine param_cast ∈ {engine, model} in the search (only for
    # models with use-site dtype handling — the flax `dtype=` convention)
    tune_param_cast: bool = False
    # run each experiment in a spawned child process (reference
    # scheduler.py:32 isolates experiments so an OOM/abort of one candidate
    # cannot poison the rest of the search)
    exp_isolation: bool = False
    exp_timeout: float = 600.0
    # compile-only HBM prefit before any experiment runs: XLA buffer
    # assignment is an EXACT memory oracle on TPU (the reference's
    # model-based memory estimate, minus the estimation), so provably-OOM
    # candidates never cost a timed experiment, and every candidate the
    # prefit proved to fit carries its predicted peak bytes
    # (``Autotuner.prefit_predicted_bytes``). Monotone pruning: once a
    # micro-batch OOMs at a given (stage, remat), every larger micro-batch
    # there is pruned too. Probes run under the same exp_isolation/
    # exp_timeout protection as experiments; tune() points JAX's persistent
    # compilation cache at results_dir (unless one is configured) so a
    # prefit compile warms the matching experiment's compile — including
    # across exp_isolation child processes. Default None = auto: prefit on
    # TPU backends (where compile-time buffer assignment actually raises
    # RESOURCE_EXHAUSTED), off elsewhere (CPU compiles never OOM, so probes
    # would be pure overhead); True/False force it.
    memory_prefit: Optional[bool] = None
