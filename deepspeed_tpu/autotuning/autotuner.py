"""Autotuner — searches micro-batch size × ZeRO stage × remat policy.

Reference: ``deepspeed/autotuning/autotuner.py:42 Autotuner`` +
``scheduler.py:32 ResourceManager`` + ``tuner/{grid_search,random,
model_based}``. The reference forks whole training jobs per experiment over
the launcher; on TPU (single-controller SPMD) each experiment is an engine
build + a few timed steps — in-process by default, or in a fresh child
process per experiment (``exp_isolation``, the reference scheduler's
process-per-experiment shape) so an XLA OOM/abort cannot poison the rest of
the search.

Search space (reference tune_space): ZeRO stage ∈ {0,1,2,3}, micro-batch ∈
powers of two up to the HBM ceiling (OOM candidates are caught and marked
infeasible, the reference's "error" exp status), remat on/off. Metric:
latency | throughput | flops (reference autotuning config metric).

``tuner_type="model_based"`` is a sequential model-based search (reference
``tuner/model_based_tuner.py:19``): seed measurements → fit a ridge cost
model on config features → evaluate the best-predicted unvisited candidate,
with ε-greedy random exploration — XGBoost swapped for a closed-form
surrogate with the same fit/predict/argmax loop (no extra dependency).
"""

import inspect
import itertools
import json
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import jax

from ..utils.logging import logger
from .config import AutotuningConfig


class _Experiment:

    def __init__(self, exp_id: int, config: Dict[str, Any]):
        self.exp_id = exp_id
        self.config = config
        self.status = "pending"  # pending | done | error
        self.metric_val: Optional[float] = None
        self.error: Optional[str] = None

    def record(self) -> Dict[str, Any]:
        return {"exp_id": self.exp_id, "config": self.config, "status": self.status,
                "metric_val": self.metric_val, "error": self.error}


class CostModel:
    """Ridge-regression surrogate over candidate features (the reference's
    ``XGBoostCostModel`` role: fit measured configs, rank the rest)."""

    def __init__(self, ridge: float = 1e-3):
        self.ridge = ridge
        self._w: Optional[np.ndarray] = None

    @staticmethod
    def features(cand: Dict[str, Any]) -> np.ndarray:
        mb = float(cand["train_micro_batch_size_per_gpu"])
        lb = np.log2(mb)
        stage = float(cand["zero_stage"])
        remat = float(bool(cand["remat"]))
        # quadratic basis: batch-size sweet spots and stage overheads are
        # unimodal, which a purely linear surrogate cannot rank
        return np.array([1.0, lb, lb * lb, stage, stage * stage, remat,
                         lb * stage, stage * remat, lb * remat], np.float64)

    def fit(self, cands: List[Dict[str, Any]], perf: List[float]) -> None:
        X = np.stack([self.features(c) for c in cands])
        y = np.asarray(perf, np.float64)
        A = X.T @ X + self.ridge * np.eye(X.shape[1])
        self._w = np.linalg.solve(A, X.T @ y)

    def predict(self, cands: List[Dict[str, Any]]) -> np.ndarray:
        if self._w is None:
            return np.zeros(len(cands))
        return np.stack([self.features(c) for c in cands]) @ self._w


def _build_exp_config(base_config: Dict[str, Any], cand: Dict[str, Any]
                      ) -> Dict[str, Any]:
    cfg = json.loads(json.dumps(base_config))  # deep copy; exps must not alias
    cfg.pop("autotuning", None)
    cfg["train_micro_batch_size_per_gpu"] = cand["train_micro_batch_size_per_gpu"]
    cfg.pop("train_batch_size", None)
    cfg["gradient_accumulation_steps"] = cfg.get("gradient_accumulation_steps", 1)
    cfg.setdefault("zero_optimization", {})["stage"] = cand["zero_stage"]
    if cand["remat"]:
        cfg["activation_checkpointing"] = {"remat_policy": "nothing_saveable"}
    if cand.get("param_cast", "engine") != "engine":
        cfg["param_cast"] = cand["param_cast"]
    return cfg


def run_candidate(base_config: Dict[str, Any], cand: Dict[str, Any],
                  steps: int, model_builder: Callable, metric: str,
                  compile_only: bool = False) -> Dict[str, Any]:
    """One experiment, start to finish (module-level so ``exp_isolation`` can
    ship it to a spawned child). Returns {"status", "metric_val", "error"}.

    ``compile_only``: lower+compile the fused train program and return XLA
    buffer assignment's exact peak-memory verdict instead of running steps —
    {"status": "fits", "predicted_bytes": N} / {"status": "oom", ...} /
    {"status": "skip_prefit"} when no one-program step exists to lower."""
    import deepspeed_tpu
    from ..comm.mesh import reset_mesh_context

    try:
        cfg = _build_exp_config(base_config, cand)
        reset_mesh_context()
        # builders may accept the candidate (per-exp model wiring, the
        # reference's per-exp ds_config) or take no arguments
        if len(inspect.signature(model_builder).parameters) >= 1:
            model, params = model_builder(cand)
        else:
            model, params = model_builder()
        engine, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                              config=cfg)
        hidden = np.asarray(jax.tree_util.tree_leaves(params)[0]).shape[0]
        bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
        # batch built on HOST, device_put straight into the sharding
        # fused_train_step uses (both branches, so the prefit's compiled
        # program is the experiment's program): a jnp.ones would materialize
        # the FULL global batch on one device first, and lowering replicated
        # host arrays would charge it to every device — both falsely OOM
        # viable candidates.
        xh = np.ones((bs, hidden), np.float32)
        yh = np.zeros_like(xh)
        if compile_only:
            fn = engine._train_step_fused
            if fn is None:
                return {"status": "skip_prefit", "metric_val": None, "error": None}
            # the transfer sits inside the try so an allocation
            # RESOURCE_EXHAUSTED classifies as oom, same as a compile one
            try:
                args = jax.device_put(
                    (xh, yh), engine.zero_plan.batch_sharding((xh, yh)))
                compiled = fn.lower(engine.params, engine.opt_state,
                                    engine.scale_state, args, {}, ()).compile()
            except Exception as e:  # noqa: BLE001
                msg = str(e)
                if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
                    return {"status": "oom", "metric_val": None,
                            "error": msg.splitlines()[0][:200] if msg else "OOM"}
                raise
            ma = compiled.memory_analysis()
            pred = None
            if ma is not None:
                pred = int(ma.temp_size_in_bytes + ma.argument_size_in_bytes
                           + ma.output_size_in_bytes
                           - getattr(ma, "alias_size_in_bytes", 0))
            return {"status": "fits", "metric_val": None, "error": None,
                    "predicted_bytes": pred}

        x, y = jax.device_put((xh, yh), engine.zero_plan.batch_sharding((xh, yh)))
        # warmup (compile), then timed steps — through the same dispatch
        # production train_batch uses: the fused one-program step when it
        # exists (also what the memory prefit compiled, so its verdict and
        # warmed compile cache describe THIS program), else fwd/bwd/step
        def one_step():
            if engine._train_step_fused is not None:
                return engine.fused_train_step(x, y)
            loss = engine.forward(x, y)
            engine.backward(loss)
            engine.step()
            return loss

        loss = one_step()
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = one_step()
        float(loss)  # host sync closes the timing region
        dt = (time.perf_counter() - t0) / steps
        if metric == "latency":
            val = -dt  # maximize
        else:  # throughput (samples/s); flops metric folds into this rank
            val = engine.train_batch_size() / dt
        return {"status": "done", "metric_val": val, "error": None}
    except Exception as e:  # infeasible config (OOM etc.)
        return {"status": "error", "metric_val": None,
                "error": f"{type(e).__name__}: {e}"}


def _isolated_child(conn, base_config, cand, steps, model_builder, metric,
                    compile_only=False):
    """Spawned-process entry: run the experiment, ship the result back."""
    try:
        conn.send(run_candidate(base_config, cand, steps, model_builder, metric,
                                compile_only=compile_only))
    finally:
        conn.close()


class Autotuner:

    def __init__(self, base_config: Dict[str, Any],
                 tuning_config: Optional[AutotuningConfig] = None,
                 model_builder: Optional[Callable] = None):
        """model_builder() -> (model, params); each experiment builds a fresh
        engine from base_config overridden with the candidate's knobs."""
        self.base_config = dict(base_config)
        self.cfg = tuning_config or AutotuningConfig(
            **base_config.get("autotuning", {"enabled": True}))
        self.model_builder = model_builder
        self.exps: List[_Experiment] = []
        self.best: Optional[_Experiment] = None
        # (mb, stage, remat) -> XLA buffer-assignment peak bytes, filled by
        # the compile-only memory prefit for every candidate it proved fits
        self.prefit_predicted_bytes: Dict[Any, int] = {}

    @staticmethod
    def _cand_key(cand: Dict[str, Any]):
        return (cand["train_micro_batch_size_per_gpu"], cand["zero_stage"],
                bool(cand["remat"]))

    # ---- search space (reference _generate_experiments) ----

    def _micro_batch_candidates(self) -> List[int]:
        lo = max(1, self.cfg.min_train_micro_batch_size_per_gpu)
        hi = self.cfg.max_train_micro_batch_size_per_gpu or lo * 16
        out, mb = [], lo
        while mb <= hi and len(out) < self.cfg.num_tuning_micro_batch_sizes:
            out.append(mb)
            mb *= 2
        return out

    def _zero_candidates(self) -> List[int]:
        if self.cfg.zero_stages:
            return list(self.cfg.zero_stages)
        return [0, 1, 2, 3]

    def experiment_space(self) -> List[Dict[str, Any]]:
        # param_cast joins the space only when the model advertises use-site
        # dtype handling (the flax convention): with "engine" excluded, old
        # configs search the identical space as before
        casts = (["engine", "model"]
                 if self.cfg.tune_param_cast else [None])
        space = []
        for mb, stage, remat, cast in itertools.product(
                self._micro_batch_candidates(), self._zero_candidates(),
                [False, True], casts):
            cand = {"train_micro_batch_size_per_gpu": mb,
                    "zero_stage": stage, "remat": remat}
            if cast is not None:
                cand["param_cast"] = cast
            space.append(cand)
        return space

    # ---- tuner orderings (reference tuner/{grid_search,random,model_based}) ----

    def _order(self, space: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        kind = self.cfg.tuner_type
        if kind == "random":
            rng = random.Random(0)
            space = list(space)
            rng.shuffle(space)
            return space
        if kind == "model_based":
            # seed ordering only (the adaptive loop re-ranks after every
            # measurement): larger micro-batch and lower stage first
            return sorted(space, key=lambda c: (-c["train_micro_batch_size_per_gpu"],
                                                c["zero_stage"], c["remat"]))
        return space  # gridsearch

    # ---- experiment runner (reference scheduler.run_job) ----

    def _measure(self, cand: Dict[str, Any], steps: int,
                 compile_only: bool = False) -> Dict[str, Any]:
        if not self.cfg.exp_isolation:
            return run_candidate(self.base_config, cand, steps,
                                 self.model_builder, self.cfg.metric,
                                 compile_only=compile_only)
        # fresh child per experiment (reference scheduler.py:32 isolates
        # experiments for exactly this reason): a hard death — XLA OOM abort,
        # SIGKILL — is an "error" experiment, not a dead search. Raw Process
        # (not ProcessPoolExecutor, whose shutdown blocks on a hung worker)
        # so exp_timeout can terminate a wedged child for real.
        import multiprocessing as mp
        ctx = mp.get_context("spawn")
        recv, send = ctx.Pipe(duplex=False)
        try:
            proc = ctx.Process(target=_isolated_child,
                               args=(send, self.base_config, cand, steps,
                                     self.model_builder, self.cfg.metric,
                                     compile_only))
            proc.start()
        except Exception as e:  # unpicklable builder etc.
            recv.close()
            send.close()
            return {"status": "error", "metric_val": None,
                    "error": f"{type(e).__name__}: {e}"}
        send.close()  # our copy; the child's stays open until it exits
        try:
            if recv.poll(self.cfg.exp_timeout):
                try:
                    return recv.recv()
                except EOFError:  # child died before sending (OOM/abort)
                    return {"status": "error", "metric_val": None,
                            "error": "child process died (OOM/abort)"}
            proc.terminate()
            return {"status": "error", "metric_val": None,
                    "error": f"experiment exceeded {self.cfg.exp_timeout}s"}
        finally:
            proc.join(5)
            if proc.is_alive():
                proc.kill()
                proc.join()
            recv.close()

    def _run_experiment(self, exp: _Experiment, steps: int) -> None:
        res = self._measure(exp.config, steps)
        exp.status = res["status"]
        exp.metric_val = res["metric_val"]
        exp.error = res["error"]

    # ---- main loop (reference autotuner.tune) ----

    def _next_candidates(self, space, visited, model, rng):
        """Model-based selection: best predicted unvisited candidate, with
        ε-greedy exploration (reference model_based_tuner.py:19 next_batch)."""
        open_idx = [i for i in range(len(space)) if i not in visited]
        if not open_idx:
            return None
        if rng.random() < 0.2:  # random_exploration_ratio
            return rng.choice(open_idx)
        preds = model.predict([space[i] for i in open_idx])
        return open_idx[int(np.argmax(preds))]

    def _prefit_enabled(self) -> bool:
        """memory_prefit=None means auto: prefit only where the compile-time
        OOM oracle exists (TPU buffer assignment); on CPU every probe would
        return "fits", paying an engine build per group for zero pruning."""
        if self.cfg.memory_prefit is not None:
            return self.cfg.memory_prefit
        from ..ops.registry import on_tpu
        return on_tpu()

    def _memory_prefit(self, space: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """Compile-only HBM prefit (config ``memory_prefit``): XLA buffer
        assignment is an exact fit/OOM oracle on the target backend, so
        provably-OOM candidates never spawn an experiment. Prunes
        monotonically — once a micro-batch OOMs at a given (stage, remat),
        every larger micro-batch there is pruned unprobed — and annotates
        survivors with ``predicted_bytes``. Any unexpected prefit failure
        keeps the candidate (the experiment itself remains the arbiter)."""
        def probe(cand):
            # through _measure so exp_isolation/exp_timeout protect the prefit
            # exactly like an experiment (a builder that hard-aborts or hangs
            # must not kill the search before it starts)
            try:
                return self._measure(cand, 0, compile_only=True)
            except Exception as e:  # noqa: BLE001 — prefit never kills a search
                logger.warning(f"autotune prefit error for {cand}: {e}")
                # NOT skip_prefit: a transient probe failure says nothing
                # about whether a fused program exists, so it must not bail
                # the whole prefit (and discard other groups' proven prunes)
                return {"status": "probe_error"}

        def note(cand, res):
            if res.get("predicted_bytes") is not None:
                self.prefit_predicted_bytes[self._cand_key(cand)] = \
                    res["predicted_bytes"]

        by_group: Dict[Any, List[Dict[str, Any]]] = {}
        for c in space:
            by_group.setdefault(
                (c.get("zero_stage"), c.get("remat")), []).append(c)
        pruned: set = set()
        for group in by_group.values():
            group.sort(key=lambda c: c["train_micro_batch_size_per_gpu"])
            # monotone fit boundary, found by bisection from the top: if the
            # LARGEST micro-batch fits (the common case) the whole group is
            # cleared with ONE compile; otherwise ~log2(len) probes locate
            # the first OOM and everything at/above it is pruned unprobed
            lo, hi = 0, len(group) - 1
            res = probe(group[hi])
            if res["status"] == "fits":
                note(group[hi], res)
                continue
            if res["status"] == "skip_prefit":
                # no fused program exists — a base-config property (gas>1,
                # host-offload optimizer), not a candidate property: every
                # further probe would pay an engine build for the same answer
                logger.info("autotune prefit: no fused one-program step for "
                            "this config — prefit skipped")
                return space
            if res["status"] != "oom":
                # probe_error / build failure / backend hiccup: only a
                # compile-proven OOM may prune — experiments decide this
                # group, and other groups' proven prunes are kept
                continue
            first_oom = hi  # group[hi] OOMed; find the boundary below it
            while lo < first_oom:
                mid = (lo + first_oom) // 2
                r = probe(group[mid])
                if r["status"] == "oom":
                    first_oom = mid
                elif r["status"] == "fits":
                    note(group[mid], r)
                    lo = mid + 1
                else:  # inconclusive mid-search: stop pruning below this point
                    break
            for cand in group[first_oom:]:
                pruned.add(id(cand))
                logger.info(f"autotune prefit: pruned {cand} (compile OOM)")
        return [c for c in space if id(c) not in pruned]

    def _enable_compile_cache(self) -> Callable[[], None]:
        """Point JAX's persistent compilation cache at results_dir for the
        search (unless the user already configured one). Fresh engines and
        spawned children share NO in-memory jit cache, so this is the only
        mechanism by which a prefit compile actually warms the matching
        experiment's compile — set as env so exp_isolation children inherit.
        Returns an undo() restoring prior state so the redirect does not
        outlive the search (production compiles must not land in a
        tuner-owned, disposable directory)."""
        if (os.environ.get("JAX_COMPILATION_CACHE_DIR")
                or getattr(jax.config, "jax_compilation_cache_dir", None)):
            return lambda: None  # user's cache wins — search compiles warm it
        path = os.path.join(self.cfg.results_dir, "jax_cache")
        os.makedirs(path, exist_ok=True)
        os.environ["JAX_COMPILATION_CACHE_DIR"] = path
        prev = getattr(jax.config, "jax_compilation_cache_dir", None)
        applied = False
        try:
            jax.config.update("jax_compilation_cache_dir", path)  # this process
            applied = True
        except Exception as e:  # pragma: no cover — cache is an optimization
            logger.warning(f"autotune: persistent compile cache unavailable: {e}")

        def undo() -> None:
            os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
            if applied:
                try:
                    jax.config.update("jax_compilation_cache_dir", prev)
                except Exception:  # pragma: no cover
                    pass
        return undo

    def tune(self, steps: int = 3) -> Optional[Dict[str, Any]]:
        assert self.model_builder is not None, "model_builder is required to tune"
        # the cache's one job is warming the prefit→experiment compile pair;
        # without a prefit it is pure disk I/O + a global env mutation
        undo_cache = (self._enable_compile_cache() if self._prefit_enabled()
                      else (lambda: None))
        try:
            return self._tune_inner(steps)
        finally:
            undo_cache()

    def _tune_inner(self, steps: int) -> Optional[Dict[str, Any]]:
        space = self._order(self.experiment_space())
        if self._prefit_enabled():
            space = self._memory_prefit(space)
        adaptive = self.cfg.tuner_type == "model_based"
        if not adaptive:
            space = space[:self.cfg.tuner_num_trials]
        model, rng = CostModel(), random.Random(0)
        visited: set = set()
        stagnant = 0
        for i in range(min(len(space), self.cfg.tuner_num_trials)):
            if adaptive and i >= 2:  # INIT_NUM seed measurements, then SMBO
                idx = self._next_candidates(space, visited, model, rng)
                if idx is None:
                    break
            else:
                idx = i
            visited.add(idx)
            cand = space[idx]
            exp = _Experiment(i, cand)
            self.exps.append(exp)
            self._run_experiment(exp, steps)
            if exp.status == "done" and (self.best is None
                                         or exp.metric_val > self.best.metric_val):
                self.best = exp
                stagnant = 0
            else:
                stagnant += 1
            if adaptive:
                done = [(e.config, e.metric_val) for e in self.exps
                        if e.status == "done"]
                if len(done) >= 2:
                    model.fit([c for c, _ in done], [v for _, v in done])
            logger.info(f"autotune exp {i}: {cand} -> {exp.status} "
                        f"metric={exp.metric_val}")
            if stagnant >= self.cfg.tuner_early_stopping:
                logger.info("autotune early stopping")
                break
        self._write_results()
        return None if self.best is None else self.best.config

    def _write_results(self) -> None:
        os.makedirs(self.cfg.results_dir, exist_ok=True)
        with open(os.path.join(self.cfg.results_dir, "exps.json"), "w") as f:
            json.dump([e.record() for e in self.exps], f, indent=2)
        if self.best is not None:
            with open(os.path.join(self.cfg.results_dir, "best.json"), "w") as f:
                json.dump(self.best.record(), f, indent=2)

    def get_best_space_records(self) -> Dict[str, Any]:
        """Reference get_best_space_records: per-stage best."""
        per_stage: Dict[str, Any] = {}
        for e in self.exps:
            if e.status != "done":
                continue
            key = f"z{e.config['zero_stage']}"
            if key not in per_stage or e.metric_val > per_stage[key]["metric_val"]:
                per_stage[key] = e.record()
        return per_stage
