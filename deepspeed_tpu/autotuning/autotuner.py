"""Autotuner — searches micro-batch size × ZeRO stage × remat policy.

Reference: ``deepspeed/autotuning/autotuner.py:42 Autotuner`` +
``scheduler.py:32 ResourceManager`` + ``tuner/{grid_search,random,
model_based}``. The reference forks whole training jobs per experiment over
the launcher; on TPU (single-controller SPMD) each experiment is an
in-process engine build + a few timed steps — the search logic and result
layout carry over, the multi-node experiment scheduler collapses away.

Search space (reference tune_space): ZeRO stage ∈ {0,1,2,3}, micro-batch ∈
powers of two up to the HBM ceiling (OOM candidates are caught and marked
infeasible, the reference's "error" exp status), remat on/off. Metric:
latency | throughput | flops (reference autotuning config metric).
"""

import itertools
import json
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional

import jax

from ..utils.logging import logger
from .config import AutotuningConfig


class _Experiment:

    def __init__(self, exp_id: int, config: Dict[str, Any]):
        self.exp_id = exp_id
        self.config = config
        self.status = "pending"  # pending | done | error
        self.metric_val: Optional[float] = None
        self.error: Optional[str] = None

    def record(self) -> Dict[str, Any]:
        return {"exp_id": self.exp_id, "config": self.config, "status": self.status,
                "metric_val": self.metric_val, "error": self.error}


class Autotuner:

    def __init__(self, base_config: Dict[str, Any],
                 tuning_config: Optional[AutotuningConfig] = None,
                 model_builder: Optional[Callable] = None):
        """model_builder() -> (model, params); each experiment builds a fresh
        engine from base_config overridden with the candidate's knobs."""
        self.base_config = dict(base_config)
        self.cfg = tuning_config or AutotuningConfig(
            **base_config.get("autotuning", {"enabled": True}))
        self.model_builder = model_builder
        self.exps: List[_Experiment] = []
        self.best: Optional[_Experiment] = None

    # ---- search space (reference _generate_experiments) ----

    def _micro_batch_candidates(self) -> List[int]:
        lo = max(1, self.cfg.min_train_micro_batch_size_per_gpu)
        hi = self.cfg.max_train_micro_batch_size_per_gpu or lo * 16
        out, mb = [], lo
        while mb <= hi and len(out) < self.cfg.num_tuning_micro_batch_sizes:
            out.append(mb)
            mb *= 2
        return out

    def _zero_candidates(self) -> List[int]:
        if self.cfg.zero_stages:
            return list(self.cfg.zero_stages)
        return [0, 1, 2, 3]

    def experiment_space(self) -> List[Dict[str, Any]]:
        space = []
        for mb, stage, remat in itertools.product(
                self._micro_batch_candidates(), self._zero_candidates(), [False, True]):
            space.append({"train_micro_batch_size_per_gpu": mb,
                          "zero_stage": stage, "remat": remat})
        return space

    # ---- tuner orderings (reference tuner/{grid_search,random,model_based}) ----

    def _order(self, space: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        kind = self.cfg.tuner_type
        if kind == "random":
            rng = random.Random(0)
            space = list(space)
            rng.shuffle(space)
            return space
        if kind == "model_based":
            # cheap surrogate: larger micro-batch and lower stage first
            # (higher predicted throughput), refine from measurements
            return sorted(space, key=lambda c: (-c["train_micro_batch_size_per_gpu"],
                                                c["zero_stage"], c["remat"]))
        return space  # gridsearch

    # ---- experiment runner (reference scheduler.run_job, in-process) ----

    def _run_experiment(self, exp: _Experiment, steps: int) -> None:
        import deepspeed_tpu
        from ..comm.mesh import reset_mesh_context
        import jax.numpy as jnp
        import numpy as np

        cand = exp.config
        cfg = json.loads(json.dumps(self.base_config))  # deep copy; exps must not alias
        cfg.pop("autotuning", None)
        mb = cand["train_micro_batch_size_per_gpu"]
        cfg["train_micro_batch_size_per_gpu"] = mb
        cfg.pop("train_batch_size", None)
        cfg["gradient_accumulation_steps"] = cfg.get("gradient_accumulation_steps", 1)
        cfg.setdefault("zero_optimization", {})["stage"] = cand["zero_stage"]
        if cand["remat"]:
            cfg["activation_checkpointing"] = {"remat_policy": "nothing_saveable"}
        try:
            reset_mesh_context()
            model, params = self.model_builder()
            engine, *_ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                                  config=cfg)
            hidden = np.asarray(jax.tree_util.tree_leaves(params)[0]).shape[0]
            bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
            x = jnp.ones((bs, hidden), jnp.float32)
            y = jnp.zeros_like(x)
            # warmup (compile), then timed steps
            loss = engine.forward(x, y)
            engine.backward(loss)
            engine.step()
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = engine.forward(x, y)
                engine.backward(loss)
                engine.step()
            float(loss)  # host sync closes the timing region
            dt = (time.perf_counter() - t0) / steps
            if self.cfg.metric == "latency":
                exp.metric_val = -dt  # maximize
            else:  # throughput (samples/s); flops metric folds into this rank
                exp.metric_val = engine.train_batch_size() / dt
            exp.status = "done"
        except Exception as e:  # infeasible config (OOM etc.)
            exp.status = "error"
            exp.error = f"{type(e).__name__}: {e}"

    # ---- main loop (reference autotuner.tune) ----

    def tune(self, steps: int = 3) -> Optional[Dict[str, Any]]:
        assert self.model_builder is not None, "model_builder is required to tune"
        space = self._order(self.experiment_space())
        space = space[:self.cfg.tuner_num_trials]
        stagnant = 0
        for i, cand in enumerate(space):
            exp = _Experiment(i, cand)
            self.exps.append(exp)
            self._run_experiment(exp, steps)
            if exp.status == "done" and (self.best is None
                                         or exp.metric_val > self.best.metric_val):
                self.best = exp
                stagnant = 0
            else:
                stagnant += 1
            logger.info(f"autotune exp {i}: {cand} -> {exp.status} "
                        f"metric={exp.metric_val}")
            if stagnant >= self.cfg.tuner_early_stopping:
                logger.info("autotune early stopping")
                break
        self._write_results()
        return None if self.best is None else self.best.config

    def _write_results(self) -> None:
        os.makedirs(self.cfg.results_dir, exist_ok=True)
        with open(os.path.join(self.cfg.results_dir, "exps.json"), "w") as f:
            json.dump([e.record() for e in self.exps], f, indent=2)
        if self.best is not None:
            with open(os.path.join(self.cfg.results_dir, "best.json"), "w") as f:
                json.dump(self.best.record(), f, indent=2)

    def get_best_space_records(self) -> Dict[str, Any]:
        """Reference get_best_space_records: per-stage best."""
        per_stage: Dict[str, Any] = {}
        for e in self.exps:
            if e.status != "done":
                continue
            key = f"z{e.config['zero_stage']}"
            if key not in per_stage or e.metric_val > per_stage[key]["metric_val"]:
                per_stage[key] = e.record()
        return per_stage
