"""Per-request span timelines in bounded ring buffers.

A *span* is one closed interval of a request's life on the host clock:
queue wait, a prefill chunk, one fused K-wave, a journal append, the
finish. Spans carry a small ``args`` dict (wave K, wave size, spec
accept counts, chunk tokens...) and are recorded with plain
``time.monotonic()`` timestamps — recording never touches the device.

Storage is bounded three ways so a long-lived daemon cannot grow:

- at most ``max_requests`` live timelines (oldest evicted first),
- at most ``max_spans_per_request`` spans per timeline (a deque ring —
  a pathological million-token request keeps its most recent spans),
- a global ``max_waves`` ring of wave/global spans for the bulk
  ``GET /debug/trace`` Chrome export.

Export formats:

- :meth:`RequestTracer.timeline` — the per-uid JSON served by
  ``GET /requests/<uid>/trace``: ordered spans with ``t0``/``t1``
  relative to submit, plus the raw monotonic anchors.
- :meth:`RequestTracer.chrome_trace` — Chrome ``trace_event`` JSON
  (``ph: "X"`` complete events, microsecond timestamps) loadable in
  Perfetto / chrome://tracing, one ``tid`` lane per request.
"""

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional


class _Timeline:
    __slots__ = ("uid", "t_submit", "spans", "events", "done")

    def __init__(self, uid: str, t_submit: float, max_spans: int):
        self.uid = uid
        self.t_submit = t_submit
        self.spans = deque(maxlen=max_spans)
        self.events = deque(maxlen=max_spans)
        self.done = False


class RequestTracer:
    """Bounded recorder of request lifecycles and global daemon spans."""

    def __init__(self, max_requests: int = 512,
                 max_spans_per_request: int = 512,
                 max_waves: int = 2048):
        self._lock = threading.Lock()
        self._max_requests = int(max_requests)
        self._max_spans = int(max_spans_per_request)
        self._timelines: "OrderedDict[str, _Timeline]" = OrderedDict()
        self._waves = deque(maxlen=int(max_waves))

    # ---- recording (hot path: one lock, one deque append) ----

    def begin(self, uid: str, t_submit: Optional[float] = None) -> None:
        """Open a timeline at submit time. Idempotent per uid (a replayed
        request re-begins and keeps its original timeline)."""
        t = time.monotonic() if t_submit is None else t_submit
        with self._lock:
            tl = self._timelines.get(uid)
            if tl is not None:
                self._timelines.move_to_end(uid)
                return
            tl = _Timeline(uid, t, self._max_spans)
            self._timelines[uid] = tl
            while len(self._timelines) > self._max_requests:
                self._timelines.popitem(last=False)

    def span(self, uid: str, name: str, t0: float, t1: float,
             args: Optional[dict] = None) -> None:
        """Record a closed [t0, t1] interval for a request."""
        with self._lock:
            tl = self._timelines.get(uid)
            if tl is None:
                return
            tl.spans.append((name, t0, t1, args))

    def event(self, uid: str, name: str, t: Optional[float] = None,
              args: Optional[dict] = None) -> None:
        """Record an instant (shed, expiry, quarantine, resume...)."""
        t = time.monotonic() if t is None else t
        with self._lock:
            tl = self._timelines.get(uid)
            if tl is None:
                return
            tl.events.append((name, t, args))

    def finish(self, uid: str, name: str = "finish",
               t: Optional[float] = None,
               args: Optional[dict] = None) -> None:
        t = time.monotonic() if t is None else t
        with self._lock:
            tl = self._timelines.get(uid)
            if tl is None:
                return
            tl.events.append((name, t, args))
            tl.done = True

    def global_span(self, name: str, t0: float, t1: float,
                    args: Optional[dict] = None,
                    uids: Optional[List[str]] = None) -> None:
        """Record a daemon-level interval (a fused wave, a restart) into
        the global ring, optionally mirrored onto member timelines."""
        with self._lock:
            self._waves.append((name, t0, t1, args))
            if uids:
                for uid in uids:
                    tl = self._timelines.get(uid)
                    if tl is not None:
                        tl.spans.append((name, t0, t1, args))

    # ---- export (cold path) ----

    def has(self, uid: str) -> bool:
        with self._lock:
            return uid in self._timelines

    def timeline(self, uid: str) -> Optional[dict]:
        """Per-request JSON timeline: spans sorted by start, times both
        absolute (monotonic) and relative to submit."""
        with self._lock:
            tl = self._timelines.get(uid)
            if tl is None:
                return None
            spans = list(tl.spans)
            events = list(tl.events)
            t_submit, done = tl.t_submit, tl.done
        spans.sort(key=lambda s: s[1])
        out_spans = []
        for name, t0, t1, args in spans:
            d = {"name": name, "t0": t0 - t_submit, "t1": t1 - t_submit,
                 "dur_s": t1 - t0, "t0_monotonic": t0, "t1_monotonic": t1}
            if args:
                d["args"] = dict(args)
            out_spans.append(d)
        out_events = []
        for name, t, args in sorted(events, key=lambda e: e[1]):
            d = {"name": name, "t": t - t_submit, "t_monotonic": t}
            if args:
                d["args"] = dict(args)
            out_events.append(d)
        return {"uid": uid, "t_submit_monotonic": t_submit, "done": done,
                "spans": out_spans, "events": out_events}

    def chrome_trace(self, last: Optional[int] = None) -> dict:
        """Chrome ``trace_event`` JSON of recent global spans plus every
        live timeline, one ``tid`` lane per request (pid 1 = daemon)."""
        with self._lock:
            waves = list(self._waves)
            tls = [(tl.uid, tl.t_submit, list(tl.spans), list(tl.events))
                   for tl in self._timelines.values()]
        if last is not None and last >= 0:
            waves = waves[-last:]
        events = []
        for name, t0, t1, args in waves:
            ev = {"name": name, "ph": "X", "pid": 1, "tid": 0,
                  "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6}
            if args:
                ev["args"] = dict(args)
            events.append(ev)
        for tid, (uid, t_submit, spans, instants) in enumerate(tls, start=1):
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tid, "args": {"name": f"req {uid}"}})
            for name, t0, t1, args in spans:
                ev = {"name": name, "ph": "X", "pid": 1, "tid": tid,
                      "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6}
                if args:
                    ev["args"] = dict(args)
                events.append(ev)
            for name, t, args in instants:
                ev = {"name": name, "ph": "i", "pid": 1, "tid": tid,
                      "ts": t * 1e6, "s": "t"}
                if args:
                    ev["args"] = dict(args)
                events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def reset(self) -> None:
        with self._lock:
            self._timelines.clear()
            self._waves.clear()


_TRACER = RequestTracer()


def get_tracer() -> RequestTracer:
    """The process-wide tracer (serving injects its own sized instance)."""
    return _TRACER
