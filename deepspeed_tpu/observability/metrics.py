"""Process-wide metrics registry: counters, gauges, log-bucketed histograms.

Design constraints (the scheduler tick is the hottest caller):

- ``record()``/``inc()`` are allocation-light: a bisect into a precomputed
  edge list and one numpy bucket bump under a per-metric lock. No dict
  lookups on the hot path — callers pre-resolve metric handles once.
- Histograms are log-bucketed with FIXED-size numpy count arrays sized at
  construction (default: 10 buckets/decade), so memory is bounded no
  matter how many samples land. Quantiles (p50/p90/p99...) are derived at
  READ time from the bucket counts — recording never sorts or stores raw
  samples. A derived quantile is exact to within one bucket (relative
  error ≤ ``10**(1/buckets_per_decade)`` ≈ 1.26× at the default), which
  is the standard Prometheus-histogram contract.
- Everything renders to Prometheus text exposition format
  (``render_prometheus``) and to the ``monitor/`` fan-out's
  ``(name, value, step)`` event schema (``to_events``), so serving and
  training share one pipeline.

The module-level registry (:func:`get_registry`) is process-wide on
purpose: the serving scheduler, engine dispatch boundaries, journal, and
supervisor all record into one namespace, and ``GET /metrics`` scrapes
one coherent snapshot. Tests and benches needing isolation construct
their own :class:`MetricsRegistry` (or diff ``snapshot()`` deltas).
"""

import os
import threading
from bisect import bisect_left
from math import ceil, log10, sqrt
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


def _fmt(v) -> str:
    """Prometheus sample value: shortest round-trippable decimal."""
    if v != v:  # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return format(float(v), ".10g")


def _label_str(labels: Optional[dict], extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    items = tuple(sorted((labels or {}).items())) + extra
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


class Counter:
    """Monotonic counter. ``inc`` only ever adds a non-negative amount."""

    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name: str, help: str = "", labels: Optional[dict] = None):
        self.name, self.help, self.labels = name, help, labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self._value}

    def render(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} counter")
        lines.append(f"{self.name}{_label_str(self.labels)} {_fmt(self._value)}")
        return lines


class Gauge:
    """Point-in-time value (queue depth, occupancy, adaptive K)."""

    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name: str, help: str = "", labels: Optional[dict] = None):
        self.name, self.help, self.labels = name, help, labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._value}

    def render(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} gauge")
        lines.append(f"{self.name}{_label_str(self.labels)} {_fmt(self._value)}")
        return lines


def _log_edges(lo: float, hi: float, buckets_per_decade: int) -> List[float]:
    """Upper bucket edges ``lo * 10**(i/bpd)`` covering [lo, hi]."""
    if not (0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    bpd = int(buckets_per_decade)
    if bpd < 1:
        raise ValueError("buckets_per_decade must be >= 1")
    n = int(ceil((log10(hi) - log10(lo)) * bpd + 1e-9)) + 1
    return [lo * 10.0 ** (i / bpd) for i in range(n)]


def quantiles_from_counts(edges: Sequence[float], counts,
                          qs: Iterable[float]) -> List[Optional[float]]:
    """Derive quantiles from log-bucket counts (``counts`` has one extra
    trailing overflow bucket beyond ``edges``). Interior buckets resolve
    to their geometric midpoint — halving the worst-case log error; the
    underflow bucket resolves to its upper edge, the overflow bucket to
    the last edge. Returns None per-q when the histogram is empty."""
    counts = np.asarray(counts)
    total = int(counts.sum())
    if total == 0:
        return [None for _ in qs]
    cum = np.cumsum(counts)
    out = []
    for q in qs:
        target = q * total
        i = int(np.searchsorted(cum, target, side="left"))
        i = min(i, len(counts) - 1)
        if i == 0:
            out.append(float(edges[0]))
        elif i >= len(edges):  # overflow bucket: clamp to the last edge
            out.append(float(edges[-1]))
        else:
            out.append(float(sqrt(edges[i - 1] * edges[i])))
    return out


class Histogram:
    """Log-bucketed histogram over (0, inf) with fixed numpy bucket counts.

    ``counts`` has ``len(edges) + 1`` slots: ``counts[i]`` holds samples in
    ``(edges[i-1], edges[i]]`` (``(0, edges[0]]`` for i=0) and the final
    slot is the +Inf overflow bucket. Recording is a bisect + one bump
    under the metric lock — cheap enough for the scheduler tick."""

    __slots__ = ("name", "help", "labels", "edges", "counts",
                 "_sum", "_count", "_lock")

    def __init__(self, name: str, help: str = "", lo: float = 1e-6,
                 hi: float = 1e3, buckets_per_decade: int = 10,
                 labels: Optional[dict] = None):
        self.name, self.help, self.labels = name, help, labels
        self.edges = _log_edges(lo, hi, buckets_per_decade)
        self.counts = np.zeros(len(self.edges) + 1, dtype=np.int64)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def record(self, v: float) -> None:
        v = float(v)
        if v < 0:
            v = 0.0  # clock skew guard: a negative duration is a 0 sample
        idx = bisect_left(self.edges, v) if v > 0 else 0
        with self._lock:
            self.counts[idx] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> Optional[float]:
        return self._sum / self._count if self._count else None

    def quantile(self, q: float) -> Optional[float]:
        return self.percentiles((q, ))[0]

    def percentiles(self, qs: Iterable[float]) -> List[Optional[float]]:
        with self._lock:
            counts = self.counts.copy()
        return quantiles_from_counts(self.edges, counts, qs)

    def snapshot(self) -> dict:
        with self._lock:
            return {"type": "histogram", "count": self._count,
                    "sum": self._sum, "counts": self.counts.copy(),
                    "edges": self.edges}

    def render(self) -> List[str]:
        with self._lock:
            counts = self.counts.copy()
            s, c = self._sum, self._count
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} histogram")
        cum = 0
        for edge, n in zip(self.edges, counts[:-1]):
            cum += int(n)
            le = _label_str(self.labels, (("le", _fmt(edge)), ))
            lines.append(f"{self.name}_bucket{le} {cum}")
        cum += int(counts[-1])
        le = _label_str(self.labels, (("le", "+Inf"), ))
        lines.append(f"{self.name}_bucket{le} {cum}")
        lab = _label_str(self.labels)
        lines.append(f"{self.name}_sum{lab} {_fmt(s)}")
        lines.append(f"{self.name}_count{lab} {c}")
        return lines


class MetricsRegistry:
    """Named metric store. ``counter``/``gauge``/``histogram`` return the
    existing instance on re-request (handles are meant to be resolved once
    and kept), raising if the name is already bound to another type.

    A metric with ``labels`` is one SERIES of a metric family: the store
    key is ``name{labels}``, so ``counter("x", labels={"k": "a"})`` and
    ``counter("x", labels={"k": "b"})`` coexist and render under one
    ``# TYPE x`` header (compile keys, goodput categories)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_make(self, cls, name, kwargs):
        key = name + _label_str(kwargs.get("labels"))
        with self._lock:
            m = self._metrics.get(key)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(f"metric {key!r} already registered as "
                                    f"{type(m).__name__}, not {cls.__name__}")
                return m
            m = cls(name, **kwargs)
            self._metrics[key] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        return self._get_or_make(Counter, name,
                                 dict(help=help, labels=labels))

    def gauge(self, name: str, help: str = "",
              labels: Optional[dict] = None) -> Gauge:
        return self._get_or_make(Gauge, name, dict(help=help, labels=labels))

    def histogram(self, name: str, help: str = "", lo: float = 1e-6,
                  hi: float = 1e3, buckets_per_decade: int = 10,
                  labels: Optional[dict] = None) -> Histogram:
        return self._get_or_make(
            Histogram, name,
            dict(help=help, lo=lo, hi=hi,
                 buckets_per_decade=buckets_per_decade, labels=labels))

    def get(self, name: str, labels: Optional[dict] = None):
        return self._metrics.get(name + _label_str(labels))

    def series(self, name: str) -> List[object]:
        """Every registered series of a metric family, labeled or not."""
        with self._lock:
            items = sorted(self._metrics.items())
        return [m for _, m in items if m.name == name]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, dict]:
        """Point-in-time copy of every metric — diffable, so benches can
        compute interval percentiles from before/after deltas."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def reset(self) -> None:
        """Zero every metric in place (handles stay valid) — tests and
        bench reruns in one process."""
        with self._lock:
            items = list(self._metrics.values())
        for m in items:
            with m._lock:
                if isinstance(m, Histogram):
                    m.counts[:] = 0
                    m._sum, m._count = 0.0, 0
                else:
                    m._value = 0.0

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 (one scrape body).
        Labeled series of the same family (sort-adjacent, since the store
        key is ``name{labels}``) share one ``# HELP``/``# TYPE`` header."""
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        seen_families = set()
        for _, m in items:
            rendered = m.render()
            if m.name in seen_families:
                rendered = [ln for ln in rendered if not ln.startswith("#")]
            else:
                seen_families.add(m.name)
            lines.extend(rendered)
        return "\n".join(lines) + "\n" if lines else ""

    def write_textfile(self, path: str) -> str:
        """Prometheus *textfile* export for processes with no HTTP server
        (training runs): render the full registry and atomically replace
        ``path`` (write to ``path + ".tmp"`` then ``os.replace``), so a
        node-exporter-style collector or ``ds_top --file`` never observes
        a torn body. Recreates the parent directory if it was deleted."""
        body = self.render_prometheus()
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # unique tmp per writer: a shared ".tmp" would let one writer's
        # replace publish another's half-written body
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "w") as f:
            f.write(body)
            f.flush()
        os.replace(tmp, path)
        return path

    def to_events(self, step: int, prefix: str = "",
                  percentiles: Sequence[float] = (0.5, 0.9, 0.99)):
        """Bridge into the ``monitor/`` fan-out: the same
        ``(name, value, step)`` triples training writers consume.
        Histograms emit ``_count``/``_mean`` plus one derived ``_pNN`` per
        requested percentile (skipped while empty)."""
        with self._lock:
            items = sorted(self._metrics.items())
        events = []
        for name, m in items:
            if isinstance(m, Histogram):
                if not m.count:
                    continue
                events.append((f"{prefix}{name}_count", float(m.count), step))
                events.append((f"{prefix}{name}_mean", float(m.mean), step))
                for q, v in zip(percentiles, m.percentiles(percentiles)):
                    if v is not None:
                        events.append(
                            (f"{prefix}{name}_p{int(round(q * 100))}",
                             float(v), step))
            else:
                events.append((f"{prefix}{name}", float(m.value), step))
        return events


def histogram_delta(before: Optional[dict], after: dict) -> dict:
    """Interval view of one histogram between two ``snapshot()`` entries
    (``before`` may be None → the interval starts at zero)."""
    counts = np.asarray(after["counts"]).copy()
    count, total = int(after["count"]), float(after["sum"])
    if before is not None:
        counts -= np.asarray(before["counts"])
        count -= int(before["count"])
        total -= float(before["sum"])
    return {"type": "histogram", "edges": after["edges"], "counts": counts,
            "count": count, "sum": total}


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem records into."""
    return _REGISTRY
