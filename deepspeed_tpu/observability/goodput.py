"""Wall-clock goodput ledger: attribute every training second to a cause.

``GoodputLedger`` partitions the wall clock of a training process into
exhaustive, non-overlapping categories and exports them as the labeled
counter family ``ds_goodput_seconds_total{category=...}`` plus a derived
``ds_goodput_fraction`` gauge (useful-step share). The invariant the
acceptance tests check: the categories SUM to the elapsed wall clock
(within the slack of whatever has elapsed since the last attribution
point), so "where did my training day go" is answerable from one scrape.

Attribution model — two complementary mechanisms:

- ``mark(category)``: attribute everything since the previous mark (the
  *cursor*) to ``category``. The engine calls ``mark("useful_step")`` at
  each optimizer-step boundary, so in steady state the whole step wall
  (dispatch + device wait + dataloader) lands in ``useful_step``.
- ``span(category)``: a context manager for excursions with clear
  boundaries (checkpoint save/load, anomaly rollback, the async-window
  host fetch). A span records its own duration directly AND banks it as
  *foreign* time, which the next ``mark`` subtracts from the cursor
  interval — the same second is never counted twice. Nested spans fold
  into the outermost category (a rollback that internally loads a
  checkpoint is all "anomaly_rollback").

Compile time has no clean boundary of its own — it surfaces as an
unusually long step call — so the compile watch (observability/xla.py)
reports measured compile seconds via ``note_compile``; the next ``mark``
carves that much out of the interval into "compile" before attributing
the remainder. "restart" closes engine construction + auto-resume time
(one ``mark("restart")`` at the end of ``__init__``).

The ledger is host-side only and lock-cheap: one ``perf_counter`` and a
few float ops per mark/span. A test can inject a fake ``clock``.
"""

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from .metrics import MetricsRegistry, get_registry

CATEGORIES = (
    "useful_step",      # optimizer-step wall (dispatch + device + data wait)
    "compile",          # jit trace + XLA compile (from the compile watch)
    "host_sync_stall",  # blocking device→host fetches (async-window drain)
    "checkpoint_save",
    "checkpoint_load",
    "anomaly_rollback",  # sentry-triggered restore-to-last-good
    "restart",          # engine construction, auto-resume, warm restart
    "param_gather_stall",  # ZeRO-3 whole-model gather (full_params/export)
)

_HELP = ("Wall-clock seconds attributed to each training-time category "
         "(categories sum to elapsed wall clock)")


class GoodputLedger:
    """See module docstring. One instance per training engine."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 clock=time.perf_counter):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self._clock = clock
        # eager per-category series so a scrape always shows all categories
        # (a zero is information; an absent series is a parse special-case)
        self._counters = {
            c: reg.counter("ds_goodput_seconds_total", _HELP,
                           labels={"category": c})
            for c in CATEGORIES
        }
        self.fraction = reg.gauge(
            "ds_goodput_fraction",
            "useful_step share of all attributed wall-clock seconds")
        self._lock = threading.RLock()
        now = clock()
        self._t0 = now
        self._cursor = now
        self._foreign = 0.0          # span seconds already attributed since cursor
        self._pending_compile = 0.0  # compile seconds awaiting the next mark
        self._span_depth = 0

    # -- recording ---------------------------------------------------------

    def add(self, category: str, seconds: float) -> None:
        """Directly attribute ``seconds`` to ``category`` (no cursor move)."""
        if seconds > 0:
            self._counters[category].inc(seconds)

    def note_compile(self, seconds: float) -> None:
        """Compile watch callback: carve this much out of the next marked
        interval into the "compile" category."""
        if seconds > 0:
            with self._lock:
                self._pending_compile += seconds

    @contextmanager
    def span(self, category: str):
        """Attribute the enclosed wall time to ``category`` and bank it so
        the next ``mark`` doesn't attribute it again. Nested spans record
        nothing themselves — the outermost category wins."""
        with self._lock:
            self._span_depth += 1
            nested = self._span_depth > 1
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            with self._lock:
                self._span_depth -= 1
                if not nested:
                    self.add(category, dt)
                    self._foreign += max(0.0, dt)

    def mark(self, category: str = "useful_step") -> float:
        """Attribute the interval since the previous mark to ``category``
        (minus banked span time, minus pending compile seconds which go to
        "compile"). Returns the raw interval length."""
        now = self._clock()
        with self._lock:
            elapsed = max(0.0, now - self._cursor)
            residual = max(0.0, elapsed - self._foreign)
            carved = min(self._pending_compile, residual)
            if carved > 0:
                self.add("compile", carved)
                self._pending_compile -= carved
            self.add(category, residual - carved)
            self._cursor = now
            self._foreign = 0.0
        return elapsed

    # -- derived views -----------------------------------------------------

    def totals(self) -> Dict[str, float]:
        return {c: m.value for c, m in self._counters.items()}

    def attributed_seconds(self) -> float:
        return sum(m.value for m in self._counters.values())

    def wall_seconds(self) -> float:
        return self._clock() - self._t0

    def goodput_fraction(self) -> float:
        total = self.attributed_seconds()
        return self._counters["useful_step"].value / total if total else 0.0

    def publish(self) -> float:
        """Refresh the derived gauge (called at the registry-publish
        cadence, i.e. the async-window drain)."""
        f = self.goodput_fraction()
        self.fraction.set(f)
        return f
