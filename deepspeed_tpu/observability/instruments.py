"""Pre-resolved metric/tracer handles for the serving hot path.

The scheduler tick must not pay a registry dict lookup per event, so
every metric it records is resolved ONCE here at construction; the call
sites then touch plain attributes. The scheduler holds one
:class:`ServingInstruments` (or None with the ``observability`` config
block disabled) and every recording site is guarded by a single
``if self._obs is not None``.

A custom ``registry``/``tracer`` is injectable for test isolation; the
defaults are the process-wide singletons so the HTTP ``GET /metrics``
scrape, the engine/journal/supervisor instrumentation, and the
``monitor/`` bridge all see one namespace.
"""

import time
from typing import Iterable, Optional

from .metrics import MetricsRegistry, get_registry
from .tracing import RequestTracer
from .profiler import ProfilerCapture

# Latency histograms share one shape: 1µs..1000s at 10 buckets/decade
# (91 buckets) — wide enough for a journal fsync and a 10-minute decode.
_HIST = dict(lo=1e-6, hi=1e3, buckets_per_decade=10)


class ServingInstruments:
    """Handle bundle + recording helpers for ``ServingScheduler``."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[RequestTracer] = None,
                 trace_requests: int = 512,
                 trace_spans_per_request: int = 512,
                 trace_waves: int = 2048,
                 profile_dir: Optional[str] = None,
                 profile_max_seconds: float = 60.0):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self.tracer = tracer if tracer is not None else RequestTracer(
            max_requests=trace_requests,
            max_spans_per_request=trace_spans_per_request,
            max_waves=trace_waves)
        self.profiler = ProfilerCapture(profile_dir,
                                        max_seconds=profile_max_seconds)
        h, c, g = reg.histogram, reg.counter, reg.gauge
        self.ttft = h("ds_ttft_seconds",
                      "Submit to first emitted token (replays excluded)",
                      **_HIST)
        self.inter_token = h("ds_inter_token_seconds",
                             "Gap between consecutive emitted tokens of one "
                             "request", **_HIST)
        self.e2e = h("ds_request_e2e_seconds",
                     "Submit to finish for successful requests", **_HIST)
        self.queue_wait = h("ds_queue_wait_seconds",
                            "Submit to admission into the live set", **_HIST)
        self.tick = h("ds_serving_tick_seconds",
                      "One scheduler tick (admit + advance)", **_HIST)
        self.wave = h("ds_fused_wave_seconds",
                      "Fused K-step wave, dispatch to harvest", **_HIST)
        self.prefill = h("ds_prefill_chunk_seconds",
                         "One SplitFuse prefill chunk put", **_HIST)
        self.submitted = c("ds_requests_submitted_total",
                           "Requests accepted by submit()")
        self.finished = c("ds_requests_finished_total",
                          "Requests finished successfully")
        self.errored = c("ds_requests_errored_total",
                         "Requests finished with an error")
        self.cancelled = c("ds_requests_cancelled_total",
                           "Requests cancelled by the client")
        self.shed = c("ds_requests_shed_total",
                      "Requests refused at submit() by the shed policy")
        self.expired = c("ds_requests_expired_total",
                         "Requests expired on a deadline/TTL")
        self.quarantined = c("ds_requests_quarantined_total",
                             "Requests isolated by the tick-fault bisect")
        self.replayed = c("ds_requests_replayed_total",
                          "Requests re-admitted from the journal")
        self.tokens = c("ds_tokens_emitted_total",
                        "Tokens surfaced to consumers")
        self.fused_tokens = c("ds_fused_tokens_total",
                              "Decode tokens produced by fused dispatches")
        self.decode_tokens = c("ds_decode_tokens_total",
                               "All decode tokens produced")
        self.prefill_overlap = c(
            "ds_prefill_overlap_tokens_total",
            "Prefill tokens fed while a fused wave ran on device")
        self.fused_dispatches = c("ds_fused_dispatches_total",
                                  "Fused K-step dispatches issued")
        self.spec_drafted = c("ds_spec_drafted_total",
                              "Speculative tokens offered for verification")
        self.spec_accepted = c("ds_spec_accepted_total",
                               "Speculative tokens accepted")
        self.watchdog_trips = c("ds_watchdog_trips_total",
                                "Watchdog transitions into degraded")
        self.queue_depth = g("ds_queue_depth",
                             "Unadmitted requests (inbox + waiting)")
        self.live_requests = g("ds_live_requests",
                               "Requests in the live decode set")
        self.kv_free_blocks = g("ds_kv_free_blocks",
                                "Free KV cache blocks")
        self.adaptive_k = g("ds_adaptive_k",
                            "Fused window K chosen by the last adaptive "
                            "computation")
        self.fused_occupancy = g(
            "ds_fused_occupancy",
            "Fraction of decode tokens produced by fused dispatches")
        self.wave_mfu = g(
            "ds_serving_wave_mfu",
            "Model FLOPs utilization of the last fused decode wave "
            "(cost-analysis FLOPs / wall / peak_bf16_flops)")
        from .xla import peak_device_flops
        self.peak_flops = peak_device_flops()
        # per-tenant handle bundles, created lazily on first sight of a
        # tenant name — the hot path still touches plain attributes after
        # one dict hit, and an untenanted deployment allocates nothing
        self._tenants: dict = {}
        # per-adapter handle bundles (multi-LoRA serving), same lazy scheme
        self._adapters: dict = {}

    def _adapter(self, name: str):
        """Labeled series for one adapter id (``name@version``)."""
        a = self._adapters.get(name)
        if a is None:
            lbl = {"adapter": name}
            reg = self.registry
            from types import SimpleNamespace
            a = SimpleNamespace(
                tokens=reg.counter(
                    "ds_adapter_tokens_total",
                    "Tokens emitted by requests decoding with one adapter",
                    labels=lbl),
                finished=reg.counter(
                    "ds_adapter_requests_finished_total",
                    "Requests finished successfully per adapter",
                    labels=lbl))
            self._adapters[name] = a
        return a

    def adapter_token(self, adapter: str) -> None:
        self._adapter(adapter).tokens.inc()

    def _tenant(self, name: str):
        """Labeled series for one tenant, sharing the family names of the
        unlabeled aggregates (``ds_tokens_emitted_total{tenant="a"}`` sits
        next to plain ``ds_tokens_emitted_total``)."""
        t = self._tenants.get(name)
        if t is None:
            lbl = {"tenant": name}
            reg = self.registry
            from types import SimpleNamespace
            t = SimpleNamespace(
                tokens=reg.counter(
                    "ds_tokens_emitted_total",
                    "Tokens surfaced to consumers", labels=lbl),
                finished=reg.counter(
                    "ds_requests_finished_total",
                    "Requests finished successfully", labels=lbl),
                ttft=reg.histogram(
                    "ds_ttft_seconds",
                    "Submit to first emitted token (replays excluded)",
                    labels=lbl, **_HIST),
                e2e=reg.histogram(
                    "ds_request_e2e_seconds",
                    "Submit to finish for successful requests",
                    labels=lbl, **_HIST),
                queue_depth=reg.gauge(
                    "ds_tenant_queue_depth",
                    "Unadmitted requests of one tenant", labels=lbl))
            self._tenants[name] = t
        return t

    # ---- recording helpers (each: a few attribute ops + one deque/lock) ----

    def request_submitted(self, uid, t_submit: float) -> None:
        self.submitted.inc()
        self.tracer.begin(str(uid), t_submit)

    def request_replayed(self, uid, t_submit: float, n_outputs: int) -> None:
        self.replayed.inc()
        self.tracer.begin(str(uid), t_submit)
        self.tracer.event(str(uid), "replay", t_submit,
                          {"journaled_tokens": n_outputs})

    def request_admitted(self, uid, t_submit: float,
                         t_now: Optional[float] = None) -> None:
        t = time.monotonic() if t_now is None else t_now
        self.queue_wait.record(t - t_submit)
        self.tracer.span(str(uid), "queue", t_submit, t)

    def first_token(self, req_t_submit: float, t: float,
                    replayed: bool, tenant: Optional[str] = None) -> None:
        # a replayed request's TTFT spans the crash+restart — real for the
        # client but not a scheduler-latency signal, so it stays out
        if not replayed:
            self.ttft.record(t - req_t_submit)
            if tenant is not None:
                self._tenant(tenant).ttft.record(t - req_t_submit)

    def token_gap(self, dt: float) -> None:
        self.inter_token.record(dt)

    def tenant_token(self, tenant: str) -> None:
        self._tenant(tenant).tokens.inc()

    def tenant_queue_depth(self, tenant: str, depth: int) -> None:
        self._tenant(tenant).queue_depth.set(depth)

    def wave_span(self, uids: Iterable, t0: float, t1: float, K: int,
                  size: int, kind: str, drafted: int = 0,
                  accepted: int = 0, flops: float = 0.0) -> None:
        self.wave.record(t1 - t0)
        if flops > 0 and t1 > t0:
            self.wave_mfu.set(min(1.0, flops / ((t1 - t0) * self.peak_flops)))
        args = {"K": K, "size": size, "kind": kind}
        if drafted:
            args["drafted"], args["accepted"] = drafted, accepted
        self.tracer.global_span(f"fused_wave[{kind}]", t0, t1, args,
                                uids=[str(u) for u in uids])

    def prefill_span(self, uids: Iterable, t0: float, t1: float,
                     tokens: int, overlap: bool = False) -> None:
        self.prefill.record(t1 - t0)
        name = "prefill_overlap" if overlap else "prefill"
        args = {"tokens": tokens}
        for u in uids:
            self.tracer.span(str(u), name, t0, t1, args)

    def request_finished(self, uid, t_submit: float, t_done: float,
                         outcome: str, n_tokens: int,
                         replayed: bool, tenant: Optional[str] = None,
                         adapter: Optional[str] = None) -> None:
        if outcome == "ok":
            self.finished.inc()
            if tenant is not None:
                self._tenant(tenant).finished.inc()
            if adapter is not None:
                self._adapter(adapter).finished.inc()
            if not replayed:
                self.e2e.record(t_done - t_submit)
                if tenant is not None:
                    self._tenant(tenant).e2e.record(t_done - t_submit)
        elif outcome == "cancelled":
            self.cancelled.inc()
        elif outcome == "expired":
            self.expired.inc()
        else:
            self.errored.inc()
        self.tracer.finish(str(uid), "finish", t_done,
                           {"outcome": outcome, "tokens": n_tokens})

    def refresh(self, queue_depth: int, live: int, free_blocks: int,
                fused_tokens: int, decode_tokens: int) -> None:
        self.queue_depth.set(queue_depth)
        self.live_requests.set(live)
        self.kv_free_blocks.set(free_blocks)
        if decode_tokens:
            self.fused_occupancy.set(fused_tokens / decode_tokens)
