"""Guarded on-demand ``jax.profiler`` captures.

``POST /debug/profile`` maps here: start a trace capture into a
directory, bounded in duration, with at most one capture in flight per
process (a second request gets :class:`ProfilerBusy` → HTTP 409).
The capture auto-stops after ``seconds`` via a daemon timer so an
operator who fires a capture and walks away cannot leave the profiler
running forever.

``start_fn``/``stop_fn`` are injectable so unit tests (and CPU-only
environments without a working profiler backend) never import-commit to
``jax.profiler``.
"""

import os
import threading
import time
from typing import Callable, Optional


class ProfilerBusy(RuntimeError):
    """A capture is already in flight (one at a time per process)."""


def profile_dir(explicit: Optional[str] = None) -> str:
    """Resolve the capture directory: explicit > ``$DS_TPU_PROFILE_DIR`` >
    ``$XDG_CACHE_HOME/deepspeed_tpu/profiles`` (mirrors journal_dir())."""
    if explicit:
        return explicit
    env = os.environ.get("DS_TPU_PROFILE_DIR")
    if env:
        return env
    cache = os.environ.get("XDG_CACHE_HOME",
                           os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(cache, "deepspeed_tpu", "profiles")


def _jax_start(directory: str) -> None:
    import jax
    jax.profiler.start_trace(directory)


def _jax_stop() -> None:
    import jax
    jax.profiler.stop_trace()


class ProfilerCapture:
    """One-at-a-time, duration-bounded profiler capture controller."""

    def __init__(self, directory: Optional[str] = None,
                 max_seconds: float = 60.0,
                 start_fn: Callable[[str], None] = _jax_start,
                 stop_fn: Callable[[], None] = _jax_stop):
        self._dir = profile_dir(directory)
        self._max_seconds = float(max_seconds)
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._lock = threading.Lock()
        self._active: Optional[dict] = None
        self._timer: Optional[threading.Timer] = None
        self._captures = 0

    @property
    def directory(self) -> str:
        return self._dir

    @property
    def active(self) -> Optional[dict]:
        """Info dict for the in-flight capture, or None."""
        with self._lock:
            return dict(self._active) if self._active else None

    @property
    def captures(self) -> int:
        return self._captures

    def start(self, seconds: Optional[float] = None,
              directory: Optional[str] = None) -> dict:
        """Begin a capture; auto-stops after ``seconds`` (clamped to the
        configured maximum). Raises :class:`ProfilerBusy` if one is
        already running."""
        dur = self._max_seconds if seconds is None else float(seconds)
        dur = max(0.01, min(dur, self._max_seconds))
        target = directory or self._dir
        with self._lock:
            if self._active is not None:
                raise ProfilerBusy(
                    f"capture already running in {self._active['dir']}")
            os.makedirs(target, exist_ok=True)
            self._start_fn(target)
            self._captures += 1
            self._active = {"dir": target, "seconds": dur,
                            "t_start": time.monotonic()}
            self._timer = threading.Timer(dur, self.stop)
            self._timer.daemon = True
            self._timer.start()
            return dict(self._active)

    def stop(self) -> Optional[dict]:
        """Stop the in-flight capture (no-op when idle — the auto-stop
        timer and an explicit stop may race benignly)."""
        with self._lock:
            if self._active is None:
                return None
            info, self._active = self._active, None
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()
        try:
            self._stop_fn()
        finally:
            info["dur_s"] = time.monotonic() - info["t_start"]
        return info
