"""Unified serving/training observability.

Three pillars, all host-side and allocation-light (nothing here ever
touches the device — timestamps are ``time.monotonic()`` around already
existing host boundaries, honoring the async-dispatch design):

- :mod:`metrics` — a process-wide registry of counters, gauges, and
  log-bucketed histograms (fixed-size numpy bucket arrays; p50/p90/p99
  derivable at read time). Rendered as Prometheus text by the serving
  daemon's ``GET /metrics`` and bridgeable into the ``monitor/`` fan-out
  (one ``(name, value, step)`` event schema shared with training).
- :mod:`tracing` — per-request span timelines (submit → queue → admit →
  prefill chunks → fused K-waves → journal → finish) in a bounded ring,
  exportable per-uid as JSON and in bulk as Chrome ``trace_event`` JSON
  (loadable in Perfetto / chrome://tracing).
- :mod:`profiler` — guarded on-demand ``jax.profiler`` captures (one at
  a time, duration-bounded) behind ``POST /debug/profile``.

Gated by the ``observability`` config block (:class:`ObservabilityConfig`
in ``inference/v2/config_v2.py``): on by default with bounded ring sizes.
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      get_registry, histogram_delta, quantiles_from_counts)
from .tracing import RequestTracer, get_tracer
from .profiler import ProfilerBusy, ProfilerCapture, profile_dir
from .instruments import ServingInstruments

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "histogram_delta", "quantiles_from_counts",
    "RequestTracer", "get_tracer",
    "ProfilerBusy", "ProfilerCapture", "profile_dir",
    "ServingInstruments",
]
