"""Unified serving/training observability.

Three pillars, all host-side and allocation-light (nothing here ever
touches the device — timestamps are ``time.monotonic()`` around already
existing host boundaries, honoring the async-dispatch design):

- :mod:`metrics` — a process-wide registry of counters, gauges, and
  log-bucketed histograms (fixed-size numpy bucket arrays; p50/p90/p99
  derivable at read time). Rendered as Prometheus text by the serving
  daemon's ``GET /metrics`` and bridgeable into the ``monitor/`` fan-out
  (one ``(name, value, step)`` event schema shared with training).
- :mod:`tracing` — per-request span timelines (submit → queue → admit →
  prefill chunks → fused K-waves → journal → finish) in a bounded ring,
  exportable per-uid as JSON and in bulk as Chrome ``trace_event`` JSON
  (loadable in Perfetto / chrome://tracing).
- :mod:`profiler` — guarded on-demand ``jax.profiler`` captures (one at
  a time, duration-bounded) behind ``POST /debug/profile``.
- :mod:`xla` — compile observability: per-compile-key compile/retrace/hit
  telemetry (:class:`CompileWatch` wrapping every jit entry point),
  cost-analysis FLOPs feeding the ``ds_train_mfu`` /
  ``ds_serving_wave_mfu`` gauges, and device-memory gauges.
- :mod:`goodput` — a wall-clock ledger attributing every training second
  to {useful step, compile, host-sync stall, checkpoint save/load,
  anomaly rollback, restart}, exported as
  ``ds_goodput_seconds_total{category=...}``.

Serving is gated by the ``observability`` config block
(:class:`ObservabilityConfig` in ``inference/v2/config_v2.py``); training
by :class:`TrainObservabilityConfig` (``config/feature_configs.py``).
Training runs have no HTTP server — they export through
``MetricsRegistry.write_textfile`` (atomic Prometheus textfile consumed
by ``ds_top --file``) and the ``monitor.write_registry`` bridge.
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      get_registry, histogram_delta, quantiles_from_counts)
from .tracing import RequestTracer, get_tracer
from .profiler import ProfilerBusy, ProfilerCapture, profile_dir
from .instruments import ServingInstruments
from .xla import (CompileWatch, TrainInstruments, WatchedJit,
                  cost_analysis_flops, install_backend_compile_listener,
                  refresh_memory_gauges)
from .goodput import CATEGORIES as GOODPUT_CATEGORIES, GoodputLedger

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "histogram_delta", "quantiles_from_counts",
    "RequestTracer", "get_tracer",
    "ProfilerBusy", "ProfilerCapture", "profile_dir",
    "ServingInstruments",
    "CompileWatch", "TrainInstruments", "WatchedJit", "cost_analysis_flops",
    "install_backend_compile_listener", "refresh_memory_gauges",
    "GOODPUT_CATEGORIES", "GoodputLedger",
]
