"""Compile & runtime observability for the XLA layer.

Three pieces, all host-side and off the per-step critical path:

- :class:`CompileWatch` + :class:`WatchedJit`: transparent wrappers around
  jitted callables that classify every dispatch as compile / retrace /
  cache hit **per compile key** and record the wall time of compiling
  calls into labeled histograms (``ds_compile_seconds{key=...}``). The
  detection mechanism is ``fn._cache_size()`` growth across a call — one
  cheap C call per dispatch; when the attribute is missing (plain
  function wrappers, e.g. the grad-comm step builder) the first call
  counts as the compile and later calls as hits.
- FLOPs accounting: a compiling call captures ``ShapeDtypeStruct`` specs
  of its arguments so :meth:`WatchedJit.program_flops` can later run
  ``lower().cost_analysis()`` — HLO-level cost analysis on the lowered
  (NOT compiled) module, ~10ms once per program, done lazily at publish
  time, never on the step path. :class:`TrainInstruments` turns (dispatches × program FLOPs)
  over a wall interval into the ``ds_train_mfu`` gauge; serving uses the
  same ``program_flops`` for ``ds_serving_wave_mfu``.
- Device-memory gauges (:func:`refresh_memory_gauges`) from
  ``device.memory_stats()`` — live bytes, peak watermark, allocator
  limit. CPU backends return no stats; the gauges simply stay absent.

``install_backend_compile_listener`` additionally taps jax's monitoring
event ``/jax/core/compile/backend_compile_duration`` into an unlabeled
histogram — it catches XLA compiles that bypass the wrapped entry points
(model init, eager ops, persistent-cache misses during deserialization).
"""

import threading
import time
from typing import Any, Optional, Tuple

from .metrics import Histogram, MetricsRegistry, get_registry

# compile times span ~ms (tiny CPU programs) to ~1h (giant TPU programs)
_COMPILE_HIST = dict(lo=1e-3, hi=1e4, buckets_per_decade=5)
# step times: µs-scale fused CPU steps to minutes-long K-step waves
_STEP_HIST = dict(lo=1e-6, hi=1e3, buckets_per_decade=10)

_FALLBACK_PEAK_FLOPS = 197e12  # accelerator ABC default (v5e-class)


def cost_analysis_flops(stage) -> float:
    """FLOPs from ``cost_analysis()`` of a ``jax.stages.Lowered`` OR
    ``Compiled``, normalizing the list-of-dicts vs dict return across jax
    versions; 0.0 when the backend doesn't report a cost model."""
    try:
        cost = stage.cost_analysis()
    except Exception:
        return 0.0
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    try:
        return float(cost.get("flops", 0.0) or 0.0)
    except Exception:
        return 0.0


def _arg_specs(args, kwargs) -> Tuple[tuple, dict]:
    """Shape/dtype skeleton of a call's arguments: arrays become
    ``ShapeDtypeStruct`` (shape metadata survives donation; no buffers are
    retained), statics pass through untouched — good enough to re-``lower``
    the same program for cost analysis."""
    import jax

    def spec(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x

    return (jax.tree_util.tree_map(spec, args),
            jax.tree_util.tree_map(spec, kwargs))


class WatchedJit:
    """Transparent wrapper around one jitted program. Forwards everything
    (``lower``, ``clear_cache``, ...) so callers — including the flops
    profiler's ``hasattr(fn, "lower")`` probe — can't tell the difference;
    adds per-dispatch compile/hit classification and lazy FLOPs."""

    def __init__(self, fn, key: str, watch: "CompileWatch"):
        self._fn = fn
        self.key = key
        self._watch = watch
        self._calls = 0
        self.dispatches = 0       # read by TrainInstruments.publish()
        self._flops: Optional[float] = None
        self._flops_spec = None

    def _cache_entries(self) -> Optional[int]:
        try:
            return int(self._fn._cache_size())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        before = self._cache_entries()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        after = self._cache_entries()
        if after is None:
            # no jit cache introspection: first call is the compile
            compiled, retrace = self._calls == 0, False
        else:
            compiled = after > (before or 0)
            retrace = compiled and bool(before)
        self._calls += 1
        self.dispatches += 1
        if compiled:
            # wall of a compiling call ≈ trace + compile: execution is
            # dispatched async, so the device work barely contributes
            dt = time.perf_counter() - t0
            self._watch.on_compile(self.key, dt, retrace)
            if self._flops_spec is None:
                try:
                    self._flops_spec = _arg_specs(args, kwargs)
                except Exception:
                    pass
                # real programs (compile cost ≫ lowering cost): resolve the
                # cost analysis NOW, inside the compile event — deferring it
                # would bill the first steady-state publish() a
                # whole-program lowering. Tiny programs (unit tests) stay
                # lazy: their lowering is milliseconds wherever it lands,
                # and doing it eagerly taxes every engine construction.
                if dt > 0.5:
                    self.program_flops()
        else:
            self._watch.on_hit(self.key)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def program_flops(self) -> float:
        """Cost-analysis FLOPs of one dispatch of this program. Lazy and
        cached: the first call re-lowers from the captured arg specs and
        runs HLO-level cost analysis on the LOWERED module (~10ms) — it
        deliberately never calls ``.compile()``, which would pay a full
        fresh XLA compile (the AOT path shares no executable cache with
        dispatch). Never invoked on the step path."""
        if self._flops is not None:
            return self._flops
        if self._flops_spec is None:
            return 0.0
        a, k = self._flops_spec
        try:
            self._flops = cost_analysis_flops(self._fn.lower(*a, **k))
        except Exception:
            self._flops = 0.0
        return self._flops


class CompileWatch:
    """Per-compile-key compile telemetry sink. Lazily creates one labeled
    series per key:

    - ``ds_compile_seconds{key=...}``: wall seconds of compiling calls
    - ``ds_compiles_total{key=...}``: compile events (first + retraces)
    - ``ds_recompiles_total{key=...}``: retraces only (cache already warm
      — the "why is my steady state recompiling" counter)
    - ``ds_compile_cache_hits_total{key=...}``: dispatches served from the
      jit cache

    ``on_compile_seconds`` (optional) feeds measured compile wall into the
    goodput ledger's pending-compile pool."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 on_compile_seconds=None):
        self.registry = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._per_key = {}
        self._on_compile_seconds = on_compile_seconds

    def _handles(self, key: str):
        h = self._per_key.get(key)
        if h is None:
            with self._lock:
                h = self._per_key.get(key)
                if h is None:
                    lab = {"key": key}
                    reg = self.registry
                    h = (reg.histogram(
                            "ds_compile_seconds",
                            "Wall seconds of jit trace+compile per compile "
                            "key (first call and retraces)",
                            labels=lab, **_COMPILE_HIST),
                         reg.counter(
                            "ds_compiles_total",
                            "Compile events per compile key", labels=lab),
                         reg.counter(
                            "ds_recompiles_total",
                            "Retraces per compile key (compile with a warm "
                            "cache — steady state should hold at 0)",
                            labels=lab),
                         reg.counter(
                            "ds_compile_cache_hits_total",
                            "Dispatches served from the jit cache per "
                            "compile key", labels=lab))
                    self._per_key[key] = h
        return h

    def wrap(self, fn, key: str) -> Optional[WatchedJit]:
        if fn is None:
            return None
        if isinstance(fn, WatchedJit):
            return fn
        return WatchedJit(fn, key, self)

    def on_compile(self, key: str, seconds: float, retrace: bool) -> None:
        hist, compiles, recompiles, _ = self._handles(key)
        hist.record(seconds)
        compiles.inc()
        if retrace:
            recompiles.inc()
        cb = self._on_compile_seconds
        if cb is not None:
            cb(seconds)

    def on_hit(self, key: str) -> None:
        self._handles(key)[3].inc()

    def counts(self, key: str) -> dict:
        """Introspection helper for tests/consoles."""
        hist, compiles, recompiles, hits = self._handles(key)
        return {"compiles": compiles.value, "recompiles": recompiles.value,
                "hits": hits.value, "compile_seconds": hist.sum}


def refresh_memory_gauges(registry: Optional[MetricsRegistry] = None) -> dict:
    """Device-memory gauges from the first local device's allocator stats
    (live bytes, peak watermark, capacity). Backends without memory stats
    (CPU) produce no gauges — returns whatever was set."""
    reg = registry if registry is not None else get_registry()
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        stats = {}
    out = {}
    for src, name, help_ in (
            ("bytes_in_use", "ds_device_bytes_in_use",
             "Live device (HBM) bytes in use"),
            ("peak_bytes_in_use", "ds_device_peak_bytes_in_use",
             "Peak device bytes watermark since process start"),
            ("bytes_limit", "ds_device_bytes_limit",
             "Device memory capacity visible to the allocator")):
        if src in stats:
            v = float(stats[src])
            reg.gauge(name, help_).set(v)
            out[name] = v
    return out


_BACKEND_LISTENER_INSTALLED = False


def install_backend_compile_listener(
        registry: Optional[MetricsRegistry] = None) -> bool:
    """Tap jax's ``/jax/core/compile/backend_compile_duration`` monitoring
    event into ``ds_xla_backend_compile_seconds`` — XLA compile wall as the
    runtime itself measures it, including compiles outside any watched
    entry point. Idempotent per process (jax.monitoring offers no listener
    removal); returns False when the hook isn't available."""
    global _BACKEND_LISTENER_INSTALLED
    if _BACKEND_LISTENER_INSTALLED:
        return True
    reg = registry if registry is not None else get_registry()
    hist = reg.histogram(
        "ds_xla_backend_compile_seconds",
        "XLA backend_compile wall seconds (jax.monitoring event, all "
        "compiles process-wide)", **_COMPILE_HIST)
    try:
        import jax.monitoring as _monitoring

        def _on_event(name, secs, **kw):
            if name.endswith("backend_compile_duration"):
                hist.record(float(secs))

        _monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:
        return False
    _BACKEND_LISTENER_INSTALLED = True
    return True


def peak_device_flops() -> float:
    """Per-device peak bf16 FLOP/s from the accelerator abstraction (the
    MFU denominator); falls back to the v5e-class default."""
    try:
        from ..accelerator import get_accelerator
        return max(1.0, float(get_accelerator().peak_bf16_flops()))
    except Exception:
        return _FALLBACK_PEAK_FLOPS


class TrainInstruments:
    """Pre-resolved training-side metric handles (the engine's sibling of
    ``ServingInstruments``): per-step wall histogram, MFU gauge, the
    compile watch, and the goodput ledger — one object the engine threads
    through its step boundaries and window drains.

    Per-step cost (``step_mark``): one ``perf_counter``, a histogram bump
    per optimizer step, one ledger mark. Everything derived — FLOPs cost
    analysis, memory stats, MFU, goodput fraction — happens in
    ``publish()`` at the drain/monitor cadence."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 ledger=None, compile_watch: Optional[CompileWatch] = None,
                 peak_flops: Optional[float] = None):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self.ledger = ledger
        self.step_seconds = reg.histogram(
            "ds_train_step_seconds",
            "Wall seconds per optimizer step at the host dispatch boundary "
            "(a K-step fused dispatch records K samples of wall/K)",
            **_STEP_HIST)
        self.mfu = reg.gauge(
            "ds_train_mfu",
            "Model FLOPs utilization over the last publish interval: "
            "dispatched program FLOPs (XLA cost analysis) / wall / "
            "peak_bf16_flops")
        self.compile_watch = compile_watch or CompileWatch(
            registry=reg,
            on_compile_seconds=(ledger.note_compile
                                if ledger is not None else None))
        self.peak_flops = (peak_device_flops() if peak_flops is None
                           else max(1.0, float(peak_flops)))
        self._programs = []     # [WatchedJit, dispatches_already_published]
        self._t_last = None     # step-boundary clock (set by start_clock)
        self._mfu_t0 = None

    # -- program registry --------------------------------------------------

    def watch_program(self, fn, key: str):
        """Wrap a jitted program for compile telemetry AND register it for
        FLOPs/MFU accounting. Idempotent on already-wrapped programs."""
        if fn is None:
            return None
        if isinstance(fn, WatchedJit):
            return fn
        w = self.compile_watch.wrap(fn, key)
        self._programs.append([w, 0])
        return w

    # -- step boundary (hot path) -----------------------------------------

    def start_clock(self, now: Optional[float] = None) -> None:
        """Anchor the step clock — call once when the engine is ready to
        train, so the first step's sample excludes construction time."""
        now = time.perf_counter() if now is None else now
        self._t_last = now
        self._mfu_t0 = now

    def step_mark(self, steps: int = 1) -> None:
        """Record the wall since the previous boundary as ``steps``
        optimizer steps (K samples of wall/K for a fused K-step dispatch)
        and attribute the interval to goodput "useful_step"."""
        now = time.perf_counter()
        if self._t_last is None:
            self.start_clock(now)
            if self.ledger is not None:
                self.ledger.mark("useful_step")
            return
        dt = max(0.0, now - self._t_last)
        self._t_last = now
        n = max(1, int(steps))
        per = dt / n
        for _ in range(n):
            self.step_seconds.record(per)
        if self.ledger is not None:
            self.ledger.mark("useful_step")

    # -- publish cadence ---------------------------------------------------

    def publish(self) -> None:
        """Refresh every derived view: device-memory gauges, the goodput
        fraction, and MFU over the interval since the last publish. Runs
        at the async-window drain (or per step in sync mode) — the lazy
        ``program_flops`` cost analyses land here, not on the step path."""
        refresh_memory_gauges(self.registry)
        if self.ledger is not None:
            self.ledger.publish()
        now = time.perf_counter()
        if self._mfu_t0 is None:
            self._mfu_t0 = now
            return
        wall = now - self._mfu_t0
        flops = 0.0
        any_dispatch = False
        for ent in self._programs:
            prog, seen = ent
            d = prog.dispatches - seen
            if d > 0:
                any_dispatch = True
                f = prog.program_flops()
                if f > 0:
                    flops += f * d
                ent[1] = prog.dispatches
        if any_dispatch and wall > 0 and flops > 0:
            self.mfu.set(min(1.0, flops / (wall * self.peak_flops)))
        if any_dispatch:
            self._mfu_t0 = now
