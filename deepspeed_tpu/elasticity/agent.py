"""DSElasticAgent analog: failure detection + elastic restart orchestration.

Reference: ``deepspeed/elasticity/elastic_agent.py:21 DSElasticAgent``
(a ``torch.distributed.elastic`` LocalElasticAgent subclass): monitor the
training workers, and on worker failure or a membership (scale) event,
restart the job at the new world size — convergence-safe because the
elastic config keeps the GLOBAL batch invariant across compatible worlds.

TPU-native shape: under single-controller SPMD there is one training
PROCESS per host, not one per device, so the agent is a host-side
supervisor around that process:

* **failure detection** — the child exiting nonzero (XLA abort, OOM,
  preemption signal) is the failure signal; no rendezvous layer needed.
* **scale events** — ``world_fn()`` reports the currently-available device
  count (default: probe env ``DS_ELASTIC_WORLD_SIZE`` so tests/schedulers
  can shrink the slice); when it changes mid-run the agent SIGTERMs the
  child and relaunches at the new world.
* **elastic relaunch** — each (re)launch recomputes
  ``compute_elastic_config`` for the current world and exports the result
  (``DS_ELASTIC_WORLD_SIZE`` / ``DS_ELASTIC_MICRO_BATCH`` /
  ``DS_ELASTIC_GLOBAL_BATCH``) to the child, which resumes from its latest
  checkpoint (universal any→any resume; the global batch is invariant by
  construction — ``TestElasticResumeInvariant`` pins the math end-to-end).
* **restart budget** — ``max_restarts`` failures (reference agent's
  ``@record``-wrapped run loop raises after the budget).
"""

import os
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

from ..utils.logging import logger
from .config import ElasticityIncompatibleWorldSize
from .elasticity import compute_elastic_config


_probed_world: Optional[int] = None


def _probe_world() -> int:
    """One device-count probe in a subprocess (importing jax here would
    initialize the TPU backend inside the supervisor and lock it away from
    the very child it launches)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.device_count())"],
            capture_output=True, text=True, timeout=120)
        return int(out.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001 — no backend reachable
        logger.warning(
            "DSElasticAgent: could not probe device count (set "
            "DS_ELASTIC_WORLD_SIZE or pass world_fn); assuming 1")
        return 1


def _default_world_fn(refresh: bool = False) -> int:
    """Available world: ``DS_ELASTIC_WORLD_SIZE`` if set, else a cached
    subprocess device probe. The cache keeps the steady-state monitor poll
    cheap, but it is NOT authoritative across a relaunch: the agent passes
    ``refresh=True`` on its restart paths so a membership change that
    crashed the child is observed instead of shadowed by the stale cached
    value (which previously won for the whole process lifetime)."""
    w = os.environ.get("DS_ELASTIC_WORLD_SIZE")
    if w:
        return int(w)
    global _probed_world
    if _probed_world is None or refresh:
        _probed_world = _probe_world()
    return _probed_world


def probe_available_world(refresh: bool = False) -> int:
    """Public face of the cached world probe for non-training supervisors
    (the serving fleet router sizes its replica pool ceiling from this):
    ``DS_ELASTIC_WORLD_SIZE`` if set, else one subprocess device-count
    probe — never a jax import in the calling process."""
    return _default_world_fn(refresh=refresh)


class DSElasticAgent:
    """Supervise one SPMD training process with elastic restarts."""

    def __init__(self, cmd: Sequence[str], ds_config: dict,
                 max_restarts: int = 3,
                 monitor_interval: float = 1.0,
                 world_fn: Optional[Callable[[], int]] = None,
                 env: Optional[dict] = None,
                 restart_backoff: float = 0.0):
        self.cmd = list(cmd)
        self.ds_config = ds_config
        self.max_restarts = int(max_restarts)
        self.monitor_interval = float(monitor_interval)
        self.world_fn = world_fn or _default_world_fn
        self.base_env = dict(env if env is not None else os.environ)
        self.restart_backoff = float(restart_backoff)
        self.restarts = 0          # failures consumed against the budget
        self.scale_events = 0      # membership changes (don't count as failures)
        self.history: List[dict] = []

    # ------------------------------------------------------------------

    def _world(self, refresh: bool = False) -> int:
        """Currently-available world. A caller-supplied ``world_fn`` is
        always authoritative — it is invoked directly and its result is
        never shadowed by the module's cached device probe. Only the
        default probe honors ``refresh`` (relaunch paths force it so a
        membership change across a crash is actually observed)."""
        if self.world_fn is not _default_world_fn:
            return self.world_fn()
        return _default_world_fn(refresh=refresh)

    def _resolve_world(self, want: int) -> int:
        """Largest world ≤ want that the elastic config accepts (a shrunk
        slice may not be in the compatible set — step down to one that is,
        reference _get_compatible_gpus semantics)."""
        for w in range(want, 0, -1):
            try:
                compute_elastic_config(self.ds_config, world_size=w)
                return w
            except ElasticityIncompatibleWorldSize:
                continue
        raise ElasticityIncompatibleWorldSize(
            f"no world size in [1, {want}] satisfies the elastic config")

    def _launch(self, world: int) -> subprocess.Popen:
        batch, _, micro = compute_elastic_config(
            self.ds_config, world_size=world, return_microbatch=True)
        env = dict(self.base_env)
        env["DS_ELASTIC_WORLD_SIZE"] = str(world)
        env["DS_ELASTIC_MICRO_BATCH"] = str(micro)
        env["DS_ELASTIC_GLOBAL_BATCH"] = str(batch)
        env["DS_ELASTIC_RESTART_COUNT"] = str(self.restarts + self.scale_events)
        self.history.append({"world": world, "micro": micro, "batch": batch,
                             "t": time.time()})
        logger.info(f"DSElasticAgent: launching world={world} micro={micro} "
                    f"global_batch={batch} "
                    f"(restart {self.restarts}/{self.max_restarts})")
        return subprocess.Popen(self.cmd, env=env)

    def run(self) -> int:
        """Supervise until clean exit, budget exhaustion, or an
        unsatisfiable world. Returns the final child returncode."""
        world = self._resolve_world(self._world())
        proc = self._launch(world)
        try:
            while True:
                rc = proc.poll()
                if rc is not None:
                    if rc == 0:
                        logger.info("DSElasticAgent: clean exit")
                        return 0
                    self.restarts += 1
                    if self.restarts > self.max_restarts:
                        logger.error(
                            f"DSElasticAgent: restart budget exhausted "
                            f"({self.max_restarts}); last rc={rc}")
                        return rc
                    logger.warning(
                        f"DSElasticAgent: worker failed rc={rc} — elastic "
                        f"restart {self.restarts}/{self.max_restarts}")
                    if self.restart_backoff:
                        time.sleep(self.restart_backoff)
                    # the crash may itself be the membership change (device
                    # loss) — re-probe instead of trusting the launch-time
                    # cached world
                    world = self._resolve_world(self._world(refresh=True))
                    proc = self._launch(world)
                    continue
                avail = self._resolve_world(self._world())
                if avail != world:
                    # membership change: drain the child and relaunch at the
                    # new world (reference agent's rendezvous-version bump)
                    self.scale_events += 1
                    logger.warning(
                        f"DSElasticAgent: scale event {world} -> {avail}; "
                        f"restarting workers")
                    proc.send_signal(signal.SIGTERM)
                    try:
                        proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    world = avail
                    proc = self._launch(world)
                time.sleep(self.monitor_interval)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def main(argv=None):
    import argparse
    import json
    ap = argparse.ArgumentParser(
        description="Elastic training supervisor (DSElasticAgent analog)")
    ap.add_argument("-c", "--config", required=True, help="DS config json")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--monitor-interval", type=float, default=1.0)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="training command (after --)")
    args = ap.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":  # only the LEADING separator; the child may
        cmd = cmd[1:]           # legitimately use "--" in its own argv
    if not cmd:
        ap.error("no training command given")
    with open(args.config) as f:
        ds_config = json.load(f)
    agent = DSElasticAgent(cmd, ds_config, max_restarts=args.max_restarts,
                           monitor_interval=args.monitor_interval)
    sys.exit(agent.run())


if __name__ == "__main__":
    main()
