"""Elastic batch-size / chip-count math.

Reference: ``deepspeed/elasticity/elasticity.py:233 compute_elastic_config``
(+ ``_get_compatible_gpus_v01 :83`` / ``_get_compatible_gpus_v02 :126``).
Pure arithmetic — same algorithm, reimplemented:

Pick a global batch size B ≤ max_acceptable that maximizes the number of
chip counts w for which B = micro_batch × grad_accum × w has an integer
solution with some allowed micro-batch. Scaling the job up/down across any
w in the valid set then never changes the *global* batch (convergence-safe
elastic training). Candidates are built by scaling each micro-batch (and
their LCM) by highly composite numbers — maximally divisor-rich, hence
maximally elastic.

v0.2 operates at node granularity (whole TPU hosts) with model-parallel
awareness: valid world sizes are multiples of chips-per-node, and MP shrinks
the effective data-parallel width per node.

The reference's ``DSElasticAgent`` (torch-elastic subclass managing worker
restarts) has a host-level analog in :mod:`.agent` — a supervisor around
the single SPMD training process that detects failures, watches for scale
events, recomputes this module's elastic config for the new world and
relaunches with resume (``bin/ds_elastic run``). Cluster schedulers
(GKE/xmanager) can instead call ``compute_elastic_config`` directly; resume
correctness comes from the universal checkpoint (any→any) path either way.
"""

import math
from functools import reduce
from typing import List, Optional, Tuple

from ..utils.logging import logger
from .config import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                     ElasticityIncompatibleWorldSize, LATEST_ELASTICITY_VERSION)

# Smallest highly composite numbers — divisor-count record holders. Enough to
# cover global batches into the ~700k range (reference elasticity.py:21).
_HCN = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280, 720720
]


def _lcm(nums: List[int]) -> int:
    return reduce(math.lcm, nums)


def _largest_hcn_multiple(base: int, limit: int) -> int:
    """base × (largest HCN keeping the product ≤ limit)."""
    if base >= limit:
        return base
    q = limit // base
    best = 1
    for h in _HCN:
        if h > q:
            break
        best = h
    return base * best


def _candidate_batch_sizes(micro_batches: List[int], max_batch: int) -> List[int]:
    bases = list(micro_batches) + [_lcm(micro_batches)]
    return sorted({_largest_hcn_multiple(b, max_batch) for b in bases})


def _valid_chip_counts(batch_size: int, micro_batches: List[int], lo: int, hi: int) -> List[int]:
    """All chip counts w in [lo, hi] such that some micro-batch divides
    batch_size/w evenly (i.e. gas = batch/(mb·w) is a positive integer)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        per_mb_chips = batch_size // mb
        # every divisor of per_mb_chips is a workable world size
        for d in range(1, int(math.isqrt(per_mb_chips)) + 1):
            if per_mb_chips % d == 0:
                for w in (d, per_mb_chips // d):
                    if lo <= w <= hi:
                        valid.add(w)
    return sorted(valid)


def get_compatible_chip_counts(micro_batches: List[int],
                               max_batch: int,
                               min_chips: int = 1,
                               max_chips: Optional[int] = None,
                               prefer_larger: bool = True) -> Tuple[int, List[int]]:
    """v0.1 core (reference _get_compatible_gpus_v01): choose the candidate
    batch with the most valid chip counts; ties break toward the larger
    (or smaller) batch per prefer_larger."""
    if max_chips is None:
        max_chips = max_batch // min(micro_batches)
    bad = [m for m in micro_batches if m > max_batch]
    if bad:
        raise ElasticityError(f"micro batches {bad} exceed max batch size {max_batch}")

    best_batch, best_valid = min(micro_batches), []
    for cand in _candidate_batch_sizes(micro_batches, max_batch):
        valid = _valid_chip_counts(cand, micro_batches, min_chips, max_chips)
        better = len(valid) > len(best_valid) or (
            len(valid) == len(best_valid) and
            (cand > best_batch if prefer_larger else cand < best_batch))
        if better:
            best_batch, best_valid = cand, valid
    return best_batch, best_valid


def _node_level_config(cfg: ElasticityConfig, current_chips: int):
    """v0.2 (reference _get_compatible_gpus_v02): node-granular scaling with
    model parallelism folded out of the dp width."""
    cpn = cfg.num_gpus_per_node
    if cpn % cfg.model_parallel_size != 0:
        raise ElasticityError(f"chips per node {cpn} must be divisible by "
                              f"model_parallel_size {cfg.model_parallel_size}")
    dp_per_node = cpn // cfg.model_parallel_size

    batch, node_counts = get_compatible_chip_counts(
        cfg.micro_batches, cfg.max_acceptable_batch_size // dp_per_node,
        max(1, cfg.min_gpus // cpn), max(1, cfg.max_gpus // cpn),
        prefer_larger=cfg.prefer_larger_batch_size)
    batch *= dp_per_node
    valid_dp = [n * dp_per_node for n in node_counts]

    if current_chips and current_chips // cfg.model_parallel_size not in valid_dp:
        # fall back: keep the current topology, take the biggest batch it fits
        cur_dp = (current_chips // cpn) * dp_per_node
        cands = [mb * cur_dp * (cfg.max_acceptable_batch_size // (mb * cur_dp))
                 for mb in cfg.micro_batches if mb * cur_dp <= cfg.max_acceptable_batch_size]
        if not cands:
            raise ElasticityIncompatibleWorldSize(
                f"no batch fits world size {current_chips} under "
                f"{cfg.max_acceptable_batch_size}")
        batch = max(cands) if cfg.prefer_larger_batch_size else min(cands)
        valid_dp = [cur_dp]
    return batch, valid_dp


def _pick_micro_batch(cfg: ElasticityConfig, batch: int, dp_world: int) -> Optional[int]:
    """Largest (or smallest) allowed micro-batch dividing the per-chip batch
    (reference get_microbatch, elasticity.py:146)."""
    fitting = [mb for mb in cfg.micro_batches if (batch // dp_world) % mb == 0]
    if not fitting:
        return None
    return max(fitting) if cfg.prefer_larger_batch_size else min(fitting)


def elasticity_enabled(ds_config: dict) -> bool:
    """Reference elasticity.py:202."""
    return ds_config.get("elasticity", {}).get("enabled", False)


def compute_elastic_config(ds_config: dict,
                           target_deepspeed_version: str = None,
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """Reference elasticity.py:233 — deterministic (batch, valid chip counts
    [, micro_batch]) from the elasticity config block. Called by both the
    cluster scheduler (to pick slice sizes) and the runtime (to derive gas)."""
    if not isinstance(ds_config, dict):
        raise ValueError(f"Expected ds_config dict, got {type(ds_config)}")
    if "elasticity" not in ds_config:
        raise ElasticityConfigError("'elasticity' is missing from config json")
    cfg = ElasticityConfig(ds_config["elasticity"])
    if not cfg.enabled:
        raise ElasticityConfigError("Elasticity is disabled ('enabled': false)")
    if cfg.version > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"elasticity version {cfg.version} > supported {LATEST_ELASTICITY_VERSION}")
    if cfg.model_parallel_size > 1 and cfg.version < 0.2:
        raise ElasticityConfigError(
            f"elasticity v{cfg.version} does not support model parallelism")

    if cfg.version >= 0.2:
        batch, valid = _node_level_config(cfg, world_size)
    else:
        batch, valid = get_compatible_chip_counts(
            cfg.micro_batches, cfg.max_acceptable_batch_size, cfg.min_gpus, cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch_size)

    if world_size > 0:
        dp = world_size // cfg.model_parallel_size
        if dp not in valid:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} (dp={dp}) not in valid set {valid}")
    logger.info(f"elastic config: batch={batch}, valid chip counts={valid}")

    if return_microbatch:
        dp = (world_size or valid[-1]) // cfg.model_parallel_size
        return batch, valid, _pick_micro_batch(cfg, batch, dp)
    return batch, valid
