"""Elasticity config (reference ``deepspeed/elasticity/config.py``)."""


class ElasticityError(Exception):
    """Base elasticity error (reference config.py:10)."""


class ElasticityConfigError(ElasticityError):
    """Config error (reference config.py:16)."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """Current world size not in the valid set (reference config.py:22)."""


LATEST_ELASTICITY_VERSION = 0.2


class ElasticityConfig:
    """Validated view of the ``elasticity`` config block (config.py:28).

    {"enabled": true, "max_train_batch_size": 2000,
     "micro_batch_sizes": [2,4,6], "min_gpus": 1, "max_gpus": 10000,
     "min_time": 20, "version": 0.2, "ignore_non_elastic_batch_info": false,
     "num_gpus_per_node": 1, "model_parallel_size": 1}

    Chip-count knobs keep the reference's "gpus" key names for config-file
    compatibility; they mean TPU chips here.
    """

    def __init__(self, param_dict: dict):
        self.enabled = param_dict.get("enabled", False)
        if self.enabled:
            if "max_train_batch_size" not in param_dict:
                raise ElasticityConfigError("max_train_batch_size is required when "
                                            "elasticity is enabled")
            if "micro_batch_sizes" not in param_dict:
                raise ElasticityConfigError("micro_batch_sizes is required when "
                                            "elasticity is enabled")
        self.max_acceptable_batch_size = param_dict.get("max_train_batch_size", 2000)
        self.micro_batches = param_dict.get("micro_batch_sizes", [2, 4, 6])
        if not isinstance(self.micro_batches, list) or not self.micro_batches:
            raise ElasticityConfigError(
                f"micro_batch_sizes must be a non-empty list, got {self.micro_batches}")
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive ints, got {self.micro_batches}")
        self.min_gpus = param_dict.get("min_gpus", 1)
        self.max_gpus = param_dict.get("max_gpus", 10000)
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"invalid chip range [{self.min_gpus}, {self.max_gpus}]")
        self.min_time = param_dict.get("min_time", 0)
        self.version = float(param_dict.get("version", 0.2))
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch_size", True)
        self.ignore_non_elastic_batch_info = param_dict.get("ignore_non_elastic_batch_info", False)
        self.num_gpus_per_node = param_dict.get("num_gpus_per_node", 1)
        self.model_parallel_size = param_dict.get("model_parallel_size", 1)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return str(self.__dict__)
