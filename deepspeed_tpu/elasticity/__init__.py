from .config import (ElasticityConfig, ElasticityError, ElasticityConfigError,
                     ElasticityIncompatibleWorldSize)
from .elasticity import (compute_elastic_config, elasticity_enabled,
                         get_compatible_chip_counts)
from .agent import DSElasticAgent, probe_available_world
