"""Config-model base utilities.

TPU-native analog of the reference's ``deepspeed/runtime/config_utils.py``
(``DeepSpeedConfigModel``): pydantic v2 models with support for the literal
string ``"auto"`` on selected fields, deprecated-field plumbing, and
dict-style dumps of only user-set fields.
"""

from functools import reduce
from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, field_validator  # noqa: F401


class ConfigModel(BaseModel):
    """Base for all config models.

    Fields annotated with a union including ``Literal["auto"]`` (or typed
    ``Any``) may be set to the string "auto"; resolution happens in the engine.
    """

    model_config = ConfigDict(
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="ignore",
        protected_namespaces=(),
        arbitrary_types_allowed=True,
    )

    def __init__(self, strict=False, **data):
        if not strict:  # This is temporary until we refactor all DS configs
            data = {k: v for k, v in data.items() if (v != "auto" or k == "replace_method")}
        super().__init__(**data)

    def get(self, key, default=None):
        return getattr(self, key, default)

    def __getitem__(self, key):
        return getattr(self, key)

    def dump(self) -> Dict[str, Any]:
        return self.model_dump()


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys when parsing JSON."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, v in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d
