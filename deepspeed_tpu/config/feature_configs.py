"""Per-feature config models.

Mirrors the reference JSON surface: ``runtime/zero/config.py:83``
(DeepSpeedZeroConfig), ``runtime/fp16`` keys, ``runtime/activation_checkpointing/config.py``,
``utils/comms_logging`` keys, ``profiling/config.py``, ``monitor/config.py``,
``runtime/swap_tensor/aio_config.py`` — with identical key names so reference
JSON configs parse unchanged. TPU-only extensions are marked.
"""

from enum import Enum
from typing import Any, Dict, List, Optional
from pathlib import Path

from pydantic import Field, model_validator

from .config_utils import ConfigModel

# -------------------- ZeRO --------------------


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(ConfigModel):
    """Param offload (reference ``runtime/zero/offload_config.py``)."""
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[Path] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(int(1e8), ge=0)
    max_in_cpu: int = Field(int(1e9), ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(ConfigModel):
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[Path] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = Field(1.0, ge=0.0, le=1.0)

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write


class ZeroConfig(ConfigModel):
    """ZeRO sharding config (reference ``runtime/zero/config.py:83``).

    On TPU the stages map to sharding rules over the ``fsdp``/``data`` mesh
    axes rather than hook-driven partitioning:
      stage 0 = pure DP; stage 1 = optimizer-state sharding;
      stage 2 = + gradient (accumulation buffer) sharding;
      stage 3 = + parameter sharding (XLA inserts gather/scatter).

    Knob disposition (the audit of every accepted key):
    - WIRED: stage, offload_param/offload_optimizer (device/ratio),
      max_live_parameters (scan-chunk governor), param_persistence_threshold,
      zero_hpz_partition_size, zero_quantized_weights/gradients (qwZ/qgZ),
      mics_shard_size, gather_16bit_weights_on_model_save (consolidated
      16-bit export with every checkpoint).
    - MOOT by construction (accepted for config-file compatibility, the
      guarantee they buy is unconditional here): elastic_checkpoint (orbax
      restores across any topology), load_from_fp32_weights (master weights
      are always fp32), ignore_unused_parameters (no hook machinery to
      trip), contiguous_gradients (XLA owns layout).
    - TORCH-MECHANISM knobs with no XLA seam (accepted, inert, the
      scheduler/compiler owns the behavior they tuned): bucket sizes,
      overlap_comm, round_robin_gradients, sub_group_size, prefetch/
      reuse-distance/module-granularity thresholds, legacy_stage1,
      use_all_reduce_for_fetch_params, use_multi_rank_bucket_allreduce,
      memory_efficient_linear, pipeline_loading_checkpoint,
      override_module_apply, cpu_offload* legacy spellings (the offload_*
      sub-configs are the wired path).
    """
    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(int(5e8), ge=0)
    use_multi_rank_bucket_allreduce: bool = True
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(int(5e8), ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = Field(int(1e9), ge=0)
    cpu_offload_param: Optional[bool] = None
    cpu_offload_use_pin_memory: Optional[bool] = None
    cpu_offload: Optional[bool] = None
    prefetch_bucket_size: int = Field(int(5e7), ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(int(1e5), ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(int(1e9), ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(int(1e9), ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(int(1e9), ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(False, alias="stage3_gather_16bit_weights_on_model_save")
    module_granularity_threshold: int = Field(0, alias="stage3_module_granularity_threshold")
    use_all_reduce_for_fetch_params: bool = Field(False, alias="stage3_use_all_reduce_for_fetch_params")
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = Field(1, ge=0)
    zero_quantized_weights: bool = False
    zero_quantized_nontrainable_weights: bool = False
    zero_quantized_gradients: bool = False
    mics_shard_size: int = Field(-1, alias="mics_shard_size")
    mics_hierarchical_params_gather: bool = False
    memory_efficient_linear: bool = True
    pipeline_loading_checkpoint: bool = False
    override_module_apply: bool = True

    @model_validator(mode="after")
    def offload_ratio_check(self):
        offload_config = self.offload_optimizer
        if offload_config and offload_config.ratio < 1.0:
            assert self.stage == 3, "Partial offload only supported for ZeRO Stage 3."
        return self

    @property
    def offload_optimizer_device(self):
        return self.offload_optimizer.device if self.offload_optimizer else "none"

    @property
    def offload_param_device(self):
        return self.offload_param.device if self.offload_param else "none"


# -------------------- precision --------------------


class FP16Config(ConfigModel):
    enabled: Any = False
    auto_cast: bool = False
    loss_scale: float = Field(0.0, ge=0.0)
    initial_scale_power: int = Field(16, ge=0)
    loss_scale_window: int = Field(1000, ge=0)
    hysteresis: int = Field(2, ge=0)
    consecutive_hysteresis: bool = False
    min_loss_scale: float = Field(1.0, ge=0.0)
    fp16_master_weights_and_grads: bool = False


class BF16Config(ConfigModel):
    enabled: Any = False
    immediate_grad_update: bool = True


class DataTypesConfig(ConfigModel):
    grad_accum_dtype: Optional[str] = None


# -------------------- activation checkpointing --------------------


class ActivationCheckpointingConfig(ConfigModel):
    """Reference ``runtime/activation_checkpointing/config.py``.

    On TPU: ``partition_activations`` maps to sharding the saved residuals
    over the ``model`` axis; cpu_checkpointing maps to host offload of remat
    inputs; contiguous/synchronize flags are accepted for parity (XLA owns
    buffer placement).
    """
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU extension: jax.checkpoint policy name
    remat_policy: Optional[str] = None


# -------------------- gradient comm planner (extension) --------------------


class CommQuantizationEnum(str, Enum):
    fp32 = "fp32"
    int8 = "int8"
    onebit = "onebit"


class GradientCommConfig(ConfigModel):
    """Bucketed + quantized gradient collectives (TPU extension; the analog
    of the reference's ``reduce_bucket_size``/``overlap_comm`` knobs, which
    are torch-mechanism-inert here — see ZeroConfig docstring — plus an
    EQuARX-style int8 wire tier between fp32 and the 1-bit sign path).

    - ``enabled``: build the bucketed gradient-comm program when supported
      (implied by overlap_comm or a non-fp32 quantization tier).
    - ``bucket_size_mb``: flat-bucket budget; gradients flow as
      ``ceil(total_bytes / bucket_size)`` collectives per dtype instead of
      one per pytree leaf.
    - ``comm_quantization``: wire tier for the gradient reduce —
      fp32 (exact), int8 (blockwise scale+zero-point, ~4x wire cut),
      onebit (sign+scale, ~32x).
    - ``quantization_block_size``: elements per int8 quantization block.
    - ``error_feedback``: carry the quantization residual into the next
      microbatch's gradients (quantized tiers only).
    - ``overlap_comm``: reduce bucket i inside the microbatch scan while
      microbatch i+1's backward runs (T3-style), carrying partially-reduced
      bucket shards through the scan instead of reducing the whole
      accumulated tree at the boundary.
    - ``comm_quantization_per_dtype``: per-dtype tier override, e.g.
      ``{"bfloat16": "int8"}`` — selects the tier per-bucket (buckets are
      dtype-homogeneous).
    """
    enabled: bool = False
    bucket_size_mb: float = Field(25.0, gt=0)
    comm_quantization: CommQuantizationEnum = CommQuantizationEnum.fp32
    quantization_block_size: int = Field(256, gt=0)
    error_feedback: bool = True
    overlap_comm: bool = False
    comm_quantization_per_dtype: Dict[str, CommQuantizationEnum] = {}

    @property
    def active(self) -> bool:
        return (self.enabled or self.overlap_comm
                or self.comm_quantization != CommQuantizationEnum.fp32
                or bool(self.comm_quantization_per_dtype))

    def tier_for_dtype(self, dtype) -> str:
        import numpy as _np
        key = str(_np.dtype(dtype))
        tier = self.comm_quantization_per_dtype.get(key, self.comm_quantization)
        return tier.value if isinstance(tier, CommQuantizationEnum) else str(tier)


# -------------------- comms logging --------------------


class CommsLoggerConfig(ConfigModel):
    enabled: bool = False
    prof_all: bool = True
    prof_ops: List[str] = []
    verbose: bool = False
    debug: bool = False


# -------------------- flops profiler --------------------


class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


# -------------------- monitors --------------------


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CometConfig(ConfigModel):
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: Optional[bool] = None
    mode: Optional[str] = None


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class MonitorConfig(ConfigModel):
    tensorboard: TensorBoardConfig = {}
    comet: CometConfig = {}
    wandb: WandbConfig = {}
    csv_monitor: CSVConfig = {}
    registry_events: bool = False
    """Also publish the process observability registry (counters/gauges/
    histogram percentiles from ``deepspeed_tpu.observability``) into the
    monitor fan-out at each flush — one event schema across training steps
    and serving metrics."""


# -------------------- AIO / NVMe --------------------


class AioConfig(ConfigModel):
    """Reference ``runtime/swap_tensor/aio_config.py`` keys; consumed by the
    C++ host AIO library (``deepspeed_tpu/csrc/aio.cpp``)."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True
    use_gds: bool = False


# -------------------- checkpoint --------------------


class ValidationMode(str, Enum):
    WARN = "WARN"
    IGNORE = "IGNORE"
    FAIL = "FAIL"


class ParallelWriteConfig(ConfigModel):
    pipeline_stage: bool = False


class CheckpointConfig(ConfigModel):
    """Knob disposition: tag_validation WIRED (cross-process tag agreement
    check before any write, reference engine.py:3092); load_universal WIRED
    (engine.load_universal_checkpoint path). use_node_local_storage and
    parallel_write.pipeline_stage are torch-engine IO staging knobs with no
    seam here — orbax owns per-process shard writes and async staging —
    accepted inert for config-file compatibility."""
    tag_validation: ValidationMode = ValidationMode.WARN
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: ParallelWriteConfig = {}


# -------------------- compile --------------------


class CompileConfig(ConfigModel):
    """Reference ``runtime/compiler.py`` surface; on TPU everything is always
    compiled — these knobs control jit options (donation, persistent cache).

    - ``cache_dir``: persistent XLA compilation-cache directory (the
      autotuner's ``_enable_compile_cache`` promoted into engine init).
      Multi-restart runs skip recompiles; a pre-existing
      ``JAX_COMPILATION_CACHE_DIR`` env/config always wins.
    - ``cache_min_compile_secs``: only programs whose compile took at least
      this long are persisted (JAX's
      ``jax_persistent_cache_min_compile_time_secs``).
    """
    enabled: bool = True
    backend: str = "xla"
    kwargs: Dict[str, Any] = {}
    cache_dir: Optional[str] = None
    cache_min_compile_secs: Optional[float] = Field(None, ge=0)


# -------------------- training observability --------------------


class TrainObservabilityConfig(ConfigModel):
    """TPU extension (``"observability"`` config block): training-side
    compile/goodput/MFU telemetry (``observability/xla.py`` +
    ``observability/goodput.py``), the training sibling of serving's
    ``ObservabilityConfig``.

    - ``enabled``: master gate. Off ⇒ the engine records nothing beyond
      the pre-existing ``ds_train_steps_total`` counter (the bench A/B
      arm).
    - ``goodput``: wall-clock goodput ledger
      (``ds_goodput_seconds_total{category=...}`` + fraction gauge).
    - ``compile_watch``: wrap every jitted step program so compile vs
      cache-hit vs retrace is counted per compile key
      (``ds_compile_seconds{key=...}`` etc.), and install the process-wide
      ``backend_compile_duration`` listener.
    - ``mfu``: publish ``ds_train_mfu`` from cost-analysis FLOPs at each
      registry publish (lazy AOT cost analysis — never on the step path).
    - ``memory``: refresh device-memory gauges (live/peak/limit bytes) at
      the publish cadence; silently absent on backends without
      ``memory_stats`` (CPU).
    - ``textfile``: path of an atomically-replaced Prometheus textfile
      written at each registry publish (training has no HTTP server; this
      is what ``ds_top --file`` and node-exporter textfile collectors
      read). ``DS_TPU_METRICS_TEXTFILE`` env is the fallback when unset.
    """
    enabled: bool = True
    goodput: bool = True
    compile_watch: bool = True
    mfu: bool = True
    memory: bool = True
    textfile: Optional[str] = None


class AsyncPipelineConfig(ConfigModel):
    """TPU extension: fully asynchronous train-step pipeline — keep the
    device's dispatch queue full by never blocking the host on a per-step
    device→host round trip in steady state.

    - ``enabled``: switch the engine's train paths to windowed host sync
      (losses/overflow flags accumulate as device scalars and are fetched
      in ONE batched transfer every ``sync_interval`` optimizer steps, or
      on demand via ``engine.get_loss()``), and skip the per-step
      ``effects_barrier`` in the throughput timer.
    - ``prefetch_depth``: how many upcoming batches the device-side
      prefetch iterator keeps in flight (``jax.device_put`` dispatched,
      sharded per the mesh) while the current step runs; 0 disables the
      prefetch wrap of ``engine.training_dataloader``.
    - ``sync_interval``: optimizer steps per host sync window. Deferred
      inside a window: loss fetch, overflow/skipped-step accounting, host
      lr-scheduler advance (compiled-path lr is exact regardless — optax
      reads the update count carried in opt_state), monitor events, and
      steps_per_print logging.
    """
    enabled: bool = False
    prefetch_depth: int = Field(2, ge=0)
    sync_interval: int = Field(16, ge=1)


# -------------------- resilience (extension) --------------------


class FaultInjectionConfig(ConfigModel):
    """Deterministic fault plan (``deepspeed_tpu/utils/fault_injection.py``).
    Each fault entry: ``{"site": <name>, "nth": 1, "times": 1, "args": {}}``
    — the site fires on its ``nth`` visit for ``times`` visits. Sites:
    checkpoint.torn_write, checkpoint.corrupt, train.sigterm,
    train.nan_grads, comm.init_timeout. Inert unless ``enabled``."""
    enabled: bool = False
    seed: int = 0
    faults: List[Dict[str, Any]] = []


class ResilienceConfig(ConfigModel):
    """Fault-tolerant training lifecycle (extension; reference analogue is
    Nebula tiered checkpointing + the elastic agent). Three cooperating
    pieces, all off by default:

    - **Preemption autosave / auto-resume**: SIGTERM/SIGINT request a save
      at the next step boundary (the async window is drained first so the
      snapshot is exact); ``autosave_interval_steps`` adds periodic saves;
      ``auto_resume`` scans ``save_dir`` at init for the newest checkpoint
      that passes manifest verification and restores it.
    - **Anomaly sentry**: watches overflow/loss-scaler signals plus a
      windowed loss-spike detector (loss > ``loss_spike_factor`` x median of
      the last ``loss_spike_window`` good losses, once
      ``loss_spike_min_history`` good steps exist). After
      ``max_consecutive_anomalies`` consecutive bad steps it rolls params /
      opt-state back to the last good checkpoint while keeping the data
      sampler's position — the offending data window is skipped, not
      replayed.
    - **Retention**: ``keep_last_n`` committed tags survive GC (0 keeps
      all); storage writes retry with exponential backoff
      (``save_retries`` attempts, ``retry_backoff_secs`` base delay).
    """
    enabled: bool = False
    save_dir: Optional[str] = None
    autosave_interval_steps: int = Field(0, ge=0)
    keep_last_n: int = Field(3, ge=0)
    auto_resume: bool = False
    preempt_save: bool = True
    preempt_signals: List[str] = ["SIGTERM", "SIGINT"]
    max_consecutive_anomalies: int = Field(3, ge=1)
    loss_spike_window: int = Field(20, ge=2)
    loss_spike_factor: float = Field(3.0, gt=1.0)
    loss_spike_min_history: int = Field(5, ge=1)
    rollback: bool = True
    save_retries: int = Field(3, ge=1)
    retry_backoff_secs: float = Field(0.05, ge=0)
    fault_injection: FaultInjectionConfig = {}


# -------------------- TPU mesh (extension) --------------------


class MeshConfig(ConfigModel):
    """TPU extension: logical mesh shape. -1 on an axis means "fill with
    remaining devices". Axes order fixed: (pipe, data, fsdp, seq, expert, model)."""
    pipe: int = 1
    data: int = -1
    fsdp: int = 1
    seq: int = 1
    expert: int = 1
    model: int = 1
    axis_order: List[str] = ["pipe", "data", "fsdp", "seq", "expert", "model"]


class TensorParallelConfig(ConfigModel):
    """Native tensor-parallel TRAINING (extension beyond the reference,
    which delegates training TP to a user-provided Megatron ``mpu`` —
    ``deepspeed/runtime/engine.py`` mpu plumbing, ``utils/groups.py:68``).
    Here TP is a sharding rule composed WITH the ZeRO plan: linear weights
    are column/row-sharded over the mesh ``model`` axis (AutoTP name
    heuristics / logical-axis rules, ``parallel/tp.py``) and ZeRO shards a
    dimension TP left free, so ZeRO-1/2/3 x TP compose in one program and
    XLA inserts the per-layer psum the reference's mpu codes by hand.

    ``tp_size`` also creates the mesh ``model`` axis when the mesh config
    doesn't name one (the inference config's ``tensor_parallel.tp_size``
    spelling). ``enabled`` engages composition on an existing model axis."""
    enabled: bool = False
    tp_size: Optional[int] = None
