"""Top-level config tree.

TPU-native analog of ``DeepSpeedConfig`` (reference ``runtime/config.py:706``):
parses a JSON dict/file with the same keys, enforces the batch-size triangle
``train_batch_size = micro_batch * grad_accum * dp_world_size``
(reference ``runtime/config.py:917 _batch_assertion``), and exposes per-feature
sub-configs.
"""

import json
import os
from typing import Any, Dict, Optional

from .config_utils import dict_raise_error_on_duplicate_keys
from .feature_configs import (
    ActivationCheckpointingConfig,
    AioConfig,
    AsyncPipelineConfig,
    BF16Config,
    CheckpointConfig,
    CommsLoggerConfig,
    CompileConfig,
    DataTypesConfig,
    FlopsProfilerConfig,
    FP16Config,
    GradientCommConfig,
    MeshConfig,
    MonitorConfig,
    ResilienceConfig,
    TensorParallelConfig,
    TrainObservabilityConfig,
    ZeroConfig,
)
from ..utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
LION_OPTIMIZER = "lion"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
# muP width-scaled variants (reference runtime/config.py:79-81)
MUADAM_OPTIMIZER = "muadam"
MUADAMW_OPTIMIZER = "muadamw"
MUSGD_OPTIMIZER = "musgd"

DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, LION_OPTIMIZER, SGD_OPTIMIZER, ADAGRAD_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER,
    MUADAM_OPTIMIZER, MUADAMW_OPTIMIZER, MUSGD_OPTIMIZER
]

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"


class ScientificNotationEncoder(json.JSONEncoder):
    """Print ints >= 1e3 in scientific notation when dumping configs."""

    def iterencode(self, o, _one_shot=False, level=0):
        indent = self.indent if self.indent is not None else 4
        prefix_close = " " * level * indent
        level += 1
        prefix = " " * level * indent
        if isinstance(o, bool):
            return "true" if o else "false"
        elif isinstance(o, float) or isinstance(o, int):
            if o > 1e3:
                return f"{o:e}"
            else:
                return f"{o}"
        elif isinstance(o, dict):
            x = [f'\n{prefix}"{k}": {self.iterencode(v, level=level)}' for k, v in o.items()]
            return "{" + ", ".join(x) + f"\n{prefix_close}" + "}"
        elif isinstance(o, list):
            x = [f"\n{prefix}{self.iterencode(el, level=level)}" for el in o]
            return "[" + ", ".join(x) + f"\n{prefix_close}" + "]"
        return "".join(super().iterencode(o, _one_shot))


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedTpuConfig:
    """The validated config tree the engine reads everywhere."""

    def __init__(self, config: Any, world_size: Optional[int] = None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(f"DeepSpeed config file not found: {config}")
            with open(config) as f:
                self._param_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        elif isinstance(config, DeepSpeedTpuConfig):
            self._param_dict = dict(config._param_dict)
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path to a json file or a dict, got: {type(config)}")

        self.world_size = world_size if world_size is not None else self._detect_world_size()
        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    def reresolve(self, world_size: int):
        """Re-run batch-triangle resolution for a corrected dp world size
        (the engine learns the true dp = data*fsdp only after the mesh is
        built; see engine.py)."""
        if world_size == self.world_size:
            return
        self.world_size = world_size
        self.train_batch_size = self._param_dict.get(TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = self._param_dict.get(TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = self._param_dict.get(GRADIENT_ACCUMULATION_STEPS)
        self._configure_train_batch_size()
        self._do_sanity_check()

    @staticmethod
    def _detect_world_size():
        try:
            import jax
            return jax.device_count()
        except Exception:
            return int(os.environ.get("WORLD_SIZE", 1))

    # ------------------------------------------------------------------

    def _initialize_params(self, pd: Dict[str, Any]):
        self.train_batch_size = pd.get(TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = pd.get(TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = pd.get(GRADIENT_ACCUMULATION_STEPS)

        self.steps_per_print = pd.get("steps_per_print", 10)
        self.dump_state = pd.get("dump_state", False)
        self.wall_clock_breakdown = pd.get("wall_clock_breakdown", False)
        self.memory_breakdown = pd.get("memory_breakdown", False)
        self.prescale_gradients = pd.get("prescale_gradients", False)
        self.gradient_predivide_factor = pd.get("gradient_predivide_factor", 1.0)
        self.sparse_gradients_enabled = pd.get("sparse_gradients", False)
        self.gradient_clipping = pd.get("gradient_clipping", 0.0)
        self.communication_data_type = pd.get("communication_data_type", None)
        self.disable_allgather = pd.get("disable_allgather", False)
        self.zero_allow_untested_optimizer = pd.get("zero_allow_untested_optimizer", False)
        self.zero_force_ds_cpu_optimizer = pd.get("zero_force_ds_cpu_optimizer", True)

        self.optimizer_name = None
        self.optimizer_params = None
        self.optimizer_legacy_fusion = False
        opt = pd.get("optimizer")
        if opt is not None:
            self.optimizer_name = opt.get("type", "").lower()
            self.optimizer_params = opt.get("params", {})
            self.optimizer_legacy_fusion = opt.get("legacy_fusion", False)

        self.scheduler_name = None
        self.scheduler_params = None
        sched = pd.get("scheduler")
        if sched is not None:
            self.scheduler_name = sched.get("type")
            self.scheduler_params = sched.get("params", {})

        self.zero_config = ZeroConfig(**pd.get("zero_optimization", {}))
        self.fp16_config = FP16Config(**pd.get("fp16", {}))
        self.bf16_config = BF16Config(**pd.get("bf16", pd.get("bfloat16", {})))
        self.data_types_config = DataTypesConfig(**pd.get("data_types", {}))
        self.activation_checkpointing_config = ActivationCheckpointingConfig(
            **pd.get("activation_checkpointing", {}))
        self.comms_config = CommsLoggerConfig(**pd.get("comms_logger", {}))
        self.gradient_comm_config = GradientCommConfig(**pd.get("gradient_comm", {}))
        self.flops_profiler_config = FlopsProfilerConfig(**pd.get("flops_profiler", {}))
        self.monitor_config = MonitorConfig(
            tensorboard=pd.get("tensorboard", {}),
            wandb=pd.get("wandb", {}),
            csv_monitor=pd.get("csv_monitor", {}),
            comet=pd.get("comet", {}),
            registry_events=bool(pd.get("registry_events", False)),
        )
        self.observability_config = TrainObservabilityConfig(
            **pd.get("observability", {}))
        self.aio_config = AioConfig(**pd.get("aio", {}))
        self.checkpoint_config = CheckpointConfig(**pd.get("checkpoint", {}))
        self.compile_config = CompileConfig(**pd.get("compile", {}))
        self.async_pipeline_config = AsyncPipelineConfig(
            **pd.get("async_pipeline", {}))
        self.resilience_config = ResilienceConfig(**pd.get("resilience", {}))
        self.mesh_config = MeshConfig(**pd.get("mesh", {}))
        self.tensor_parallel_config = TensorParallelConfig(
            **pd.get("tensor_parallel", {}))

        self.elasticity_enabled = bool(pd.get("elasticity", {}).get("enabled", False))
        self.elasticity_config = pd.get("elasticity", {})
        self.autotuning_config = pd.get("autotuning", {})
        self.compression_config = pd.get("compression_training", {})
        self.curriculum_enabled_legacy = bool(pd.get("curriculum_learning", {}).get("enabled", False))
        self.curriculum_params_legacy = pd.get("curriculum_learning", {})
        self.data_efficiency_config = pd.get("data_efficiency", {})

        # Pipeline parallelism settings (engine-level; reference engine.py pipeline plumbing)
        self.pipeline_config = pd.get("pipeline", {})

        # Sequence parallel (Ulysses) degree; mesh 'seq' axis wins if both given.
        self.sequence_parallel_size = pd.get("sequence_parallel_size", self.mesh_config.seq)

        self.eigenvalue_config = pd.get("eigenvalue", {})
        self.use_data_before_expert_parallel_ = pd.get("use_data_before_expert_parallel", False)
        self.hybrid_engine_config = pd.get("hybrid_engine", {})
        self.nebula_config = pd.get("nebula", {})
        self.weight_quantization_config = pd.get("weight_quantization", {})

        self.load_universal_checkpoint = self.checkpoint_config.load_universal
        self.use_node_local_storage = self.checkpoint_config.use_node_local_storage

        self.graph_harvesting = pd.get("graph_harvesting", False)
        self.seed = pd.get("seed", 42)

        # TPU-native extension (no reference key): where the fp32-master ->
        # compute-dtype cast happens. "engine" casts the whole tree before
        # apply (safe for models that ignore dtype); "model" passes fp32
        # masters straight through and relies on the model's use-site casts
        # (the flax `dtype=` convention). For nn.scan-stacked models "model"
        # is the structural fix for whole-model-sized convert_element_type
        # temps: each scan step casts only its chunk's slice.
        self.param_cast = pd.get("param_cast", "engine")
        if self.param_cast not in ("engine", "model"):
            raise ValueError(
                f'param_cast must be "engine" or "model", got {self.param_cast!r}')

    # ------------------------------------------------------------------

    def _configure_train_batch_size(self):
        """Resolve the batch triangle (reference ``config.py:846-915``)."""
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        dp = max(self.world_size, 1)

        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= dp
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // dp
            micro_batch //= grad_acc
        elif micro_batch is not None and grad_acc is not None:
            train_batch = micro_batch * grad_acc * dp
        elif train_batch is not None:
            grad_acc = 1
            micro_batch = train_batch // dp
        elif micro_batch is not None:
            train_batch = micro_batch * dp
            grad_acc = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided")

        self.train_batch_size = train_batch
        self.train_micro_batch_size_per_gpu = micro_batch
        self.gradient_accumulation_steps = grad_acc

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        dp = max(self.world_size, 1)
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * dp, (
            f"Check batch related parameters. train_batch_size is not equal "
            f"to micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {dp}")

    def _do_sanity_check(self):
        self._batch_assertion()
        if self.optimizer_name is not None and self.optimizer_name not in DEEPSPEED_OPTIMIZERS:
            # Unknown optimizers fall through to optax lookup at engine build;
            # mirror reference behavior of allowing client optimizers.
            logger.debug(f"Optimizer {self.optimizer_name} not a built-in; "
                         "will resolve against optax at engine build time.")
        if self.fp16_enabled and self.bf16_enabled:
            raise DeepSpeedConfigError("fp16 and bf16 modes cannot both be enabled")

    # ------------------------------------------------------------------

    @property
    def fp16_enabled(self):
        return bool(self.fp16_config.enabled)

    @property
    def bf16_enabled(self):
        return bool(self.bf16_config.enabled)

    @property
    def loss_scale(self):
        return self.fp16_config.loss_scale

    @property
    def dynamic_loss_scale(self):
        return self.fp16_config.loss_scale == 0

    @property
    def zero_enabled(self):
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self):
        return self.zero_config.stage

    def print(self, name="DeepSpeedTpuConfig"):
        logger.info("{}:".format(name))
        logger.info(json.dumps(self._param_dict, sort_keys=True, indent=4, cls=ScientificNotationEncoder,
                               default=str))

    def dump(self):
        return dict(self._param_dict)
