"""Flops profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:29 FlopsProfiler``
— monkey-patched torch functionals + module hooks accumulating analytic
flops/macs/latency per module, printed as a depth-tree; feeds the autotuner.

TPU rebuild, two complementary sources:
1. **Exact totals from XLA**: a jitted function's
   ``lowered.compile().cost_analysis()`` reports the true post-fusion flops
   and bytes accessed — strictly better than the reference's analytic sums
   (which miss fusion effects). Exposed via ``profile_compiled``.
2. **Per-module breakdown**: flax interception (``nn.Module`` capture) with
   analytic per-primitive counts — same numbers the reference's hooks
   produce, for the familiar per-depth model tree.

The reference's latency hooks become wall-clock timing of the compiled
step (device events are XLA's business; per-module latency inside one fused
program is not observable, which is exactly why source (1) exists).
"""

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...utils.logging import logger


# ---------------------------------------------------------------- utilities

def num_to_string(num, precision=2):
    if num // 10**9 > 0:
        return str(round(num / 10.0**9, precision)) + " G"
    elif num // 10**6 > 0:
        return str(round(num / 10.0**6, precision)) + " M"
    elif num // 10**3 > 0:
        return str(round(num / 10.0**3, precision)) + " K"
    return str(num)


def flops_to_string(flops, units=None, precision=2):
    """Reference profiler.py flops_to_string."""
    if units is None:
        return num_to_string(flops, precision) + "FLOPS"
    return str(round(flops / {"GFLOPS": 1e9, "MFLOPS": 1e6, "KFLOPS": 1e3}.get(units, 1.0),
                     precision)) + " " + units


def params_to_string(n, precision=2):
    return num_to_string(n, precision).rstrip()


def duration_to_string(seconds, precision=2):
    if seconds > 1:
        return str(round(seconds, precision)) + " s"
    if seconds * 1e3 > 1:
        return str(round(seconds * 1e3, precision)) + " ms"
    return str(round(seconds * 1e6, precision)) + " us"


# ------------------------------------------------------- XLA cost analysis

def profile_compiled(fn: Callable, *args, static_argnums=(), **kwargs) -> Dict[str, float]:
    """Exact flops/bytes of fn's compiled XLA program (the numbers the MXU
    actually executes). fn may already be jitted."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn, static_argnums=static_argnums)
    compiled = jitted.lower(*args, **kwargs).compile()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns [dict]
        costs = costs[0] if costs else {}
    return {
        "flops": float(costs.get("flops", 0.0)),
        "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
        "transcendentals": float(costs.get("transcendentals", 0.0)),
    }


# --------------------------------------------------- analytic module walk

def _count_params(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "shape"))


def _analytic_macs(path: Tuple[str, ...], leaf, batch_tokens: int) -> int:
    """Dense kernel [in, out] → in*out MACs per token (reference counts
    Linear as in*out macs per sample); embeddings are lookups (0 macs)."""
    if path and path[-1] == "kernel" and hasattr(leaf, "shape") and len(leaf.shape) >= 2:
        return int(np.prod(leaf.shape)) * batch_tokens
    return 0


class _Node:
    __slots__ = ("name", "params", "macs", "children")

    def __init__(self, name):
        self.name = name
        self.params = 0
        self.macs = 0
        self.children: Dict[str, "_Node"] = {}


def _build_tree(params, batch_tokens: int) -> _Node:
    root = _Node("model")

    def visit(node, tree, path):
        if isinstance(tree, dict):
            for k, v in tree.items():
                child = node.children.setdefault(k, _Node(k))
                visit(child, v, path + (k, ))
                node.params += child.params
                node.macs += child.macs
        else:
            if hasattr(tree, "shape"):
                node.params += int(np.prod(tree.shape))
            node.macs += _analytic_macs(path, tree, batch_tokens)

    visit(root, params, ())
    return root


# ----------------------------------------------------------- the profiler

class FlopsProfiler:
    """Reference-parity API surface over the XLA cost model.

    Usage (matches reference):
        prof = FlopsProfiler(model, ds_engine=engine)
        prof.start_profile()
        ... run a step ...
        prof.stop_profile()
        prof.print_model_profile(profile_step=step)
        flops, macs, params = prof.get_total_flops(), ...
    """

    def __init__(self, model=None, ds_engine=None, recompute_fwd_factor: float = 0.0):
        self.model = model
        self.ds_engine = ds_engine
        self.recompute_fwd_factor = recompute_fwd_factor
        self.started = False
        self._t0 = None
        self._duration = 0.0
        self._flops = 0.0
        self._bytes = 0.0
        self._params_tree = None

    # ---- lifecycle (reference start_profile/stop_profile/end_profile) ----

    def start_profile(self, ignore_list=None, skip_engine_cost=False):
        """``skip_engine_cost``: don't accrue the engine's split-path
        ``_fwd_bwd`` cost — the caller is about to ``profile_fn`` a fused
        program that already CONTAINS the fwd+bwd (counting both would
        double the reported flops)."""
        if self.started:
            return  # idempotent: engine auto-hook + a manual start must not
            # double-count the compiled program's flops
        self.started = True
        if self.ds_engine is not None:
            self._params_tree = self.ds_engine.params
            # exact flops of the engine's compiled fwd+bwd at current shapes
            try:
                spec = self.ds_engine.last_fwd_spec
                if spec is not None and not skip_engine_cost:
                    costs = profile_compiled(self.ds_engine._fwd_bwd, *spec)
                    self._flops += costs["flops"]
                    self._bytes += costs["bytes_accessed"]
            except Exception as e:  # cost analysis is best-effort per backend
                logger.debug(f"flops cost analysis unavailable: {e}")
        # timing window opens AFTER the cost analysis: its AOT
        # lower().compile() can take seconds and would otherwise be billed
        # to the step, wrecking achieved-throughput / hw-utilization
        self._t0 = time.perf_counter()

    def profile_fn(self, fn, *args, **kwargs):
        """Accumulate exact costs of one more compiled fn (multi-program
        steps: fwd_bwd + apply)."""
        costs = profile_compiled(fn, *args, **kwargs)
        self._flops += costs["flops"]
        self._bytes += costs["bytes_accessed"]
        if self.started:
            # same rule as start_profile: analysis compile time is not step
            # time — restart the wall-clock window
            self._t0 = time.perf_counter()
        return costs

    def stop_profile(self):
        if self.started and self._t0 is not None:
            self._duration = time.perf_counter() - self._t0
        self.started = False

    def end_profile(self):
        self.stop_profile()
        self._flops = self._bytes = 0.0

    def reset_profile(self):
        self._flops = self._bytes = self._duration = 0.0

    # ---- getters (reference get_total_*) ----

    def get_total_flops(self, as_string=False):
        f = self._flops * (1.0 + self.recompute_fwd_factor)
        return flops_to_string(f) if as_string else f

    def get_total_macs(self, as_string=False):
        m = self._flops / 2  # XLA reports flops; macs ≈ flops/2 for matmul-dominated
        return num_to_string(m) + "MACs" if as_string else m

    def get_total_params(self, as_string=False):
        tree = self._params_tree if self._params_tree is not None else \
            (self.model if isinstance(self.model, dict) else {})
        n = _count_params(tree)
        return params_to_string(n) if as_string else n

    def get_total_duration(self, as_string=False):
        return duration_to_string(self._duration) if as_string else self._duration

    def get_total_bytes(self):
        return self._bytes

    # ---- report (reference print_model_profile) ----

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None, batch_tokens: int = 1):
        lines = []
        lines.append("\n-------------------------- DeepSpeed-TPU Flops Profiler "
                     "--------------------------")
        lines.append(f"Profile Summary at step {profile_step}:")
        lines.append("Notations:\ndata parallel size (dp_size), flops per step (flops), "
                     "achieved bytes/s vs flops/s from XLA cost analysis")
        lines.append(f"params:                 {self.get_total_params(True)}")
        lines.append(f"flops per step:         {self.get_total_flops(True)}")
        lines.append(f"bytes accessed:         {num_to_string(self._bytes)}B")
        lines.append(f"profiled duration:      {self.get_total_duration(True)}")
        if self._duration > 0:
            achieved = self._flops / self._duration
            lines.append(f"achieved throughput:    {flops_to_string(achieved)}/s")
            from ...accelerator import get_accelerator
            try:
                peak = get_accelerator().peak_bf16_flops()
                lines.append(f"hw utilization:         {achieved / peak:.2%} "
                             f"of {flops_to_string(peak)}/s peak")
            except Exception:  # pragma: no cover — exotic accelerator
                pass
        tree = None
        if detailed and self._params_tree is not None:
            tree = _build_tree(self._params_tree, batch_tokens)
            lines.append("\nper-module breakdown (analytic MACs @ "
                         f"{batch_tokens} tokens):")
            self._render(tree, lines, depth=0,
                         max_depth=module_depth if module_depth >= 0 else 3)
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        else:
            print(report)
        return report

    def _render(self, node: _Node, lines: List[str], depth: int, max_depth: int):
        if depth > max_depth:
            return
        indent = "  " * depth
        lines.append(f"{indent}{node.name}: params={params_to_string(node.params)}, "
                     f"macs={num_to_string(node.macs)}")
        for child in node.children.values():
            self._render(child, lines, depth + 1, max_depth)


def get_model_profile(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
                      params=None, batch_tokens: int = 1, print_profile=True,
                      as_string=True):
    """Standalone entry (reference get_model_profile): profile any jittable
    fn without an engine. Returns (flops, macs, params)."""
    kwargs = kwargs or {}
    costs = profile_compiled(fn, *args, **kwargs)
    n_params = _count_params(params) if params is not None else 0
    prof = FlopsProfiler()
    prof._flops = costs["flops"]
    prof._bytes = costs["bytes_accessed"]
    prof._params_tree = params
    if print_profile:
        prof.print_model_profile(batch_tokens=batch_tokens)
    if as_string:
        return (prof.get_total_flops(True), prof.get_total_macs(True),
                params_to_string(n_params))
    return costs["flops"], costs["flops"] / 2, n_params
