"""Multi-host launcher.

Reference: ``deepspeed/launcher/runner.py:399 main`` (hostfile parsing :211,
resource filtering :266, PDSH/MPI runners in multinode_runner.py) and the
per-node ``launch.py:133``.

TPU shape of the problem: JAX is single-controller-per-host SPMD — ONE
process per host (not per chip), rendezvoused through
``jax.distributed.initialize(coordinator, num_processes, process_id)``. So
the launcher reduces to: parse hostfile → assign process ids → ssh each host
and exec the script with the rendezvous env (the reference's env-propagation
contract: we forward DS_/JAX_/XLA_ prefixed vars + --export list). On a
single host it just execs locally (chips are already visible to one
process).
"""

import argparse
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_PREFIXES = ("DS_", "JAX_", "XLA_", "TPU_", "PYTHON", "PATH", "LD_LIBRARY_PATH")


def parse_hostfile(path: str) -> "OrderedDict[str, int]":
    """'hostname slots=N' lines → {host: slots} (reference runner.py:211)."""
    resources: "OrderedDict[str, int]" = OrderedDict()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if host in resources:
                raise ValueError(f"host {host} appears twice in hostfile")
            resources[host] = slots
    if not resources:
        raise ValueError(f"no hosts found in hostfile {path}")
    return resources


def filter_resources(resources: "OrderedDict[str, int]", include: str = "",
                     exclude: str = "") -> "OrderedDict[str, int]":
    """--include/--exclude 'host1@host2' filtering (reference :266; slot
    selection is meaningless on TPU hosts so only whole hosts filter)."""
    def hostset(spec):
        return {h for h in spec.replace("@", " ").split() if h}
    inc, exc = hostset(include), hostset(exclude)
    out = OrderedDict()
    for host, slots in resources.items():
        if inc and host not in inc:
            continue
        if host in exc:
            continue
        out[host] = slots
    if not out:
        raise ValueError("resource filtering removed every host")
    return out


def _export_env(extra: List[str]) -> Dict[str, str]:
    env = {k: v for k, v in os.environ.items() if k.startswith(EXPORT_PREFIXES)}
    for name in extra:
        if name in os.environ:
            env[name] = os.environ[name]
    return env


def build_commands(hosts: List[str], master_addr: str, master_port: int,
                   script: str, script_args: List[str],
                   exports: Dict[str, str]) -> List[List[str]]:
    """One ssh command per host with the JAX rendezvous env."""
    cmds = []
    for pid, host in enumerate(hosts):
        env = dict(exports)
        env["JAX_COORDINATOR_ADDRESS"] = f"{master_addr}:{master_port}"
        env["JAX_NUM_PROCESSES"] = str(len(hosts))
        env["JAX_PROCESS_ID"] = str(pid)
        env_str = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        remote = f"cd {shlex.quote(os.getcwd())} && {env_str} " \
                 f"{sys.executable} {shlex.quote(script)} " \
                 f"{' '.join(shlex.quote(a) for a in script_args)}"
        if host in ("localhost", "127.0.0.1"):
            # local processes exec directly, no ssh (also lets tests drive a
            # real 2-process rendezvous by calling build_commands with
            # repeated localhost entries)
            cmds.append(["bash", "-c", remote])
        else:
            cmds.append(["ssh", "-o", "StrictHostKeyChecking=no", host, remote])
    return cmds


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu multi-host launcher (reference bin/deepspeed)")
    parser.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE)
    parser.add_argument("-i", "--include", default="")
    parser.add_argument("-e", "--exclude", default="")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_addr", default=None)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--export", action="append", default=[],
                        help="extra env var names to forward")
    parser.add_argument("--dry_run", action="store_true",
                        help="print commands without executing")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if os.path.exists(args.hostfile):
        resources = filter_resources(parse_hostfile(args.hostfile),
                                     args.include, args.exclude)
        hosts = list(resources)
    else:
        hosts = ["localhost"]
    if args.num_nodes > 0:
        hosts = hosts[:args.num_nodes]
    master = args.master_addr or hosts[0]

    if len(hosts) == 1 and not args.dry_run:
        # single host: exec in place, no rendezvous env needed
        os.execvpe(sys.executable, [sys.executable, args.script] + args.script_args,
                   os.environ)

    cmds = build_commands(hosts, master, args.master_port, args.script,
                          args.script_args, _export_env(args.export))
    if args.dry_run:
        for c in cmds:
            print(" ".join(shlex.quote(x) for x in c))
        return 0
    procs = [subprocess.Popen(c) for c in cmds]
    rc = 0
    for p in procs:
        rc = rc or p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
