"""Multi-host launcher.

Reference: ``deepspeed/launcher/runner.py:399 main`` (hostfile parsing :211,
resource filtering :266, PDSH/MPI runners in multinode_runner.py) and the
per-node ``launch.py:133``.

TPU shape of the problem: JAX is single-controller-per-host SPMD — ONE
process per host (not per chip), rendezvoused through
``jax.distributed.initialize(coordinator, num_processes, process_id)``. So
the launcher reduces to: parse hostfile → assign process ids → ssh each host
and exec the script with the rendezvous env (the reference's env-propagation
contract: we forward DS_/JAX_/XLA_ prefixed vars + --export list). On a
single host it just execs locally (chips are already visible to one
process).
"""

import argparse
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_PREFIXES = ("DS_", "JAX_", "XLA_", "TPU_", "PYTHON", "PATH", "LD_LIBRARY_PATH")


def parse_hostfile(path: str) -> "OrderedDict[str, int]":
    """'hostname slots=N' lines → {host: slots} (reference runner.py:211)."""
    resources: "OrderedDict[str, int]" = OrderedDict()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if host in resources:
                raise ValueError(f"host {host} appears twice in hostfile")
            resources[host] = slots
    if not resources:
        raise ValueError(f"no hosts found in hostfile {path}")
    return resources


def filter_resources(resources: "OrderedDict[str, int]", include: str = "",
                     exclude: str = "") -> "OrderedDict[str, int]":
    """--include/--exclude 'host1@host2' filtering (reference :266; slot
    selection is meaningless on TPU hosts so only whole hosts filter)."""
    def hostset(spec):
        return {h for h in spec.replace("@", " ").split() if h}
    inc, exc = hostset(include), hostset(exclude)
    out = OrderedDict()
    for host, slots in resources.items():
        if inc and host not in inc:
            continue
        if host in exc:
            continue
        out[host] = slots
    if not out:
        raise ValueError("resource filtering removed every host")
    return out


def _is_local_host(host: str) -> bool:
    """True when `host` is this machine (reference runner.py treats the
    one-line hostfile naming the local node as a local launch, not
    ssh-to-self)."""
    import socket
    if host in ("localhost", "127.0.0.1", "::1"):
        return True
    try:
        return host in (socket.gethostname(), socket.getfqdn())
    except OSError:  # hostname lookup failure: treat as remote
        return False


def _export_env(extra: List[str]) -> Dict[str, str]:
    env = {k: v for k, v in os.environ.items() if k.startswith(EXPORT_PREFIXES)}
    for name in extra:
        if name in os.environ:
            env[name] = os.environ[name]
    return env


def _remote_command(env: Dict[str, str], script: str,
                    script_args: List[str]) -> str:
    """cd-to-cwd + env + python invocation, shell-quoted (shared by the ssh
    and pdsh fan-outs so quoting/cwd fixes can't drift apart)."""
    env_str = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    return (f"cd {shlex.quote(os.getcwd())} && {env_str} "
            f"{sys.executable} {shlex.quote(script)} "
            f"{' '.join(shlex.quote(a) for a in script_args)}")


def build_commands(hosts: List[str], master_addr: str, master_port: int,
                   script: str, script_args: List[str],
                   exports: Dict[str, str]) -> List[List[str]]:
    """One ssh command per host with the JAX rendezvous env."""
    cmds = []
    for pid, host in enumerate(hosts):
        env = dict(exports)
        env["JAX_COORDINATOR_ADDRESS"] = f"{master_addr}:{master_port}"
        env["JAX_NUM_PROCESSES"] = str(len(hosts))
        env["JAX_PROCESS_ID"] = str(pid)
        # reference launch.py exports these unconditionally and ported
        # scripts (plus utils/logging, config) read them on every rank
        env["RANK"] = str(pid)
        env["LOCAL_RANK"] = "0"  # one process per host under SPMD
        env["WORLD_SIZE"] = str(len(hosts))
        env["MASTER_ADDR"] = master_addr
        env["MASTER_PORT"] = str(master_port)
        remote = _remote_command(env, script, script_args)
        if _is_local_host(host):
            # local processes exec directly, no ssh (also lets tests drive a
            # real 2-process rendezvous by calling build_commands with
            # repeated localhost entries); same predicate as main()'s
            # single-host gate so dry-run output matches real behavior
            cmds.append(["bash", "-c", remote])
        else:
            cmds.append(["ssh", "-o", "StrictHostKeyChecking=no", host, remote])
    return cmds


# ---------------------------------------------------------------------------
# multinode runners (reference launcher/multinode_runner.py:51-418)
# ---------------------------------------------------------------------------


class MultiNodeRunner:
    """One fan-out backend = one command synthesis. The reference subclasses
    (PDSH :51, OpenMPI :118, Slurm :328) each build a single launcher command
    that starts every rank; the per-rank rendezvous env is then derived by
    ``comm.mpi_discovery`` on each node (OMPI_*/SLURM_*/DS_HOSTLIST), so no
    runner needs per-host command lines."""

    name = "base"

    def __init__(self, hosts: List[str], master_addr: str, master_port: int,
                 exports: Dict[str, str]):
        self.hosts = list(hosts)
        self.master_addr = master_addr
        self.master_port = master_port
        self.exports = dict(exports)

    def backend_exists(self) -> bool:
        from shutil import which
        return which(self._probe_binary) is not None

    def get_cmd(self, script: str, script_args: List[str]) -> List[str]:
        raise NotImplementedError


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out: identical command on every host; each node finds its
    process id by locating its hostname in DS_HOSTLIST (mpi_discovery)."""

    name = "pdsh"
    _probe_binary = "pdsh"

    def get_cmd(self, script, script_args):
        env = dict(self.exports)
        env["DS_HOSTLIST"] = ",".join(self.hosts)
        env["JAX_COORDINATOR_ADDRESS"] = f"{self.master_addr}:{self.master_port}"
        remote = _remote_command(env, script, script_args)
        return ["pdsh", "-S", "-f", "1024", "-w", ",".join(self.hosts), remote]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun fan-out: OMPI_COMM_WORLD_SIZE/RANK reach every rank natively;
    the coordinator address is pinned explicitly (the OMPI hnp uri is only a
    fallback) so rendezvous never depends on OpenMPI internals."""

    name = "openmpi"
    _probe_binary = "mpirun"

    def get_cmd(self, script, script_args):
        env = dict(self.exports)
        env["JAX_COORDINATOR_ADDRESS"] = f"{self.master_addr}:{self.master_port}"
        cmd = ["mpirun", "-np", str(len(self.hosts)), "--host",
               ",".join(self.hosts), "--map-by", "ppr:1:node",
               "--allow-run-as-root"]
        for k, v in env.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + [sys.executable, script] + list(script_args)


class SlurmRunner(MultiNodeRunner):
    """srun fan-out: SLURM_NTASKS/SLURM_PROCID reach every task; one task
    per node (JAX is one process per host)."""

    name = "slurm"
    _probe_binary = "srun"

    def get_cmd(self, script, script_args):
        env = dict(self.exports)
        env["JAX_COORDINATOR_ADDRESS"] = f"{self.master_addr}:{self.master_port}"
        # env rides the caller environment (--export=ALL propagates it) via
        # an `env` prefix: srun's --export=K=V list breaks on values that
        # contain commas, which XLA_FLAGS and friends routinely do
        return (["env"] + [f"{k}={v}" for k, v in env.items()]
                + ["srun", "-N", str(len(self.hosts)), "--ntasks",
                   str(len(self.hosts)), "--ntasks-per-node", "1",
                   "--nodelist", ",".join(self.hosts), "--export=ALL",
                   sys.executable, script] + list(script_args))


class MPICHRunner(MultiNodeRunner):
    """MPICH hydra fan-out (reference multinode_runner.py MPICHRunner):
    PMI_RANK/PMI_SIZE reach every rank; the coordinator address is pinned
    via -genv because the PMI v1 env carries none."""

    name = "mpich"
    _probe_binary = "mpiexec.hydra"

    def get_cmd(self, script, script_args):
        env = dict(self.exports)
        env["JAX_COORDINATOR_ADDRESS"] = f"{self.master_addr}:{self.master_port}"
        cmd = ["mpiexec.hydra", "-np", str(len(self.hosts)), "-ppn", "1",
               "-hosts", ",".join(self.hosts)]
        for k, v in env.items():
            cmd += ["-genv", k, v]
        return cmd + [sys.executable, script] + list(script_args)


class IMPIRunner(MPICHRunner):
    """Intel MPI fan-out (reference IMPIRunner): hydra-compatible CLI, but
    probes Intel's mpiexec and turns off its rank pinning, which fights the
    one-process-per-host JAX model."""

    name = "impi"
    _probe_binary = "mpiexec"

    def get_cmd(self, script, script_args):
        cmd = super().get_cmd(script, script_args)
        cmd[0] = "mpiexec"
        # one controller process per host owns all local chips: no pinning
        return cmd[:1] + ["-genv", "I_MPI_PIN", "0"] + cmd[1:]


class MVAPICHRunner(MultiNodeRunner):
    """mpirun_rsh fan-out (reference MVAPICHRunner): hosts and K=V env pairs
    inline; ranks read MV2_COMM_WORLD_RANK/SIZE."""

    name = "mvapich"
    _probe_binary = "mpirun_rsh"

    def get_cmd(self, script, script_args):
        env = dict(self.exports)
        env["JAX_COORDINATOR_ADDRESS"] = f"{self.master_addr}:{self.master_port}"
        return (["mpirun_rsh", "-np", str(len(self.hosts))] + list(self.hosts)
                + [f"{k}={v}" for k, v in env.items()]
                + [sys.executable, script] + list(script_args))


RUNNERS = {r.name: r for r in (PDSHRunner, OpenMPIRunner, SlurmRunner,
                               MPICHRunner, IMPIRunner, MVAPICHRunner)}


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu multi-host launcher (reference bin/deepspeed)")
    parser.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE)
    parser.add_argument("-i", "--include", default="")
    parser.add_argument("-e", "--exclude", default="")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_addr", default=None)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--export", action="append", default=[],
                        help="extra env var names to forward")
    parser.add_argument("--launcher", default="ssh",
                        choices=["ssh"] + sorted(RUNNERS),
                        help="fan-out backend (reference multinode_runner.py)")
    parser.add_argument("--dry_run", action="store_true",
                        help="print commands without executing")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if os.path.exists(args.hostfile):
        resources = filter_resources(parse_hostfile(args.hostfile),
                                     args.include, args.exclude)
        hosts = list(resources)
    else:
        hosts = ["localhost"]
    if args.num_nodes > 0:
        hosts = hosts[:args.num_nodes]
    master = args.master_addr or hosts[0]

    if len(hosts) == 1 and _is_local_host(hosts[0]) and not args.dry_run:
        # single LOCAL host (localhost or this machine's own hostname — the
        # common one-line DLTS hostfile): exec in place with the FULL
        # environment. Scripts ported from the reference read
        # RANK/WORLD_SIZE/MASTER_* even single-node, and the reference
        # exports them unconditionally — stale values from a previous
        # multi-node shell must not leak through. A single REMOTE host
        # falls through to the ssh fan-out below — exec'ing it here would
        # run the script on the launch box instead.
        env = dict(os.environ)
        env["RANK"] = "0"
        env["LOCAL_RANK"] = "0"
        env["WORLD_SIZE"] = "1"
        env["MASTER_ADDR"] = master
        env["MASTER_PORT"] = str(args.master_port)
        for stale in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                      "JAX_NUM_PROCESSES", "NUM_PROCESSES",
                      "JAX_PROCESS_ID", "PROCESS_ID",
                      "OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK",
                      "DS_HOSTLIST"):
            # every name comm.mpi_discovery resolves coord/size/rank from
            # (comm.py:179-185 incl. the unprefixed and OMPI aliases);
            # leftovers from a previous multi-node shell would make
            # init_distributed wait forever for ranks we never launch
            env.pop(stale, None)
        os.execvpe(sys.executable, [sys.executable, args.script] + args.script_args,
                   env)

    if args.launcher != "ssh":
        runner = RUNNERS[args.launcher](hosts, master, args.master_port,
                                        _export_env(args.export))
        if not args.dry_run and not runner.backend_exists():
            raise RuntimeError(f"--launcher {args.launcher}: "
                               f"{runner._probe_binary} not found in PATH")
        cmds = [runner.get_cmd(args.script, args.script_args)]
    else:
        cmds = build_commands(hosts, master, args.master_port, args.script,
                              args.script_args, _export_env(args.export))
    if args.dry_run:
        for c in cmds:
            print(" ".join(shlex.quote(x) for x in c))
        return 0
    procs = [subprocess.Popen(c) for c in cmds]
    rc = 0
    for p in procs:
        r = p.wait()  # wait EVERY rank; `rc or p.wait()` would orphan the rest
        rc = rc or r
    return rc


if __name__ == "__main__":
    sys.exit(main())
