"""DeepSpeed-Chat-style RLHF loop with the hybrid engine.

The reference's headline RLHF recipe (README.md: "15x over SOTA RLHF
systems"; DeepSpeed-Chat step 3) interleaves GENERATION (experience
collection) with TRAINING inside one engine — the hybrid engine flips
between the paged-KV inference path and the fused training step over the
SAME live weights (``runtime/hybrid_engine.py``).

This example runs RAFT-style reward-ranked fine-tuning (the rejection-
sampling cousin of PPO) on a toy reward — it demonstrates exactly the
plumbing a full DeepSpeed-Chat port exercises:

1. actor engine with ``hybrid_engine.enabled``: ``engine.generate`` serves
   rollouts through the v2 paged KV cache over the LIVE training weights
   (refreshed automatically after every optimizer step);
2. experience collection: prompts → sampled rollouts → rewards;
3. the update through the standard ``forward/backward/step`` contract on
   the reward-selected rollouts.

Usage:  python examples/rlhf_chat.py [--iters 8]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--rollouts", type=int, default=16)
    ap.add_argument("--gen-len", type=int, default=12)
    args = ap.parse_args()
    if args.rollouts < 2 or args.rollouts % 2:
        ap.error("--rollouts must be an even number >= 2 (top-half selection)")

    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models import LlamaConfig, init_llama

    cfg = LlamaConfig(vocab_size=256, hidden_size=128, intermediate_size=352,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=4, max_position_embeddings=64,
                      dtype=jnp.float32)
    model, params = init_llama(cfg, seed=0)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": args.rollouts // 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 5e-4}},
                "hybrid_engine": {"enabled": True, "fp16": False,
                                  "kv_block_size": 16, "num_kv_blocks": 256,
                                  "max_out_tokens": 64},
                "steps_per_print": 1000},
        llama_config=cfg)

    def reward_fn(tokens):
        """Toy reward model: token diversity of the generated suffix."""
        gen = tokens[-args.gen_len:]
        return len(set(gen)) / len(gen)

    rng = np.random.default_rng(0)
    prompts = rng.integers(2, cfg.vocab_size, (args.rollouts, 4)).astype(np.int32)

    for it in range(args.iters):
        # 1) experience: sampled rollouts from the LIVE weights (the hybrid
        #    engine recasts its serving view lazily after each step())
        rollouts = engine.generate(prompts, max_new_tokens=args.gen_len,
                                   do_sample=True, temperature=1.0, seed=it)
        rewards = np.asarray([reward_fn(r) for r in rollouts], np.float32)

        # 2) select: keep the reward-top half (RAFT / best-of-n)
        keep = np.argsort(rewards)[-(args.rollouts // 2):]
        batch = np.asarray([rollouts[i] for i in keep], np.int32)

        # 3) update through the standard engine contract (the model's CE
        #    shifts internally: pass UNSHIFTED ids as both input and labels)
        ids = jnp.asarray(batch)
        loss = engine.forward(ids, labels=ids)
        engine.backward(loss)
        engine.step()
        print(f"iter {it}: mean_reward={rewards.mean():.3f} "
              f"kept_reward={rewards[keep].mean():.3f} loss={float(loss):.4f}")

    print("done — every iteration generated from live weights (hybrid "
          "engine paged-KV serving) and trained through the fused step.")


if __name__ == "__main__":
    main()
