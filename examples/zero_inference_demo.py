"""ZeRO-Inference demo: llama decode with weights streaming from NVMe/DRAM.

Reference capability: ``blogs/deepspeed-gds/README.md:74`` — a model too big
for device memory decodes with its weights streaming NVMe→HBM per layer.
This drives `runtime/zero_infinity.ZeroInferenceEngine` with a real llama
stack (one `LlamaDecoderLayer` per streamed layer; embed/norm/head resident)
and journals decode tok/s + achieved weight-streaming GB/s.

Greedy decode recomputes the full prefix each token (no KV cache): every
decode step re-streams the whole model, which is exactly the
NVMe-bandwidth-bound regime ZeRO-Inference lives in — the measured GB/s is
the star, tok/s follows from it as (GB/s / model-GB) at batch 1.

Run (host CPU, reduced scale):
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
      python examples/zero_inference_demo.py --layers 8 --hidden 512 \
      --device nvme --tokens 8

On TPU, drop the env overrides and raise --hidden/--layers until the model
exceeds HBM — the point of the exercise.
"""

import argparse
import json
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--tokens", type=int, default=8)
    ap.add_argument("--prompt_len", type=int, default=16)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--device", choices=["cpu", "nvme"], default="nvme")
    ap.add_argument("--nvme_path", default="/tmp/ds_tpu_zero_inference")
    ap.add_argument("--prefetch", type=int, default=1)
    args = ap.parse_args()

    from deepspeed_tpu.models import LlamaConfig, init_llama
    from deepspeed_tpu.models.llama import LlamaDecoderLayer, precompute_rope
    from deepspeed_tpu.runtime.zero_infinity import ZeroInferenceEngine

    cfg = LlamaConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                      intermediate_size=int(args.hidden * 2.75),
                      num_hidden_layers=args.layers,
                      num_attention_heads=max(args.hidden // 64, 1),
                      num_key_value_heads=max(args.hidden // 64, 1),
                      max_position_embeddings=args.prompt_len + args.tokens + 1,
                      attn_impl="xla", dtype=jnp.bfloat16)
    model, params = init_llama(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    mp = params["model"]

    # resident small pieces: embed, final norm, head
    embed_w = jnp.asarray(mp["embed_tokens"]["embedding"], jnp.bfloat16)
    norm_w = jnp.asarray(mp["norm"]["weight"], jnp.float32)
    head_w = jnp.asarray(mp["lm_head"]["kernel"], jnp.bfloat16)
    cos, sin = precompute_rope(cfg.head_dim_, cfg.max_position_embeddings,
                               cfg.rope_theta)

    layer_params = [mp[f"layers_{i}"] for i in range(cfg.num_hidden_layers)]

    def make_layer(i):
        mod = LlamaDecoderLayer(cfg, i)

        def fn(p, pack):
            x, positions, mask = pack
            y = mod.apply({"params": p}, x, cos, sin, positions, mask)
            return (y, positions, mask)
        return fn

    eng = ZeroInferenceEngine([make_layer(i) for i in range(cfg.num_hidden_layers)],
                              layer_params, device=args.device,
                              nvme_path=args.nvme_path,
                              prefetch=args.prefetch)

    @jax.jit
    def lm_head(x):
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        normed = (xf * jax.lax.rsqrt(var + cfg.rms_norm_eps) * norm_w)
        return normed.astype(jnp.bfloat16) @ head_w

    rng = np.random.default_rng(0)
    # FIXED-shape decode buffers: ids padded to prompt+tokens with a key
    # padding mask, cur_len a traced scalar — every decode step reuses the
    # same compiled per-layer programs (a growing sequence would retrace
    # all layers per token and the timing would measure XLA, not streaming)
    L = args.prompt_len + args.tokens
    ids_buf = np.zeros((args.batch, L), np.int32)
    ids_buf[:, :args.prompt_len] = rng.integers(
        0, cfg.vocab_size, size=(args.batch, args.prompt_len))
    ids_buf = jnp.asarray(ids_buf)
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                                 (args.batch, L))

    def decode_step(ids, cur_len):
        mask = (jnp.arange(L, dtype=jnp.int32)[None] < cur_len)
        mask = jnp.broadcast_to(mask, ids.shape)
        x = jnp.take(embed_w, ids, axis=0)
        x, _, _ = eng.streamed_apply((x, positions, mask))
        last = x[jnp.arange(args.batch), cur_len - 1]  # [B, H]
        logits = lm_head(last)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # warmup (compiles the per-layer programs for the ONE fixed shape)
    _ = jax.block_until_ready(decode_step(ids_buf, jnp.int32(args.prompt_len)))
    eng.bytes_streamed = 0

    t0 = time.time()
    out = ids_buf
    for t in range(args.tokens):
        cur = jnp.int32(args.prompt_len + t)
        nxt = decode_step(out, cur)
        out = jax.lax.dynamic_update_slice(
            out, nxt[:, None], (0, args.prompt_len + t))
    jax.block_until_ready(out)
    dt = time.time() - t0

    report = {
        "metric": "zero_inference_decode",
        "platform": jax.devices()[0].platform,
        "device_store": args.device,
        "model_mparams": round(n_params / 1e6, 1),
        "streamed_gb_total": round(eng.bytes_streamed / 1e9, 3),
        "achieved_stream_gbps": round(eng.bytes_streamed / 1e9 / dt, 3),
        "decode_tokens_per_sec": round(args.tokens * args.batch / dt, 3),
        "peak_streamed_param_mb": round(eng.peak_param_bytes / 1e6, 2),
        # NVMe prefetch stages HOST read buffers; only the DRAM store holds
        # (1 + prefetch) layers device-resident (see _LayerStreaming)
        "resident_layers": 1 if args.device == "nvme" else 1 + args.prefetch,
        "new_tokens": args.tokens * args.batch,
    }
    print(json.dumps(report), flush=True)


if __name__ == "__main__":
    main()
