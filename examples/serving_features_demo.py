"""Serving-extension tour: one script through every beyond-reference
feature of the v2 ragged engine.

- int8 KV cache           (half KV HBM per token, in-kernel dequant)
- automatic prefix cache  (shared system prompts prefill once)
- speculative decoding    (prompt-lookup drafts, greedy-exact)
- parallel sampling       (N samples share the prompt KV)
- score()                 (teacher-forced per-token log-probs)

Run (host CPU):
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
      python examples/serving_features_demo.py
On TPU, drop the env overrides.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2.engine_v2 import build_llama_engine
    from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
    from deepspeed_tpu.models import LlamaConfig

    eng = build_llama_engine(
        LlamaConfig.tiny(num_key_value_heads=4), seed=0, dtype=jnp.float32,
        engine_config=RaggedInferenceEngineConfig(
            num_kv_blocks=256, enable_prefix_caching=True),
        kv_block_size=16, kv_cache_dtype="int8")
    kv = eng._state_manager.kv_cache
    print(f"int8 KV cache: {kv.cache[0].dtype} data + {kv.cache[1].dtype} "
          f"scales, {kv.per_token_bytes} B/token")

    rng = np.random.default_rng(0)
    system = (rng.integers(0, 64, size=8).tolist() * 12)[:80]

    # warm every program class the timed sections hit with a THROWAWAY
    # system prompt (same lengths, different content), so the printed
    # deltas measure the FEATURES, not one-time jit compiles
    other = (rng.integers(64, 128, size=8).tolist() * 12)[:80]
    eng.generate([other + [3, 7]], max_new_tokens=8)      # full prefill
    eng.generate([other + [9, 1]], max_new_tokens=8)      # adopted prefill
    eng.generate([other + [3, 7]], max_new_tokens=16,     # drafted decode
                 speculative="prompt_lookup", num_draft_tokens=6)

    t0 = time.time()
    eng.generate([system + [3, 7]], max_new_tokens=8)
    cold = time.time() - t0
    t0 = time.time()
    eng.generate([system + [9, 1]], max_new_tokens=8)
    warm = time.time() - t0
    pc = eng._state_manager.prefix_cache
    print(f"prefix cache: {len(pc)} cached blocks; request 2 reused the "
          f"system prompt ({cold:.2f}s -> {warm:.2f}s)")

    t0 = time.time()
    plain = eng.generate([system + [3, 7]], max_new_tokens=16)
    t_plain = time.time() - t0
    t0 = time.time()
    spec = eng.generate([system + [3, 7]], max_new_tokens=16,
                        speculative="prompt_lookup", num_draft_tokens=6)
    t_spec = time.time() - t0
    assert spec == plain, "speculative must be greedy-exact"
    print(f"speculative decode: greedy-exact, {t_plain:.2f}s plain vs "
          f"{t_spec:.2f}s drafted for 16 tokens")

    samples = eng.generate([system + [5]], max_new_tokens=6, temperature=0.9,
                           num_return_sequences=3, seed=7)
    print(f"parallel sampling: 3 samples sharing one prompt prefill -> "
          f"{samples}")

    lp = eng.score([999], [system[:33]])[0]
    print(f"score(): mean teacher-forced logprob over the prompt = "
          f"{float(np.mean(lp)):.3f}")
    print("SERVING FEATURE TOUR OK")


if __name__ == "__main__":
    main()
