"""Serve with the ragged (FastGen-class) v2 engine.

    python examples/serve_fastgen.py            # random tiny model
    python examples/serve_fastgen.py --hf_dir /path/to/llama  # converted HF
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf_dir", default=None,
                    help="HF checkpoint dir (*.safetensors) to convert+serve")
    ap.add_argument("--arch", default="llama")
    ap.add_argument("--max_new_tokens", type=int, default=16)
    args = ap.parse_args()

    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2 import build_llama_engine, RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.config_v2 import DSStateManagerConfig

    if args.hf_dir:
        from deepspeed_tpu.module_inject import convert_hf_safetensors
        cfg, params = convert_hf_safetensors(args.arch, args.hf_dir)
    else:
        from deepspeed_tpu.models import LlamaConfig
        import dataclasses
        cfg, params = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32), None

    eng = build_llama_engine(cfg, params=params,
                             engine_config=RaggedInferenceEngineConfig(
                                 state_manager=DSStateManagerConfig(max_context=512),
                                 num_kv_blocks=256))
    prompts = [[1, 15, 92, 7], [2, 44], [9, 9, 9, 9, 9]]
    outs = eng.generate(prompts, max_new_tokens=args.max_new_tokens)
    for p, o in zip(prompts, outs):
        print(f"prompt {p} -> {o}")

    # the same engine behind the asynchronous daemon (Dynamic SplitFuse
    # scheduling, token streaming) — what `bin/ds_serve` wraps in HTTP
    from deepspeed_tpu.inference.v2 import ServingScheduler
    sched = ServingScheduler(eng).start()
    handle = sched.submit(prompts[0], max_new_tokens=args.max_new_tokens)
    streamed = list(handle.stream(timeout=300))
    sched.stop(drain=True)
    assert streamed == outs[0], "daemon must match generate() greedily"
    print(f"daemon streamed {len(streamed)} tokens (== generate output)")


if __name__ == "__main__":
    main()
