"""Train a Llama-class model with deepspeed_tpu.

Usage (single host):
    python examples/train_llama.py --config examples/ds_config_zero3.json

The config is a standard DeepSpeed JSON; parallelism comes from the
"mesh" key (axes: data, fsdp, model, seq, expert, pipe).
"""
import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=os.path.join(os.path.dirname(__file__),
                                                     "ds_config_zero3.json"))
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--tp", type=int, default=1,
                    help="native tensor-parallel degree: creates the mesh "
                         "model axis and composes column/row weight sharding "
                         "with the ZeRO stage (config key: tensor_parallel)")
    args = ap.parse_args()

    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models import LlamaConfig, init_llama

    with open(args.config) as f:
        config = json.load(f)
    if args.tp > 1:
        config["tensor_parallel"] = {"tp_size": args.tp}

    cfg = LlamaConfig(vocab_size=4096, hidden_size=args.hidden,
                      intermediate_size=int(2.75 * args.hidden),
                      num_hidden_layers=args.layers, num_attention_heads=8,
                      num_key_value_heads=4, max_position_embeddings=args.seq)
    model, params = init_llama(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=config)

    rng = np.random.default_rng(0)
    bs = engine.train_micro_batch_size_per_gpu() * engine.dp_world_size
    for step in range(args.steps):
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(bs, args.seq)),
                          jnp.int32)
        loss = engine.forward(ids, labels=ids)
        engine.backward(loss)
        engine.step()
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss):.4f} lr {engine.get_lr()[0]:.2e}")
    engine.save_checkpoint("/tmp/ds_tpu_example_ckpt", tag="final")
    print("checkpoint saved to /tmp/ds_tpu_example_ckpt")


if __name__ == "__main__":
    main()
